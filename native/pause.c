/*
 * pause: the sandbox anchor process.
 *
 * Reference: build/pause/pause.c — the only compiled-C artifact in the
 * reference tree.  One pause process anchors each pod sandbox: it holds
 * the sandbox's namespaces open, reaps any zombies reparented to it, and
 * sleeps until terminated.  Behavior reproduced from scratch:
 *
 *   - SIGINT/SIGTERM exit cleanly (the runtime's StopPodSandbox);
 *   - SIGCHLD reaps exited children in a loop (waitpid WNOHANG);
 *   - otherwise pause()s forever.
 */
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

static void sigdown(int signo) {
    (void)signo;
    _exit(0);
}

static void sigreap(int signo) {
    (void)signo;
    while (waitpid(-1, NULL, WNOHANG) > 0) {
    }
}

int main(void) {
    struct sigaction down = {0}, reap = {0};
    down.sa_handler = sigdown;
    reap.sa_handler = sigreap;
    reap.sa_flags = SA_NOCLDSTOP;
    if (sigaction(SIGINT, &down, NULL) < 0 ||
        sigaction(SIGTERM, &down, NULL) < 0 ||
        sigaction(SIGCHLD, &reap, NULL) < 0) {
        return 1;
    }
    for (;;) {
        pause();
    }
    return 42; /* unreachable */
}
