"""Density benchmark: scheduler_perf analog on real TPU.

Reference harness: test/integration/scheduler_perf/scheduler_test.go — 100
nodes x 3k pods with an enforced minimum of 30 pods/s and a warning threshold
of 100 pods/s (scheduler_test.go:34-38).  The north star (BASELINE.json) is
>=10k pods/s on a 5k-node snapshot with full predicate parity, single v5e-1.

This benchmark builds a 5k-node cluster (20 deployments behind services, so
resource fit + spreading + zone blending + taints/selector paths are all
live), then schedules 10k pods through the scheduling engine in batches,
chaining device-resident cluster state between batches (requested / nonzero /
spread counts never leave HBM) while the host performs the cache-commit
bookkeeping for every placement.  Besides throughput it reports per-pod
queue-add -> bind-commit latency percentiles (p50/p90/p99) — the pair the
reference's density SLO names (test/e2e/scalability/density.go:56,988-990).

Structure (VERDICT r4 #1 — the bench must be structurally unable to produce
nothing): the parent process FIRST runs the CPU benchmark in a subprocess
and BANKS its JSON line, then — if the remaining watchdog budget allows —
makes exactly ONE TPU attempt in a second subprocess.  Whatever happens
(TPU success, TPU failure, driver SIGTERM mid-attempt) the parent emits
exactly one JSON line: the TPU number if it ran, else the already-banked
CPU number.  SIGTERM/SIGINT handlers emit the banked result before dying,
so even an external timeout yields a parsed artifact.  No retry ladder: the
budget belongs to the driver, not the bench.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

_CHILD_ENV = "KTPU_BENCH_CHILD"
_DEADLINE_ENV = "KTPU_BENCH_DEADLINE"  # wall-clock deadline for a child
_LOCK_PATH = "/tmp/ktpu_device.lock"

import threading as _threading

_EMITTED = False
_EMIT_LOCK = _threading.Lock()
# the dict the one emitted line carried (the --baseline gate compares it
# against the prior artifact after the run)
_EMIT_RESULT = None


def _emit(result: dict) -> bool:
    """Exactly-one-JSON-line contract: the first caller prints, every later
    caller (e.g. a signal handler racing a just-finished run) no-ops."""
    global _EMITTED, _EMIT_RESULT
    with _EMIT_LOCK:
        if _EMITTED:
            return False
        _EMITTED = True
        _EMIT_RESULT = result
        print(json.dumps(result))
        sys.stdout.flush()
        return True


def _error_line(stage: str, err) -> dict:
    msg = err if isinstance(err, str) else f"{type(err).__name__}: {err}"
    return {
        "metric": "pods_scheduled_per_sec_5k_nodes",
        "value": 0.0,
        "unit": "pods/s",
        "vs_baseline": 0.0,
        "vs_floor": 0.0,
        "vs_north_star": 0.0,
        "detail": {"error": msg[:2000], "stage": stage},
    }


def _acquire_device_lock(timeout_s: float):
    """Serialize device processes: concurrent axon clients wedge the tunnel.

    Polls with LOCK_NB up to timeout_s so a wedged lock holder cannot make
    this process hang forever without printing its JSON line; returns None on
    timeout (caller emits a diagnostic line)."""
    import fcntl

    f = open(_LOCK_PATH, "w")
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
            return f
        except OSError:
            if time.monotonic() >= deadline:
                f.close()
                return None
            time.sleep(2.0)


_N_DEPLOY = 20
_ZONE = "failure-domain.beta.kubernetes.io/zone"


# per-node pod-slot cap for the bench fleet (binds before the 32-cpu /
# 100m-request limit would); run_overload sizes its storm against it
_NODE_PODS_CAP = 110


def _bench_nodes(args):
    """The 5k-node fleet's node OBJECTS — constructed once and reused, so
    node-encode timings measure encoder ingestion, not object parsing."""
    from kubernetes_tpu.api.factory import make_node

    return [
        make_node(
            f"node-{i}",
            cpu="32",
            mem="256Gi",
            pods=_NODE_PODS_CAP,
            labels={_ZONE: f"zone-{i % 8}", "tier": "a" if i % 3 else "b"},
            taints=[{"key": "dedicated", "value": "x", "effect": "NoSchedule"}]
            if i % 50 == 0
            else [],
        )
        for i in range(args.nodes)
    ]


def _build_encoder(args, nodes=None):
    """The shared 5k-node cluster shape (raw-engine AND live-path stages:
    identical padded tensor shapes mean one compiled program serves both).
    Nodes ingest through the bulk columnar path (encoder.add_nodes)."""
    from kubernetes_tpu.codec import SnapshotEncoder

    enc = SnapshotEncoder()
    enc.add_nodes(nodes if nodes is not None else _bench_nodes(args))
    for d in range(_N_DEPLOY):
        enc.add_spread_selector("default", {"app": f"dep-{d}"})
    return enc


def _node_encode_stats(args, nodes):
    """Cold bulk ingest vs the per-node loop vs warm re-encode, on the
    same prebuilt objects.  min-of-3 per path: this machine class is
    noisy, and min is the standard noise-robust point estimate."""
    from kubernetes_tpu.codec import SnapshotEncoder

    perpod = []
    for _ in range(3):
        e = SnapshotEncoder()
        t0 = time.monotonic()
        for n in nodes:
            e.add_node(n)
        perpod.append(time.monotonic() - t0)
    bulk = []
    enc = None
    for _ in range(3):
        enc = SnapshotEncoder()
        t0 = time.monotonic()
        enc.add_nodes(nodes)
        bulk.append(time.monotonic() - t0)
    # warm re-encode: an informer re-list of content-identical nodes
    # (fresh equal objects, so the equality check is honest)
    relist = _bench_nodes(args)
    t0 = time.monotonic()
    enc.update_nodes(relist)
    warm = time.monotonic() - t0
    t_bulk, t_perpod = min(bulk), min(perpod)
    return {
        "node_encode_seconds": round(t_bulk, 4),
        "node_encode_perpod_seconds": round(t_perpod, 4),
        "node_encode_speedup": round(t_perpod / t_bulk, 2) if t_bulk else 0.0,
        "node_reencode_warm_seconds": round(warm, 4),
    }


def _pending_pod(args, i):
    """One pending pod in the selected workload shape — the
    scheduler_bench_test.go:39-131 matrix: plain (BenchmarkScheduling),
    node-affinity, pod-affinity, pod-anti-affinity variants."""
    from kubernetes_tpu.api.factory import make_pod

    d = i % _N_DEPLOY
    if args.workload == "node-affinity":
        # BenchmarkSchedulingNodeAffinity: required In-match on a label
        return make_pod(
            f"pod-{i}", cpu="100m", mem="256Mi",
            labels={"app": f"dep-{d}"},
            affinity={"nodeAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": [{"matchExpressions": [
                        # selective: only the ~2/3 tier-a nodes match
                        {"key": "tier", "operator": "In",
                         "values": ["a"]}
                    ]}]}}},
            owner=("ReplicaSet", f"rs-{d}"),
        )
    if args.workload == "pod-affinity":
        # BenchmarkSchedulingPodAffinity: zone-level required affinity
        # to the workload's own label (co-locate with mates)
        return make_pod(
            f"pod-{i}", cpu="100m", mem="256Mi",
            labels={"app": f"dep-{d}"},
            affinity={"podAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [{
                    "labelSelector": {
                        "matchLabels": {"app": f"dep-{d}"}},
                    "topologyKey":
                        "failure-domain.beta.kubernetes.io/zone",
                }]}},
            owner=("ReplicaSet", f"rs-{d}"),
        )
    if args.workload == "pod-anti-affinity":
        # BenchmarkSchedulingPodAntiAffinity: hostname-level required
        # anti-affinity (one per node per group)
        return make_pod(
            f"pod-{i}", cpu="100m", mem="256Mi",
            labels={"app": f"dep-{d}"},
            affinity={"podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [{
                    "labelSelector": {
                        "matchLabels": {"app": f"dep-{d}"}},
                    "topologyKey": "kubernetes.io/hostname",
                }]}},
            owner=("ReplicaSet", f"rs-{d}"),
        )
    return make_pod(
        f"pod-{i}",
        cpu="100m",
        mem="256Mi",
        labels={"app": f"dep-{d}"},
        node_selector={"tier": "a"} if d % 4 == 0 else None,
        owner=("ReplicaSet", f"rs-{d}"),
    )


def _pct_ms(samples) -> dict:
    """Latency percentiles in ms — THE formatter for every per-pod
    latency report in this file (run/run_overload/run_tiered), so the
    artifacts cannot drift estimator or rounding between scenarios."""
    if not samples:
        return {}
    p50, p90, p99 = np.percentile(np.asarray(samples), [50, 90, 99])
    return {
        "p50": round(float(p50) * 1000, 1),
        "p90": round(float(p90) * 1000, 1),
        "p99": round(float(p99) * 1000, 1),
        "max": round(float(max(samples)) * 1000, 1),
        "n": len(samples),
    }


def run(args) -> dict:
    import jax

    from kubernetes_tpu.api.factory import make_pod
    from kubernetes_tpu.models.batched import (
        batch_has_pod_affinity,
        encode_batch_affinity,
        encode_batch_ports,
        make_sequential_scheduler,
    )
    from kubernetes_tpu.models.speculative import make_speculative_scheduler

    nodes = _bench_nodes(args)  # object construction excluded from encode
    enc_stats = _node_encode_stats(args, nodes)
    t0 = time.monotonic()
    enc = _build_encoder(args, nodes)
    t_build = time.monotonic() - t0  # bulk ingest + spread registration
    n_deploy = _N_DEPLOY
    # the scheduler_bench_test.go matrix's second dimension: N pods
    # ALREADY running before the measured scheduling starts (existing-pod
    # state exercises spread counts, resource accumulation, and — for the
    # affinity workloads — the committed-pod pair tensors); timed apart
    # so node_encode_seconds keeps measuring node encoding alone
    t0 = time.monotonic()
    for i in range(args.existing):
        enc.add_pod(
            make_pod(
                f"existing-{i}", cpu="100m", mem="256Mi",
                labels={"app": f"dep-{i % n_deploy}"},
                node_name=f"node-{i % args.nodes}",
                owner=("ReplicaSet", f"rs-{i % n_deploy}"),
            )
        )
    t_existing = time.monotonic() - t0

    def pending_pod(i):
        return _pending_pod(args, i)

    # both engines carry in-batch affinity state (the speculative engine
    # batch-updates the scan's per-topology-pair extras between repair
    # rounds), so every workload honors --engine
    engine = args.engine
    make_engine = (
        make_speculative_scheduler
        if engine == "speculative"
        else make_sequential_scheduler
    )
    # chained-state donation (accelerator only): the raw loop consumes
    # each returned new_cluster and never reuses the input, so the engine
    # updates requested/nonzero IN PLACE and the per-batch buffers'
    # HBM recycles into the launch instead of double-buffering
    donate = jax.default_backend() != "cpu"
    fn = make_engine(
        unsched_taint_key=enc.interner.intern("node.kubernetes.io/unschedulable"),
        zone_key_id=enc.getzone_key,
        donate_cluster=donate,
    )

    # warmup/compile on one batch shape; device-put the snapshot ONCE —
    # the static leaves stay resident and chain through every batch (the
    # tunnel otherwise re-uploads ~70MB of label/taint/topology tensors
    # per call)
    def build_aff_state(pods):
        """In-batch affinity carry, identical for warmup and timed batches
        (aff_state toggles the jit variant: warm and timed MUST agree, and
        a tail batch must not retrace — build it whenever the workload
        carries pod affinity, whatever the batch size)."""
        if batch_has_pod_affinity(pods):
            return encode_batch_affinity(enc, pods)
        return None

    pods = [pending_pod(i) for i in range(args.batch)]
    warm_aff = build_aff_state(pods)
    batch = enc.encode_pods(pods)
    ports = encode_batch_ports(enc, pods)
    cluster = jax.device_put(enc.snapshot())
    warm = cluster
    for i in range(args.warmup):
        # chain the device state exactly like the timed loop (incl. the
        # in-batch affinity variant), and FETCH the result: on the
        # tunnel-attached TPU the first device->host copy after compile
        # pays a multi-second one-time setup cost (block_until_ready alone
        # does not surface it)
        hosts, warm = fn(warm, batch, ports, np.int32(i * args.batch),
                         aff_state=warm_aff)
        np.asarray(hosts)

    # timed run: chain device state, host does cache-commit bookkeeping.
    # Dispatch is async — batch k+1's encode+launch overlaps the fetch of
    # batch k's hosts, so the tunnel RTT and the host commit loop hide
    # behind device compute (spread counts for batch k+1 then lag one
    # batch, the same staleness the speculative engine already accepts
    # within a batch).
    import copy
    import dataclasses

    row_names = {row: name for name, row in enc.node_rows.items()}
    scheduled = 0
    unschedulable = 0
    # under donation the warmup loop CONSUMED the original upload (its
    # buffers were donated into the first warm launch): re-upload a
    # pristine snapshot for the timed run, outside the timed window
    state = jax.device_put(enc.snapshot()) if donate else cluster
    last = 0
    in_flight = None  # (pods, hosts_device, t_formed)
    # per-pod latency samples for BOUND pods only: queue-add -> bind-commit,
    # where the whole burst queue-adds at t0 (the reference density harness
    # measures create -> scheduled the same way); pipeline = batch-formation
    # -> bind-commit (the batching knob's direct cost)
    lat_e2e: list = []
    lat_pipe: list = []

    def commit(pods, hosts_dev, t_formed):
        nonlocal scheduled, unschedulable
        tf = time.monotonic()
        hosts = np.asarray(hosts_dev)  # blocks on device compute + D2H copy
        tb = time.monotonic()
        phases["fetch"] += tb - tf
        committed = []
        for j, pod in enumerate(pods):
            r = int(hosts[j])
            if r < 0:
                unschedulable += 1
                continue
            # shallow-copy + set beats two dataclasses.replace calls ~2x
            # at 10k commits/s (Pod/PodSpec are plain mutable dataclasses)
            spec = copy.copy(pod.spec)
            spec.node_name = row_names[r]
            c = copy.copy(pod)
            c.spec = spec
            committed.append(c)
        # ONE vectorized encoder delta for the whole batch (the per-pod
        # add_pod loop was the dominant host cost at 10k commits/s)
        enc.add_pods(committed)
        bound = len(committed)
        scheduled += bound
        t_done = time.monotonic()
        lat_e2e.extend([t_done - t0] * bound)
        lat_pipe.extend([t_done - t_formed] * bound)
        phases["commit"] += t_done - tb

    # workload generation (the reference's RC create strategy, runners.go)
    # happens outside the measured window — the timed section is the
    # scheduler: encode -> device -> commit
    prebuilt = {}
    for start in range(0, args.pods, args.batch):
        n = min(args.batch, args.pods - start)
        pods = [pending_pod(start + j) for j in range(n)]
        if n < args.batch:  # pad the tail batch: same shape, no recompile
            pods += [pending_pod(start) for _ in range(args.batch - n)]
        prebuilt[start] = (n, pods)

    # "dispatch" is the async enqueue only; device compute + the D2H copy
    # surface in "fetch" (the np.asarray sync point); "commit" is pure host
    # bookkeeping
    # affinity workloads evaluate REQUIRED predicates against the encoder's
    # committed-pod pair tensors: batch k MUST be committed before batch
    # k+1 encodes, or placements go blind to the previous batch and violate
    # (anti-)affinity.  Plain workloads keep the overlap (only spread
    # SCORES go one batch stale there, which the engine already accepts).
    overlap_commit = args.workload in ("plain", "node-affinity")
    phases = {"encode": 0.0, "dispatch": 0.0, "fetch": 0.0, "commit": 0.0}
    # t0 AFTER workload generation: the prebuilt loop builds 10k pod
    # objects (~1s host work) that the reference's create strategy also
    # excludes — the timed window is encode -> device -> commit only
    t0 = time.monotonic()
    for start in range(0, args.pods, args.batch):
        n, pods = prebuilt[start]
        if not overlap_commit and in_flight is not None:
            commit(*in_flight)
            in_flight = None
        t_formed = time.monotonic()
        # in-batch affinity carry (models/batched.py BatchAffinityState) so
        # co-batched mates see each other — built BEFORE encode_pods, as
        # the scheduler runtime does (novel topology keys must register
        # before the TP-wide tensors are cut)
        aff_state = build_aff_state(pods)
        batch = enc.encode_pods(pods)
        if n < args.batch:
            valid = np.array(batch.valid, bool)  # padded width, not args.batch
            valid[n:] = False
            batch = dataclasses.replace(batch, valid=valid)
        ports = encode_batch_ports(enc, pods)
        phases["encode"] += time.monotonic() - t_formed
        tp = time.monotonic()
        hosts, state = fn(state, batch, ports, np.int32(last),
                          aff_state=aff_state)
        if hasattr(hosts, "copy_to_host_async"):
            hosts.copy_to_host_async()
        phases["dispatch"] += time.monotonic() - tp
        last += n
        if in_flight is not None:
            commit(*in_flight)
        in_flight = (pods[:n], hosts, t_formed)
    if in_flight is not None:
        commit(*in_flight)
    jax.block_until_ready(state.requested)
    dt = time.monotonic() - t0

    pods_per_s = scheduled / dt if dt > 0 else 0.0

    lat = _pct_ms(lat_e2e)
    # cold start = everything between an empty encoder and ready-to-
    # schedule state: bulk node ingest + spread registration + existing
    # pods (the failover re-sync figure the ISSUE 2 tentpole targets)
    cold_start = t_build + t_existing
    detail = {
        "nodes": args.nodes,
        "pods_scheduled": scheduled,
        "unschedulable": unschedulable,
        "batch": args.batch,
        "existing": args.existing,
        "existing_encode_seconds": round(t_existing, 3),
        "engine": engine,
        "workload": args.workload,
        "seconds": round(dt, 3),
        **enc_stats,
        "cold_start_seconds": round(cold_start, 3),
        "phases": {k: round(v, 3) for k, v in phases.items()},
        # queue-add -> bind-commit (burst arrival at t0, the density SLO
        # pair: throughput + p99, density.go:988-990)
        "latency_ms": lat,
        # batch-formation -> bind-commit: what one batch of this size costs
        # a pod in added latency (the batching knob's direct trade)
        "pipeline_latency_ms": _pct_ms(lat_pipe),
        "device": str(jax.devices()[0]),
    }
    # ---- live-path stage: the number that actually matters (VERDICT r05
    # weak #1) — queue -> schedule_cycle -> reserve/assume/bind through the
    # real Scheduler runtime, batched+pipelined commit.  On the CPU path a
    # second run with the per-pod commit loop pins the batched commit's
    # win as commit-phase seconds in the same artifact.
    try:
        if jax.default_backend() == "cpu":
            # comparison run FIRST so any one-time cost (jit variants,
            # allocator warm-up) lands on it, not on the headline figure
            detail["live_path_perpod"] = run_live(
                args, batched=False, pipeline=False
            )
        detail["live_path"] = run_live(args, batched=True, pipeline=True)
    except Exception as e:  # noqa: BLE001 — the raw number still emits
        detail["live_path_error"] = f"{type(e).__name__}: {e}"
    # ---- cluster_health stage (ISSUE 8), surfaced as its own detail
    # stage: the fleet analytics + telemetry-overhead figures the live
    # run just collected (CI asserts presence + sanity and uploads the
    # /debug/cluster artifact next to the trace + ledger)
    if "live_path" in detail and "cluster_health" in detail["live_path"]:
        detail["cluster_health"] = detail["live_path"]["cluster_health"]
    # ---- quality stage (ISSUE 13), surfaced as its own detail stage:
    # placement margins / feasible counts / FFD regret / drift state
    # from the live run's quality observatory (CI asserts presence and
    # uploads the /debug/quality artifact next to its siblings)
    if "live_path" in detail and "quality" in detail["live_path"]:
        detail["quality"] = detail["live_path"]["quality"]
    # ---- latency-tier stage (ISSUE 6): per-tier p50/p99 in the default
    # artifact — express p99 under a saturating bulk load + the bulk
    # throughput it costs, ratioed against the live-path single-lane
    # number just measured.  CPU child only, like --tiered itself in
    # orchestrate(): it is a control-plane benchmark, and spending the
    # single budgeted TPU attempt's window on a second full drain (+ an
    # express-width tunnel compile) risks losing the headline device
    # number; orchestrate() copies the banked CPU child's tier figures
    # into a successful TPU artifact's cpu_reference
    if jax.default_backend() == "cpu":
        try:
            detail["latency_tiers"] = run_tiered(
                args,
                single_lane_ref=detail.get("live_path", {}).get("pods_per_s"),
            )
        except Exception as e:  # noqa: BLE001
            detail["latency_tiers_error"] = f"{type(e).__name__}: {e}"
        # ---- megacycle stage (ISSUE 12): a scaled-down K-sweep (K <= 4,
        # shape capped like the sharded stage) — per-K pods/s + host
        # seconds per pod + the K-vs-1 placement-identity pin.  CPU
        # child only like the tier stage (a control-plane figure;
        # --megacycle is the standalone full-scale sweep)
        try:
            mega_args = argparse.Namespace(**vars(args))
            mega_args.nodes = min(args.nodes, 1000)
            mega_args.pods = min(args.pods, 4096)
            mega_args.batch = min(args.batch, 256)
            detail["megacycle"] = run_megacycle(
                mega_args,
                ks=[k for k in (1, 2, 4) if k <= args.megacycle_max],
            )
        except Exception as e:  # noqa: BLE001
            detail["megacycle_error"] = f"{type(e).__name__}: {e}"
        # ---- replica stage (ISSUE 14): a scaled-down N sweep (N = 1, 2)
        # of the queue-sharded replica set + the multi-tenant storm —
        # scaling factor, conflict rate, zero-lost-pods.  CPU child only
        # like the tier stage (a control-plane figure; --replicas is the
        # standalone full-scale sweep)
        try:
            rep_args = argparse.Namespace(**vars(args))
            rep_args.nodes = min(args.nodes, 500)
            rep_args.pods = min(args.pods, 2048)
            rep_args.batch = min(args.batch, 128)
            rep_args.replicas = 2
            detail["replicas"] = run_replicas(rep_args, ns=[1, 2])
        except Exception as e:  # noqa: BLE001
            detail["replicas_error"] = f"{type(e).__name__}: {e}"
        # ---- autoscale stage (ISSUE 15): the capacity-planning what-if
        # at CI scale — compressed-vs-per-pod solve speedup with the
        # bins-needed identity asserted, the compressed sweep rate, and
        # the sharded shape-axis leg — via a subprocess (the sharded
        # leg's virtual device count must be set before backend init).
        # CPU child only like its siblings; --autoscale is the
        # standalone full-scale sweep
        try:
            detail["autoscale"] = _autoscale_stage(args)
        except Exception as e:  # noqa: BLE001
            detail["autoscale_error"] = f"{type(e).__name__}: {e}"
        # ---- sharded stage (ISSUE 9): the multi-chip live path at the
        # run's scale — per-cycle placement identity vs single-chip plus
        # the sharded encode-fits figures, via a subprocess (the virtual
        # device count must be set before backend init).  CPU child only,
        # like the tier stage: it is a control-plane identity pin, and
        # the single budgeted TPU attempt must not spend its window on a
        # second full drain
        try:
            detail["sharded"] = _sharded_stage(args)
        except Exception as e:  # noqa: BLE001
            detail["sharded_error"] = f"{type(e).__name__}: {e}"
        # ---- scenario stage (ISSUE 18): a scaled-down rolling-drain
        # campaign through the trace engine — mass displacement through
        # the shed-exempt requeue path with the invariant checker as the
        # oracle, banking the recovery tail (reschedule p99) and the
        # goodput-during-event ratio the gate rows track.  CPU child
        # only like its siblings (a control-plane robustness figure;
        # --scenario is the standalone full-scale campaign)
        try:
            from kubernetes_tpu.runtime.scenario import run_scenario

            scen = run_scenario(
                "drain", seed=args.scenario_seed, pods=120, nodes=10,
                rate=120.0, drain_timeout_s=60.0,
                # --timeline-out: the stage banks the longitudinal
                # artifact (fast sampling + chaos-window annotations);
                # the stage's store is the LAST process default, so
                # _write_timeline_artifact renders ITS html sibling
                timeline_path=getattr(args, "timeline_out", None),
            ).to_dict()
            scen["clean"] = (
                scen["lost"] == 0 and scen["violations"] == 0
            )
            detail["scenario"] = scen
        except Exception as e:  # noqa: BLE001
            detail["scenario_error"] = f"{type(e).__name__}: {e}"
    out = {
        "metric": "pods_scheduled_per_sec_5k_nodes",
        "value": round(pods_per_s, 1),
        "unit": "pods/s",
        # vs_baseline keeps the historical meaning (ratio to the reference's
        # 30 pods/s enforced floor, scheduler_test.go:34-38); the two explicit
        # fields keep it honest: floor != target.
        "vs_baseline": round(pods_per_s / 30.0, 2),
        "vs_floor": round(pods_per_s / 30.0, 2),
        "vs_north_star": round(pods_per_s / 10000.0, 3),
        "p99_schedule_latency_ms": lat.get("p99", 0.0),
        # top level, NOT detail: encode/cold-start regressions must move a
        # tracked trajectory figure, and the speedup pins the bulk-ingest
        # acceptance (>=3x vs the per-node loop on this very run)
        "cold_start_seconds": round(cold_start, 3),
        "node_encode_speedup": enc_stats["node_encode_speedup"],
        "detail": detail,
    }
    if "live_path" in detail:
        # surface the live-control-plane figure next to the raw-engine one
        # so the perf trajectory tracks the number that actually matters
        out["live_path_pods_per_s"] = detail["live_path"]["pods_per_s"]
        out["live_path_overlap_efficiency"] = detail["live_path"].get(
            "overlap_efficiency", 0.0
        )
    if "latency_tiers" in detail:
        # the tier acceptance pair, tracked at top level: express tail
        # latency under saturating bulk load + what it cost the bulk lane
        out["express_p99_ms"] = detail["latency_tiers"]["express_p99_ms"]
        out["tiered_bulk_tput_ratio"] = detail["latency_tiers"][
            "bulk_tput_ratio"
        ]
    if "megacycle" in detail:
        # the megacycle acceptance pair, tracked at top level: best
        # sweep throughput + host seconds per pod at the deepest K
        # (the figure the device-resident loop exists to shrink), plus
        # the K-vs-1 identity flag
        out["megacycle_pods_per_s"] = detail["megacycle"]["best_pods_per_s"]
        out["megacycle_host_s_per_pod"] = detail["megacycle"][
            "host_s_per_pod_at_max_k"
        ]
        out["megacycle_identity"] = detail["megacycle"]["identical"]
    if "quality" in detail:
        # the placement-quality acceptance trio, tracked at top level:
        # decision confidence (tolerance-banded — a margin COLLAPSE and
        # a margin explosion both mean the scoring changed), packing
        # density vs the FFD counterfactual, and what the observatory
        # cost the hot path (lower is better)
        out["placement_margin_p50"] = detail["quality"]["margin_p50"]
        out["regret_ratio"] = detail["quality"]["regret_ratio"]
        out["quality_overhead_ratio"] = detail["quality"]["overhead_ratio"]
    if "replicas" in detail:
        # the horizontal scale-out acceptance trio, tracked at top
        # level: throughput scaling vs one replica, the optimistic
        # conflict rate at max N (requeues per placement), and the
        # conservation flag (no popped pod lost across the sweep +
        # storm)
        out["replica_scaling_x"] = detail["replicas"]["scaling_x"]
        out["replica_conflict_rate"] = detail["replicas"][
            "conflict_rate_at_max_n"
        ]
        storm = detail["replicas"].get("storm") or {}
        out["replica_storm_clean"] = bool(
            detail["replicas"]["zero_lost_pods"]
            and storm.get("no_tenant_starved")
            and storm.get("lost") == 0
            and storm.get("invariant_violations") == 0
        )
    if "autoscale" in detail:
        # the capacity-planning acceptance trio, tracked at top level:
        # the class-compressed solve's speedup over the per-pod
        # reference (bins-needed identity asserted in-leg), the sweep
        # rate over the candidate catalog, and the identity flags
        out["autoscale_speedup_x"] = detail["autoscale"]["speedup_x"]
        if "shapes_per_s" in detail["autoscale"]:
            # absent when the sweep bowed out under the deadline — the
            # gate skips absent paths instead of reading 0.0 as a
            # collapse
            out["autoscale_shapes_per_s"] = detail["autoscale"][
                "shapes_per_s"
            ]
        out["autoscale_identity"] = bool(
            detail["autoscale"]["identical"]
            and detail["autoscale"].get("sharded", {}).get(
                "identical", True
            )
        )
    if "scenario" in detail:
        # the lifecycle-robustness acceptance trio, tracked at top
        # level: displaced pods reschedule within the banked tail,
        # goodput holds through the event, and the run was CLEAN (zero
        # lost pods, zero invariant violations — the hard oracle)
        out["scenario_reschedule_p99_ms"] = detail["scenario"][
            "reschedule_ms"]["p99"]
        out["scenario_goodput_ratio"] = detail["scenario"]["goodput_ratio"]
        out["scenario_clean"] = detail["scenario"]["clean"]
    if "sharded" in detail:
        # the multi-chip acceptance, tracked at top level: sharded
        # placements bit-identical to single-chip on this very run
        out["sharded_identity"] = detail["sharded"].get("identical", False)
        shrink = detail["sharded"].get("shrink_identity")
        if shrink is not None:
            # the elastic-ladder acceptance (ISSUE 10): a mid-stream
            # shard loss shrank the mesh, stayed bit-identical, and kept
            # the invariant checker clean
            out["shrink_identity"] = bool(
                shrink.get("identical")
                and shrink.get("invariant_violations") == 0
            )
    return out


def run_live(args, batched: bool = True, pipeline: bool = True) -> dict:
    """Live control-plane throughput: queue -> pop_batch -> schedule_cycle
    -> reserve/assume/bind through the real Scheduler runtime (the path
    the density SLO measures), on the same cluster/workload shape as the
    raw-engine stage so the two figures are directly comparable.

    batched/pipeline select the commit implementation (SchedulerConfig
    .batched_commit / .pipeline_commit); per-phase host seconds come from
    the Scheduler's own phase_seconds counters, so `commit_seconds` is the
    apples-to-apples cost of the commit stage under each mode."""
    from kubernetes_tpu.runtime.cache import SchedulerCache
    from kubernetes_tpu.runtime.queue import PriorityQueue
    from kubernetes_tpu.runtime.scheduler import Scheduler, SchedulerConfig

    t_setup = time.monotonic()
    enc = _build_encoder(args)
    cache = SchedulerCache(enc)
    queue = PriorityQueue()
    # decision ledger (--ledger-out): record the HEADLINE live run (the
    # batched+pipelined stage) for the record->replay bit-identity gate;
    # the per-pod comparison run stays unrecorded
    ledger = None
    if getattr(args, "ledger_out", None) and batched and pipeline:
        from kubernetes_tpu.runtime.ledger import DecisionLedger

        ledger = DecisionLedger(path=args.ledger_out)
    sched = Scheduler(
        cache=cache,
        queue=queue,
        binder=lambda pod, node: True,
        config=SchedulerConfig(
            batch_size=args.batch,
            batch_window_s=0.0,
            engine=args.engine,
            disable_preemption=True,
            batched_commit=batched,
            pipeline_commit=pipeline,
            # regret counterfactual every other cycle: smoke runs have
            # only a handful of cycles and the quality stage must bank
            # at least one materialized FFD sample
            quality_interval_cycles=2,
        ),
        ledger=ledger,
    )
    def _drain(budget_s: float) -> int:
        """run_once until nothing schedulable remains: active/backoff work,
        an in-flight pipelined batch, or the budget.  Pods parked
        unschedulable do NOT keep the loop alive (no cluster events fire
        here to revive them — without this check a single FitError pod
        would spin the loop to the deadline)."""
        placed = 0
        deadline = time.monotonic() + budget_s
        while time.monotonic() < deadline:
            got = sched.run_once(timeout=0.0)
            placed += got
            if got == 0 and not sched.pipeline_pending:
                if not queue.has_schedulable():
                    break
                time.sleep(0.002)  # backoff expiries: don't hot-spin
        return placed + sched.flush_pipeline()

    # warmup: one full-width batch through the whole path (compile + row
    # caches + first-fetch setup), outside the timed window
    for j in range(args.batch):
        queue.add(_pending_pod(args, args.pods + j))
    _drain(600)
    setup_s = time.monotonic() - t_setup

    for k in sched.phase_seconds:
        sched.phase_seconds[k] = 0.0
    # telemetry-cost watermark: the cumulative counter minus this value
    # is exactly what the hub cost the timed window below
    from kubernetes_tpu.utils import metrics as _m_t

    _tel0 = float(_m_t.TELEMETRY_SECONDS.value)
    # quality-cost watermark: same discipline as the telemetry one —
    # the cumulative hook counter minus this is what the observatory
    # cost the timed window (the <2% overhead_ratio figure)
    _q0 = float(_m_t.QUALITY_SECONDS.value)
    total = args.pods
    # pod-object construction stays outside the timed window (the raw
    # stage and the reference's create strategy both exclude it); the
    # queue adds are inside — they ARE the live path's entry point
    pending = [_pending_pod(args, i) for i in range(total)]
    t0 = time.monotonic()
    for p in pending:
        queue.add(p)
    t_enqueue = time.monotonic() - t0
    placed = _drain(900)
    dt = time.monotonic() - t0
    # overlap efficiency: the "fetch" phase is the async D2H window
    # measured on the fetch worker (codec/transfer.AsyncFetch), so under
    # the pipelined commit it overlaps the pop/commit host phases and
    # the PHASE SUM exceeds wall clock — efficiency > 1.0 is the async
    # result path working, == 1.0 is fully serial.  fetch_block is a
    # SUBSET of the fetch window (the part the host actually waited on),
    # so it is excluded from the sum to avoid double counting.
    # (host_stall and fetch_block are lockstep ALIASES of the same fence
    # wait — subtract both so the stall is excluded exactly once)
    phase_sum = (
        sum(sched.phase_seconds.values())
        - sched.phase_seconds["fetch_block"]
        - sched.phase_seconds.get("host_stall", 0.0)
        + t_enqueue
    )
    # ---- cluster_health stage (ISSUE 8): the fleet-state analytics the
    # live run's telemetry hub collected — utilization/fragmentation/
    # imbalance/occupancy from the device-resident snapshot reduction,
    # plus the hub's own hot-path cost ratioed against the run's wall
    # clock (the <2% acceptance pin, measured on the bench shape itself)
    cluster_health = None
    if sched.telemetry is not None:
        from kubernetes_tpu.utils import metrics as _m

        tel_s = float(_m.TELEMETRY_SECONDS.value) - _tel0
        summary = sched.telemetry.summary()
        cluster_health = {
            **(summary.get("analytics") or {}),
            "samples": summary["samples"],
            "pending": summary.get("pending"),
            "slo": summary["slo"],
            "hbm": summary["hbm"],
            "compile": summary["compile"],
            "telemetry_seconds": round(tel_s, 4),
            "telemetry_overhead_ratio": (
                round(tel_s / dt, 4) if dt > 0 else 0.0
            ),
        }
    # ---- placement-quality stage (ISSUE 13): margins, feasible
    # counts, the FFD-counterfactual regret, drift-detector state, and
    # the hook's own hot-path cost ratioed against the run's wall clock
    # (the <2% acceptance pin, measured on the bench shape itself).
    # finalize() materializes the last in-flight regret launch — the
    # amortization would otherwise strand it on a drained queue.
    quality_stage = None
    if sched.quality is not None:
        sched.quality.finalize()
        q_s = float(_m_t.QUALITY_SECONDS.value) - _q0
        qsum = sched.quality.summary()
        quality_stage = {
            "margin_p50": qsum["margin"]["p50"],
            "margin_mean": qsum["margin"]["mean"],
            "margins": qsum["margin"]["count"],
            "feasible_p50": qsum["feasible"]["p50"],
            "regret_ratio": (qsum["regret"] or {}).get("ratio", 0.0),
            "regret": qsum["regret"],
            "regret_samples": qsum["regret_samples"],
            "drift": qsum["drift"],
            "drift_alerts": qsum["drift_alerts_total"],
            "top_k": qsum["top_k"],
            "decisions": qsum["decisions"],
            "quality_seconds": round(q_s, 4),
            "overhead_ratio": round(q_s / dt, 4) if dt > 0 else 0.0,
        }
        if getattr(args, "quality_out", None) and batched and pipeline:
            with open(args.quality_out, "w") as f:
                json.dump(sched.quality.debug_payload(), f, indent=1)
            sys.stderr.write(
                f"bench: wrote /debug/quality payload to "
                f"{args.quality_out}\n"
            )
    # ---- performance observatory stage (ISSUE 11): the live run's
    # host/device time attribution + transfer accounting, straight from
    # the scheduler's observatory (the /debug/perf summary body).  CI
    # asserts the split reconciles and the wire seams moved bytes.
    # NB transfers are process-cumulative (the raw-engine stage ran in
    # this process too); the split totals are this Scheduler's own.
    perf_observatory = sched.perfobs.summary()
    ledger_stats = None
    if ledger is not None:
        ledger.flush(30.0)
        ledger_stats = {
            "path": args.ledger_out,
            "cycles": ledger.cycles_total,
            "bytes": ledger.bytes_total,
            "dropped": ledger.dropped_total,
        }
        sys.stderr.write(
            f"bench: recorded {ledger.cycles_total} cycles "
            f"({ledger.bytes_total} bytes, {ledger.dropped_total} "
            f"dropped) to {args.ledger_out}\n"
        )
    return {
        "pods_per_s": round(placed / dt, 1) if dt > 0 else 0.0,
        "seconds": round(dt, 3),
        "placed": placed,
        "unschedulable": total - placed,
        "batched_commit": batched,
        "pipeline_commit": pipeline,
        **({"cluster_health": cluster_health} if cluster_health else {}),
        **({"quality": quality_stage} if quality_stage else {}),
        "perf_observatory": perf_observatory,
        **({"ledger": ledger_stats} if ledger_stats else {}),
        "commit_seconds": round(sched.phase_seconds["commit"], 3),
        "phases": {"enqueue": round(t_enqueue, 3),
                   **{k: round(v, 3)
                      for k, v in sched.phase_seconds.items()}},
        "phase_sum_seconds": round(phase_sum, 3),
        "overlap_efficiency": round(phase_sum / dt, 3) if dt > 0 else 0.0,
        "setup_seconds": round(setup_s, 3),
    }


def run_overload(args) -> dict:
    """Overload scenario (ISSUE 4): bank the live path's SATURATED
    throughput, then offer --overload-factor x that rate, sustained,
    against a BOUNDED shedding queue with AIMD adaptive batching —
    report goodput under pressure, shed rate, storm-phase p99, and
    post-storm recovery (queue drained, batch width back to baseline)."""
    import threading

    from kubernetes_tpu.runtime.cache import SchedulerCache
    from kubernetes_tpu.runtime.queue import PriorityQueue
    from kubernetes_tpu.runtime.scheduler import Scheduler, SchedulerConfig

    enc = _build_encoder(args)
    cache = SchedulerCache(enc)
    capacity = max(args.batch * 4, 1024)
    baseline = max(args.batch // 16, 16)
    queue = PriorityQueue(capacity=capacity)
    # per-pod arrival stamps + bind latencies, storm phase only (the
    # global E2E histogram mixes in the saturation phase's deep-queue
    # waits, which are not the number under test here)
    arrival: dict = {}
    bind_log: list = []
    stats = {"bound": 0}

    def binder(pod, node) -> bool:
        stats["bound"] += 1
        t = arrival.pop(pod.name, None)
        if t is not None:
            now = time.monotonic()
            bind_log.append((now, now - t))
        return True

    sched = Scheduler(
        cache=cache, queue=queue, binder=binder,
        config=SchedulerConfig(
            batch_size=args.batch, batch_window_s=0.0, engine=args.engine,
            disable_preemption=True, batched_commit=True,
            pipeline_commit=True, adaptive_batch=True,
            batch_size_min=baseline, cycle_deadline_s=0.25,
        ),
    )

    def _drain(budget_s: float) -> int:
        placed = 0
        deadline = time.monotonic() + budget_s
        while time.monotonic() < deadline:
            got = sched.run_once(timeout=0.0)
            placed += got
            if got == 0 and not sched.pipeline_pending:
                if not queue.has_schedulable():
                    break
                time.sleep(0.002)
        return placed + sched.flush_pipeline()

    # warmup: AIMD sweeps the batch width, and each new pow2 pad is a
    # fresh XLA compile — pay ALL of them here, not inside the measured
    # saturation window (otherwise phase 1 under-reports and the storm
    # "beats" saturation).  The width list is THE shared AIMD pow2 ladder
    # (codec.schema.aimd_pow2_widths — the same list Scheduler.prewarm
    # compiles), so bench warmup and runtime pre-warming cannot drift.
    from kubernetes_tpu.codec.schema import aimd_pow2_widths

    seq = 2_000_000
    for w in aimd_pow2_widths(baseline, args.batch):
        sched._cur_batch = w
        for _ in range(w):
            queue.add(_pending_pod(args, seq))
            seq += 1
        _drain(600)
    sched._cur_batch = baseline
    n_sat = min(args.pods, capacity)  # a deeper pour would shed in phase 1
    sat_pods = [_pending_pod(args, 1_000_000 + i) for i in range(n_sat)]
    t0 = time.monotonic()
    for p in sat_pods:
        queue.add(p)
    sat_placed = _drain(600)
    sat_dt = time.monotonic() - t0
    tput_sat = sat_placed / sat_dt if sat_dt > 0 else 0.0

    # phase 2: the storm — offered load = factor x saturated throughput,
    # arrivals paced against the wall clock while the scheduler runs live.
    # The storm is capped at ~80% of REMAINING cluster capacity: past
    # that, goodput measures node exhaustion (every pod a FitError), not
    # control-plane overload — the scenario under test
    offered = max(tput_sat * args.overload_factor, 1.0)
    slots_left = max(args.nodes * _NODE_PODS_CAP - stats["bound"], 0)
    count = int(min(
        offered * args.overload_duration, 200_000, 0.8 * slots_left
    ))
    duration = count / offered
    storm_pods = [_pending_pod(args, i) for i in range(count)]
    for i, p in enumerate(storm_pods):
        # two priority bands: shedding must fall entirely on the low band
        p.spec.priority = 100 if i % 10 == 0 else 0
    shed0 = queue.shed_total
    stop = threading.Event()

    def _serve():
        while not stop.is_set():
            if sched.run_once(timeout=0.005) == 0 and not sched.pipeline_pending:
                if not queue.has_schedulable():
                    time.sleep(0.001)
        sched.flush_pipeline()

    server = threading.Thread(target=_serve, daemon=True)
    server.start()
    t_storm0 = time.monotonic()
    for i, p in enumerate(storm_pods):
        arrival[p.name] = time.monotonic()
        queue.add(p)
        # pace in ~32-pod chunks against the wall clock: per-pod sub-ms
        # sleeps degrade into a GIL-hogging spin that starves the serving
        # thread and measures the adder, not the scheduler
        if (i & 31) == 31:
            lag = t_storm0 + (i + 1) / offered - time.monotonic()
            if lag > 0:
                time.sleep(lag)
    t_storm1 = time.monotonic()
    # recovery: let the backlog drain, then stop the serving thread
    deadline = time.monotonic() + 120.0
    while queue.has_schedulable() and time.monotonic() < deadline:
        time.sleep(0.01)
    time.sleep(0.05)
    stop.set()
    server.join(timeout=10.0)
    shed = queue.shed_total - shed0
    in_storm = [lat for t, lat in bind_log if t <= t_storm1]
    goodput = len(in_storm) / (t_storm1 - t_storm0) if count else 0.0
    p99_ms = _pct_ms(in_storm).get("p99", 0.0)
    recovered = (not queue.has_schedulable()
                 and sched._cur_batch == baseline)
    goodput_ratio = goodput / tput_sat if tput_sat > 0 else 0.0
    return {
        "metric": "overload_goodput_pods_per_s",
        "value": round(goodput, 1),
        "unit": "pods/s",
        "detail": {
            "saturated_pods_per_s": round(tput_sat, 1),
            "offered_pods_per_s": round(offered, 1),
            "overload_factor": args.overload_factor,
            "storm_seconds": round(duration, 2),
            "storm_pods": count,
            "goodput_ratio": round(goodput_ratio, 3),
            "shed_total": shed,
            "shed_rate_per_s": round(shed / duration, 1) if duration else 0.0,
            "p99_storm_latency_ms": p99_ms,
            "queue_capacity": capacity,
            "batch_baseline": baseline,
            "recovered": recovered,
        },
    }


def run_scenario_metric(args) -> dict:
    """--scenario {drain,zone,diurnal,trace}: the trace-driven lifecycle
    campaign (ISSUE 18, runtime/scenario.py) against the LIVE scheduler —
    arrivals replayed under a virtual clock, chaos (rolling drain / zone
    outage / diurnal swing) composed at trace time, the invariant checker
    as the pass/fail oracle.  Banks the recovery figures the gate rows
    track: displaced-pod reschedule p99, goodput ratio during the event,
    time-to-drain — and `scenario_clean` (zero lost pods AND zero
    invariant violations), which CI asserts.  --ledger-out records every
    cycle so `bench.py --replay` re-verifies the window bit-identically
    offline; --scenario-trace replays an external Alibaba/Google-format
    trace file instead of the synthetic generator."""
    from kubernetes_tpu.runtime.scenario import run_scenario

    ledger = None
    if getattr(args, "ledger_out", None):
        from kubernetes_tpu.runtime.ledger import DecisionLedger

        ledger = DecisionLedger(path=args.ledger_out)
    res = run_scenario(
        args.scenario,
        seed=args.scenario_seed,
        pods=args.scenario_pods,
        nodes=args.scenario_nodes,
        rate=args.scenario_rate,
        compression=args.scenario_compression,
        trace_path=args.scenario_trace,
        ledger=ledger,
        # --timeline-out: the campaign samples fast relative to the
        # compressed replay and banks the JSONL inside run_scenario
        # (chaos-window annotations aligned with the excursions);
        # _write_timeline_artifact then renders the HTML sibling
        timeline_path=getattr(args, "timeline_out", None),
    )
    d = res.to_dict()
    clean = res.lost == 0 and res.violations == 0
    return {
        "metric": f"scenario_{args.scenario}_reschedule_p99_ms",
        "value": res.reschedule_ms.get("p99", 0.0),
        "unit": "ms",
        "scenario_clean": clean,
        "scenario_lost": res.lost,
        "scenario_violations": res.violations,
        "scenario_displaced": res.displaced,
        "scenario_reschedule_p99_ms": res.reschedule_ms.get("p99", 0.0),
        "scenario_goodput_ratio": res.goodput_ratio,
        "scenario_time_to_drain_s": res.time_to_drain_s,
        "detail": {"scenario": d},
    }


def run_autoscale_live(args) -> dict:
    """--autoscale-live (ISSUE 19): guarded autoscaler actuation in
    three legs.  A: the diurnal-breathe campaign through the LIVE
    scheduler — the capacity plan enacted as real node registration /
    cordon+drain+delete; asserts the fleet grows AND shrinks with zero
    lost pods, zero invariant violations, goodput >= 0.9, and that the
    JSONL actuation ledger replays bit-identically offline.  B: the
    plan-oscillation chaos — a flip-flopping plan source must be
    absorbed by the cooldown window (<= maxDirectionChanges direction
    changes per window; the flap counter takes the noise).  C: the
    stuck-drain chaos — a match-all zero-budget PDB wedges the
    scale-down drain; past the deadline the controller must roll back
    (un-cordon everything, fleet bit-identical to pre-actuation), and
    proceed once the veto lifts."""
    import tempfile

    from kubernetes_tpu.api.factory import make_node, make_pod
    from kubernetes_tpu.runtime.autoscaler import (
        AutoscalerConfig, AutoscalerController, replay_actuations,
    )
    from kubernetes_tpu.runtime.chaos import Disruptions
    from kubernetes_tpu.runtime.cluster import LocalCluster
    from kubernetes_tpu.runtime.scenario import run_scenario

    detail: dict = {}
    failures: list = []

    # ---- leg A: diurnal breathe through the live scheduler ----
    pods = args.autoscale_live_pods
    ledger_path = args.autoscale_ledger_out or os.path.join(
        tempfile.mkdtemp(prefix="ktpu-autoscale-"), "actuations.jsonl"
    )
    res = run_scenario(
        "autoscale", seed=args.scenario_seed, pods=pods, nodes=4,
        rate=pods / 20.0, drain_timeout_s=45.0,
        autoscale_ledger_path=ledger_path,
    )
    a = res.autoscaler or {}
    summ = a.get("summary") or {}
    counts = summ.get("counts") or {}
    rep = replay_actuations(ledger_path)
    leg_a = {
        "initial": a.get("initial"), "peak": a.get("peak"),
        "final": a.get("final"), "counts": counts,
        "lost": res.lost, "violations": res.violations,
        "goodput_ratio": res.goodput_ratio,
        "completed": res.completed,
        "ledger": ledger_path,
        "replay_records": rep["records"],
        "replay_verified": rep["verified"],
    }
    detail["breathe"] = leg_a
    if not (a.get("peak", 0) > a.get("initial", 0)):
        failures.append("breathe: fleet never grew")
    if not (counts.get("remove", 0) >= 1
            and a.get("final", 1 << 30) < a.get("peak", 0)):
        failures.append("breathe: fleet never shrank")
    if res.lost:
        failures.append(f"breathe: {res.lost} lost pods")
    if res.violations:
        failures.append(f"breathe: {res.violations} invariant violations")
    if res.goodput_ratio < 0.9:
        failures.append(f"breathe: goodput {res.goodput_ratio} < 0.9")
    if not rep["verified"]:
        failures.append(
            f"breathe: ledger replay mismatches {len(rep['mismatches'])}"
        )

    # ---- leg B: plan-oscillation chaos (flap guard) ----
    cluster = LocalCluster()
    for i in range(2):
        cluster.add_node(make_node(f"flapbase-{i}", cpu="8", mem="32Gi"))
    t_fake = [0.0]
    ctrl = AutoscalerController(
        cluster,
        config=AutoscalerConfig(
            up_stable_rounds=1, down_stable_rounds=1, cooldown_s=10.0,
            max_direction_changes=2, max_nodes_per_round=2, min_nodes=2,
            max_nodes=32, node_prefix="flap",
        ),
        clock=lambda: t_fake[0],
    )
    Disruptions(cluster).plan_oscillation(
        ctrl, shape=ctrl.catalog[0]["name"], count=2, drain=2
    )
    max_window = 0
    fleet_sizes = []
    for _ in range(120):
        t_fake[0] += 0.25
        ctrl.step()
        s2 = ctrl.summary()
        max_window = max(max_window, s2["direction_changes_in_window"])
        fleet_sizes.append(len(list(cluster.list("nodes"))))
    s2 = ctrl.summary()
    leg_b = {
        "rounds": 120,
        "max_direction_changes_in_window": max_window,
        "flaps": s2["counts"]["flaps"],
        "adds": s2["counts"]["add"], "removes": s2["counts"]["remove"],
        "fleet_min": min(fleet_sizes), "fleet_max": max(fleet_sizes),
    }
    detail["oscillation"] = leg_b
    if max_window > 2:
        failures.append(
            f"oscillation: {max_window} direction changes in one window"
        )
    if s2["counts"]["flaps"] == 0:
        failures.append("oscillation: flap guard never engaged")

    # ---- leg C: stuck-drain chaos (rollback) ----
    cluster = LocalCluster()
    for i in range(2):
        cluster.add_node(make_node(f"stuckbase-{i}", cpu="8", mem="32Gi"))
    ctrl = AutoscalerController(
        cluster,
        config=AutoscalerConfig(
            up_stable_rounds=1, down_stable_rounds=1, cooldown_s=0.0,
            max_nodes_per_round=2, min_nodes=2, max_nodes=8,
            drain_deadline_s=0.6, drain_retry_rounds=3,
            drain_retry_after_s=0.05, node_prefix="stuck",
        ),
    )
    seqs = {"n": 0}

    def source() -> dict:
        seqs["n"] += 1
        managed = ctrl.managed_nodes()
        if not managed:
            return {
                "cycle": seqs["n"], "backlog_pods": 4, "overflow_pods": 4,
                "scale_up": {"shape": ctrl.catalog[0]["name"], "count": 2},
                "drainable": {"count": 0, "nodes": []},
            }
        return {
            "cycle": seqs["n"], "backlog_pods": 0, "overflow_pods": 0,
            "scale_up": None,
            "drainable": {"count": len(managed), "nodes": managed},
        }

    ctrl.set_plan_source(source)
    ctrl.step()  # scale up: 2 managed nodes join
    managed = ctrl.managed_nodes()
    for i, n in enumerate(managed):
        p = make_pod(f"stuckpod-{i}", cpu="100m", mem="64Mi",
                     labels={"app": "stuck"})
        cluster.add_pod(p)
        cluster.bind(p, n)
    monkey = Disruptions(cluster)
    monkey.stuck_drain()
    pre = sorted(n.name for n in cluster.list("nodes"))
    rec = ctrl.step()  # scale-down wedges on the PDB -> rollback
    post = sorted(n.name for n in cluster.list("nodes"))
    cordoned = [
        n.name for n in cluster.list("nodes") if n.spec.unschedulable
    ]
    s3 = ctrl.summary()
    rolled = bool((rec.get("outcome") or {}).get("rollback"))
    monkey.clear_stuck_drain()
    ctrl.step()  # veto lifted: the same scale-down must now proceed
    leg_c = {
        "managed_before": len(managed),
        "rollback": rolled,
        "rollbacks_total": s3["counts"]["rollbacks"],
        "fleet_preserved": post == pre,
        "cordoned_after_rollback": cordoned,
        "managed_after_clear": len(ctrl.managed_nodes()),
    }
    detail["stuck_drain"] = leg_c
    if not rolled:
        failures.append("stuck-drain: no rollback recorded")
    if post != pre:
        failures.append("stuck-drain: fleet not restored")
    if cordoned:
        failures.append(f"stuck-drain: still cordoned {cordoned}")
    if ctrl.managed_nodes():
        failures.append("stuck-drain: scale-down did not proceed "
                        "after the veto lifted")

    clean = not failures
    return {
        "metric": "autoscale_live_clean",
        "value": 1.0 if clean else 0.0,
        "unit": "bool",
        "autoscale_live_clean": clean,
        "autoscale_live_failures": failures,
        "autoscale_live_peak": a.get("peak"),
        "autoscale_live_final": a.get("final"),
        "autoscale_live_replay_verified": rep["verified"],
        "detail": {"autoscale_live": detail},
    }


def run_tiered(args, single_lane_ref: "float | None" = None) -> dict:
    """Latency-tier scenario (ISSUE 6): a SATURATING bulk backlog drains
    through the tiered scheduler while express pods (priority above the
    threshold) arrive paced throughout the window.  Reports per-tier
    p50/p99 (arrival -> bind-commit), bulk throughput as a ratio of the
    single-lane saturated number, and a COMPILE-INCLUSIVE cold start
    (encoder build + Scheduler.prewarm) — the figure the persistent
    compile cache collapses on a second run (CI asserts the drop).

    `single_lane_ref`: saturated single-lane pods/s to ratio against;
    measured fresh via run_live when not supplied (run() passes its
    live_path figure so the default bench pays the stage once)."""
    import threading

    from kubernetes_tpu.runtime.cache import SchedulerCache
    from kubernetes_tpu.runtime.queue import PriorityQueue
    from kubernetes_tpu.runtime.scheduler import Scheduler, SchedulerConfig

    if single_lane_ref is None:
        single_lane_ref = run_live(args, batched=True, pipeline=True)[
            "pods_per_s"
        ]

    express_width = 64
    # cold start, compile-inclusive: everything between an empty encoder
    # and ready-to-serve-at-every-width (bulk ingest + spread registration
    # + the AIMD-ladder/express prewarm).  With a warm persistent compile
    # cache the prewarm half collapses to disk reads.
    t_cold0 = time.monotonic()
    enc = _build_encoder(args)
    cache = SchedulerCache(enc)
    queue = PriorityQueue()
    arrival: dict = {}
    bind_log: list = []  # (bind time, latency, tier)

    def binder(pod, node) -> bool:
        rec = arrival.pop(pod.name, None)
        if rec is not None:
            t, tier = rec
            now = time.monotonic()
            bind_log.append((now, now - t, tier))
        return True

    baseline = max(args.batch // 16, 16)
    deadline = args.tier_deadline
    sched = Scheduler(
        cache=cache, queue=queue, binder=binder,
        config=SchedulerConfig(
            batch_size=args.batch, batch_window_s=0.0, engine=args.engine,
            disable_preemption=True, batched_commit=True,
            pipeline_commit=True,
            # AIMD with a cycle deadline: an express pod's wait is bounded
            # by the bulk cycle IN FLIGHT when it arrives, so the deadline
            # is the p99 lever (width shrinks until bulk cycles fit; the
            # bulk_tput_ratio reports what that trade costs).  Set it
            # BELOW the express SLO but ABOVE the platform's fixed
            # per-cycle host cost, or AIMD pins to the floor width and
            # throughput collapses without helping latency.
            adaptive_batch=True, batch_size_min=baseline,
            cycle_deadline_s=deadline,
            express_lane=True, express_batch_size=express_width,
            express_priority_threshold=1000,
            # megacycle-under-tiers leg (ISSUE 12 acceptance): the
            # express preemption point sits BETWEEN megacycles, so the
            # express p99 under a K-deep bulk backlog is the honest
            # worst-case the megacycle adds; default 1 = the classic run
            megacycle_batches=getattr(args, "tiered_megacycle", 1),
        ),
    )
    t_warm0 = time.monotonic()
    # warm with WORKLOAD-shaped pods: executables are keyed on every
    # PodBatch leaf shape, so minimal dummy pods would pre-grow the wrong
    # pad dims and the real batches would still compile mid-storm.  On the
    # CPU backend warm the full AIMD ladder (compiles are ~1s); through a
    # tunnel-attached TPU each compile is MINUTES, so warm only the
    # express width — the bulk cap is already compiled by the live-path
    # stage (same engine knobs + cluster shape = same executable), and a
    # deadline-driven shrink to a new width shows up honestly as one
    # mid-run stall in the tail
    import jax as _jax

    widths = None if _jax.default_backend() == "cpu" else [express_width]
    prewarmed = sched.prewarm(
        widths=widths,
        pod_factory=lambda i: _pending_pod(args, 5_000_000 + i),
    )
    prewarm_s = time.monotonic() - t_warm0
    cold_start = time.monotonic() - t_cold0
    # start the AIMD width at the cap (every width is prewarmed): the
    # scenario measures the steady-state express/bulk trade, not the
    # additive ramp — the deadline still shrinks the width if bulk
    # cycles overrun the latency budget
    sched._cur_batch = args.batch

    n_bulk = args.pods
    bulk_pods = [_pending_pod(args, 1_000_000 + i) for i in range(n_bulk)]
    # express trickle: enough samples for a stable p99, small enough not
    # to BE the load (the tier is for the latency-sensitive few)
    n_exp = max(64, min(1024, n_bulk // 20))
    exp_pods = []
    for i in range(n_exp):
        p = _pending_pod(args, 2_000_000 + i)
        p.spec.priority = 2000  # above the threshold -> express
        exp_pods.append(p)

    stop = threading.Event()

    def _serve():
        while not stop.is_set():
            if (
                sched.run_once(timeout=0.005) == 0
                and not sched.pipeline_pending
                and not queue.has_schedulable()
            ):
                time.sleep(0.001)
        sched.flush_pipeline()

    server = threading.Thread(target=_serve, daemon=True)
    server.start()
    t0 = time.monotonic()
    for p in bulk_pods:
        arrival[p.name] = (time.monotonic(), "bulk")
        queue.add(p)
    # pace express arrivals across ~80% of the expected bulk drain so
    # (virtually) every sample measures the under-saturating-load case
    est_drain = max(n_bulk / max(single_lane_ref, 1.0), 0.5)
    rate = n_exp / (0.8 * est_drain)
    for i, p in enumerate(exp_pods):
        lag = t0 + (i + 1) / rate - time.monotonic()
        if lag > 0:
            time.sleep(lag)
        arrival[p.name] = (time.monotonic(), "express")
        queue.add(p)
    drain_by = time.monotonic() + 600.0
    while queue.has_schedulable() and time.monotonic() < drain_by:
        time.sleep(0.01)
    time.sleep(0.05)
    stop.set()
    server.join(timeout=10.0)

    exp_lat = [lat for _, lat, tier in bind_log if tier == "express"]
    bulk_binds = [(t, lat) for t, lat, tier in bind_log if tier == "bulk"]
    bulk_lat = [lat for _, lat in bulk_binds]
    # bulk throughput over its own drain window (first add -> last bulk
    # bind); the ratio against the single-lane number is the acceptance
    bulk_dt = (max(t for t, _ in bulk_binds) - t0) if bulk_binds else 0.0
    bulk_tput = len(bulk_binds) / bulk_dt if bulk_dt > 0 else 0.0
    ratio = bulk_tput / single_lane_ref if single_lane_ref > 0 else 0.0
    exp_pct = _pct_ms(exp_lat)
    return {
        "tiers": {
            "express": exp_pct,
            "bulk": _pct_ms(bulk_lat),
        },
        "express_p99_ms": exp_pct.get("p99", 0.0),
        "bulk_pods_per_s": round(bulk_tput, 1),
        "single_lane_pods_per_s": round(single_lane_ref, 1),
        "bulk_tput_ratio": round(ratio, 3),
        "cold_start_seconds": round(cold_start, 3),
        "prewarm_seconds": round(prewarm_s, 3),
        "prewarm_widths": {
            str(w): round(s, 3)
            for w, s in sorted(prewarmed.items(), key=lambda kv: str(kv[0]))
        },
        "express_width": express_width,
        "express_pods": len(exp_lat),
        "bulk_pods": len(bulk_binds),
        "cycle_deadline_s": deadline,
        "megacycles": sched.megacycles_total,
    }


def run_megacycle(args, ks=None) -> dict:
    """Megacycle K-sweep (ISSUE 12): the same live workload drained with
    megacycleBatches = 1, 2, 4, ... — per-K pods/s, HOST seconds per pod
    (the figure the megacycle exists to shrink: enqueue + fence stall +
    commit from the perf observatory's split), and a placement-identity
    pin of every K against K=1.  Each K gets a fresh cluster and the
    SAME warmup pod set, so the pre-timed state is identical across the
    sweep and the identity comparison is honest."""
    from kubernetes_tpu.runtime.cache import SchedulerCache
    from kubernetes_tpu.runtime.queue import PriorityQueue
    from kubernetes_tpu.runtime.scheduler import Scheduler, SchedulerConfig

    if ks is None:
        ks = []
        k = 1
        while k <= max(1, args.megacycle_max):
            ks.append(k)
            k *= 2
    kmax = max(ks)
    # warm BOTH dispatch shapes the timed window can hit: enough depth
    # to form (and compile) the K-deep megacycle ladder, plus a partial
    # trailing batch so the single-cycle path (the sweep's tail window)
    # is compiled too — a fresh compile inside a timed window would
    # read as a K-regression
    warm_n = args.batch * max(2, kmax) + max(1, args.batch // 2)
    curve = []
    placements = {}
    for K in ks:
        enc = _build_encoder(args)
        cache = SchedulerCache(enc)
        queue = PriorityQueue()
        sched = Scheduler(
            cache=cache, queue=queue, binder=lambda pod, node: True,
            config=SchedulerConfig(
                batch_size=args.batch, batch_window_s=0.0,
                engine=args.engine, disable_preemption=True,
                batched_commit=True, pipeline_commit=True,
                megacycle_batches=K,
            ),
        )

        def _drain(budget_s: float) -> int:
            placed = 0
            deadline = time.monotonic() + budget_s
            while time.monotonic() < deadline:
                got = sched.run_once(timeout=0.0)
                placed += got
                if got == 0 and not sched.pipeline_pending:
                    if not queue.has_schedulable():
                        break
                    time.sleep(0.002)
            return placed + sched.flush_pipeline()

        # warmup: enough depth to form (and compile) the full-K ladder
        # outside the timed window; same pod set for every K
        for j in range(warm_n):
            queue.add(_pending_pod(args, args.pods + j))
        _drain(600)
        host0 = sched.perfobs.summary()["host_s"]
        mega0 = sched.megacycles_total
        pending = [_pending_pod(args, i) for i in range(args.pods)]
        t0 = time.monotonic()
        for p in pending:
            queue.add(p)
        placed = _drain(900)
        dt = time.monotonic() - t0
        host_s = sched.perfobs.summary()["host_s"] - host0
        placements[K] = {
            (r.pod.namespace, r.pod.name): r.node for r in sched.results
        }
        curve.append({
            "k": K,
            "pods_per_s": round(placed / dt, 1) if dt > 0 else 0.0,
            "host_s_per_pod": (
                round(host_s / placed, 6) if placed else None
            ),
            "host_seconds": round(host_s, 3),
            "seconds": round(dt, 3),
            "placed": placed,
            "megacycles": sched.megacycles_total - mega0,
        })
        sys.stderr.write(
            f"bench: megacycle k={K}: {curve[-1]['pods_per_s']} pods/s, "
            f"{curve[-1]['host_s_per_pod']} host s/pod, "
            f"{curve[-1]['megacycles']} megacycles\n"
        )
    identical = all(placements[K] == placements[ks[0]] for K in ks)
    host_curve = [
        c["host_s_per_pod"] for c in curve
        if c["host_s_per_pod"] is not None
    ]
    decreasing = all(
        b < a for a, b in zip(host_curve, host_curve[1:])
    )
    best = max(curve, key=lambda c: c["pods_per_s"])
    # express-under-megacycle leg (the acceptance line: express p99
    # under a K-deep bulk backlog no worse than the tiered numbers):
    # one tiered run with megacycleBatches=kmax — the express lane's
    # preemption point sits between megacycles
    express = None
    if kmax > 1:
        try:
            t_args = argparse.Namespace(**vars(args))
            t_args.tiered_megacycle = kmax
            tiered = run_tiered(
                t_args, single_lane_ref=curve[0]["pods_per_s"]
            )
            express = {
                "express_p50_ms": tiered["tiers"]["express"].get("p50"),
                "express_p99_ms": tiered["express_p99_ms"],
                "bulk_tput_ratio": tiered["bulk_tput_ratio"],
                "megacycles": tiered["megacycles"],
                "k": kmax,
            }
        except Exception as e:  # noqa: BLE001 — the sweep still banks
            express = {"error": f"{type(e).__name__}: {e}"}
    return {
        "curve": curve,
        "identical": identical,
        "host_s_per_pod_decreasing": decreasing,
        "best_k": best["k"],
        "best_pods_per_s": best["pods_per_s"],
        "host_s_per_pod_at_max_k": curve[-1]["host_s_per_pod"],
        "engine": args.engine,
        **({"express_under_megacycle": express}
           if express is not None else {}),
    }


def run_megacycle_metric(args) -> dict:
    """--megacycle standalone mode: the K sweep as the run's one JSON
    line (value = best pods/s across the sweep; the identity flag and
    the host-seconds curve ride detail)."""
    out = run_megacycle(args)
    return {
        "metric": "megacycle_k_sweep",
        "value": out["best_pods_per_s"],
        "unit": "pods/s",
        "megacycle_identity": out["identical"],
        "detail": out,
    }


def run_replicas(args, ns=None) -> dict:
    """Replica scaling curve (ISSUE 14): the same live workload drained
    with N = 1, 2, 4, ... queue-sharded scheduler replicas sharing one
    cache/queue/resident snapshot and committing through the sequenced
    optimistic conflict reconciler — pods/s + conflict rate vs N at a
    fixed cluster size, plus a multi-tenant storm (one flooding tenant
    against three paced ones) asserting nothing starves and no pod is
    lost.  Every N gets a fresh cluster and the SAME pod set, warmed
    outside the timed window; engines compile once per sweep (replicas
    share replica 0's executables)."""
    from kubernetes_tpu.runtime.cache import SchedulerCache
    from kubernetes_tpu.runtime.queue import PriorityQueue
    from kubernetes_tpu.runtime.replicas import SchedulerReplicaSet
    from kubernetes_tpu.runtime.scheduler import SchedulerConfig

    if ns is None:
        ns = []
        n = 1
        while n <= max(1, args.replicas):
            ns.append(n)
            n *= 2

    def _make(n_replicas: int) -> SchedulerReplicaSet:
        enc = _build_encoder(args)
        return SchedulerReplicaSet(
            replicas=n_replicas,
            cache=SchedulerCache(enc),
            queue=PriorityQueue(shards=n_replicas),
            binder=lambda pod, node: True,
            config=SchedulerConfig(
                batch_size=args.batch, batch_window_s=0.0,
                engine=args.engine, disable_preemption=True,
                batched_commit=True,
                # replicas overlap ACROSS loops; the in-loop double
                # buffer would hold the cache lock pattern hostage to
                # per-replica pipeline state — keep each loop simple
                pipeline_commit=False,
            ),
        )

    curve = []
    # the SAME warm count for every N (capacity consumed pre-window must
    # not vary with N, or the per-N workloads aren't comparable)
    warm_n = args.batch * max(ns)
    for N in ns:
        rs = _make(N)
        # warmup: full-width batches through the replicas (compile +
        # row caches + hub upload) plus the reconciler's admission
        # kernel ladder, outside the timed window
        rs.reconciler.prewarm(args.batch, rs.cache.encoder.dims.R)
        for j in range(warm_n):
            rs.queue.add(_pending_pod(args, args.pods + j))
        rs.run_until_drained(budget_s=600)
        rs.stop()
        conflicts0 = rs.reconciler.conflicts_total
        pending = [_pending_pod(args, i) for i in range(args.pods)]
        t0 = time.monotonic()
        for p in pending:
            rs.queue.add(p)
        placed = rs.run_until_drained(budget_s=900)
        dt = time.monotonic() - t0
        rs.stop()
        conflicts = rs.reconciler.conflicts_total - conflicts0
        drained = rs.assert_drained()
        curve.append({
            "replicas": N,
            "pods_per_s": round(placed / dt, 1) if dt > 0 else 0.0,
            "seconds": round(dt, 3),
            "placed": placed,
            "conflicts": conflicts,
            "conflict_rate": round(conflicts / placed, 4) if placed else 0.0,
            "requeued": rs.reconciler.conflicts_total
            + rs.reconciler.quota_vetoes_total,
            "fast_path": rs.reconciler.fast_path_total,
            "scans": rs.reconciler.scans_total,
            "invariant_violations": rs.invariant_violations_total(),
            "drained_clean": drained,
        })
        sys.stderr.write(
            f"bench: replicas n={N}: {curve[-1]['pods_per_s']} pods/s, "
            f"{conflicts} conflicts "
            f"({curve[-1]['conflict_rate']:.4f}/pod), "
            f"violations={curve[-1]['invariant_violations']}\n"
        )
    base = curve[0]["pods_per_s"]
    best = max(curve, key=lambda c: c["pods_per_s"])
    # ---- multi-tenant storm: one flooding tenant offers as much as the
    # three paced tenants combined, against a capacity-bounded queue at
    # max N — DRF-tiebroken admission + hash shards must leave every
    # tenant with placements, conserve every offered pod, and keep the
    # invariant checker clean
    storm = None
    try:
        n_max = max(ns)
        rs = _make(n_max)
        storm_pods = min(args.pods, 2048)
        offered = []
        for i in range(storm_pods):
            # 1 flooding tenant (every other pod) + 3 paced tenants
            tenant = "flood" if i % 2 == 0 else f"tenant{i % 3}"
            p = _pending_pod(args, i)
            p.metadata.namespace = tenant
            offered.append(p)
        for j in range(warm_n):  # warm outside the window
            rs.queue.add(_pending_pod(args, storm_pods + j))
        rs.run_until_drained(budget_s=600)
        t0 = time.monotonic()
        for p in offered:
            rs.queue.add(p)
        rs.run_until_drained(budget_s=900)
        rs.stop()
        per_tenant: dict = {}
        placed_keys = set()
        for s in rs.schedulers:
            for r in s.results:
                if r.node is not None:
                    placed_keys.add((r.pod.namespace, r.pod.name))
                    per_tenant[r.pod.namespace] = (
                        per_tenant.get(r.pod.namespace, 0) + 1
                    )
        storm_placed = sum(
            1 for p in offered
            if (p.metadata.namespace, p.metadata.name) in placed_keys
        )
        left = len(rs.queue)
        shed = rs.queue.shed_total
        tenants = {"flood"} | {f"tenant{t}" for t in range(3)}
        storm = {
            "seconds": round(time.monotonic() - t0, 3),
            "offered": len(offered),
            "placed": storm_placed,
            "shed": shed,
            "left_in_queue": left,
            "lost": max(0, len(offered) - storm_placed - shed - left),
            "per_tenant": {
                t: per_tenant.get(t, 0) for t in sorted(tenants)
            },
            "no_tenant_starved": all(
                per_tenant.get(t, 0) > 0 for t in tenants
            ),
            "invariant_violations": rs.invariant_violations_total(),
            "drained_clean": rs.assert_drained(),
        }
    except Exception as e:  # noqa: BLE001 — the curve still banks
        storm = {"error": f"{type(e).__name__}: {e}"}
    return {
        "curve": curve,
        # a dead N=1 stage (base 0) must read as scaling 0.0 — the
        # loud gate failure — never divide-by-fallback into a pass
        "scaling_x": (
            round(best["pods_per_s"] / base, 3) if base > 0 else 0.0
        ),
        "best_replicas": best["replicas"],
        "best_pods_per_s": best["pods_per_s"],
        "conflict_rate_at_max_n": curve[-1]["conflict_rate"],
        "zero_lost_pods": all(c["drained_clean"] for c in curve),
        "engine": args.engine,
        "storm": storm,
    }


def run_replicas_metric(args) -> dict:
    """--replicas standalone mode: the N sweep as the run's one JSON
    line (value = best pods/s across the sweep; scaling_x + the storm
    verdicts ride detail)."""
    out = run_replicas(args)
    storm = out.get("storm") or {}
    return {
        "metric": "replica_scaling",
        "value": out["best_pods_per_s"],
        "unit": "pods/s",
        "replica_scaling_x": out["scaling_x"],
        "replica_conflict_rate": out["conflict_rate_at_max_n"],
        "storm_no_starvation": storm.get("no_tenant_starved"),
        "storm_lost_pods": storm.get("lost"),
        "detail": out,
    }


def _ns_with_nodes(args, n_nodes) -> argparse.Namespace:
    a = argparse.Namespace(**vars(args))
    a.nodes = n_nodes
    return a


def _sharded_live(args, n_nodes, n_pods, batch,
                  shard_devices=0, mesh_shape=None) -> dict:
    """One live control-plane run (queue -> schedule_cycle -> bind) at the
    given scale, single-chip (shard_devices=0) or sharded, returning the
    per-pod placements for the identity pin.  Same Scheduler knobs either
    way so the ONLY variable is the mesh."""
    from kubernetes_tpu.runtime.cache import SchedulerCache
    from kubernetes_tpu.runtime.queue import PriorityQueue
    from kubernetes_tpu.runtime.scheduler import Scheduler, SchedulerConfig

    a = _ns_with_nodes(args, n_nodes)
    t_build0 = time.monotonic()
    enc = _build_encoder(a)
    build_s = time.monotonic() - t_build0
    queue = PriorityQueue()
    sched = Scheduler(
        cache=SchedulerCache(enc), queue=queue,
        binder=lambda pod, node: True,
        config=SchedulerConfig(
            batch_size=batch, batch_window_s=0.0, engine=args.engine,
            disable_preemption=True, batched_commit=True,
            pipeline_commit=True,
            shard_devices=shard_devices, mesh_shape=mesh_shape,
        ),
    )

    def _drain(budget_s: float) -> int:
        placed = 0
        deadline = time.monotonic() + budget_s
        while time.monotonic() < deadline:
            got = sched.run_once(timeout=0.0)
            placed += got
            if got == 0 and not sched.pipeline_pending:
                if not queue.has_schedulable():
                    break
                time.sleep(0.002)
        return placed + sched.flush_pipeline()

    # warmup batch outside the timed window (compiles + first fetch)
    for j in range(batch):
        queue.add(_pending_pod(a, n_pods + j))
    _drain(600)
    pending = [_pending_pod(a, i) for i in range(n_pods)]
    t0 = time.monotonic()
    for p in pending:
        queue.add(p)
    placed = _drain(900)
    dt = time.monotonic() - t0
    return {
        "pods_per_s": round(placed / dt, 1) if dt > 0 else 0.0,
        "seconds": round(dt, 3),
        "placed": placed,
        "build_seconds": round(build_s, 3),
        "shard_devices": shard_devices,
        "mesh_shape": mesh_shape,
        # warmup + timed placements in commit order: the bit-identity pin
        # compares the FULL list (same adds either run)
        "placements": [(r.pod.name, r.node) for r in sched.results],
    }


def _shrink_identity_check(args, n_nodes, n_pods, batch) -> dict:
    """The elastic-ladder half of --sharded (ISSUE 10): the SAME pod
    stream through a single-chip reference and through the sharded
    Scheduler with ONE device persistently lost mid-stream.  The sharded
    run must shrink onto the next pow2 of survivors (8 -> 4), keep
    placing BIT-IDENTICALLY to the reference (only the gap cycle rides
    the CPU adapter), end with zero invariant violations and zero lost
    pods, and climb back to the full mesh once the fault clears.

    Both legs run the SEQUENTIAL engine regardless of --engine: the CPU
    adapter that serves the gap cycle carries the sequential scan's
    tie-rotation semantics (cpuref/adapter.py contract), while the
    speculative engine matches it on semantics but not tie rotation — so
    under --engine speculative the gap cycle would diverge on ties at
    this node count and read as a false shrink regression.  Speculative
    sharded identity (no faults) is what the main --sharded leg pins."""
    from kubernetes_tpu.codec import faults as device_faults
    from kubernetes_tpu.parallel.mesh import mesh_device_ids
    from kubernetes_tpu.runtime.cache import SchedulerCache
    from kubernetes_tpu.runtime.queue import PriorityQueue
    from kubernetes_tpu.runtime.scheduler import Scheduler, SchedulerConfig

    a = _ns_with_nodes(args, n_nodes)

    def build(shard_devices):
        return Scheduler(
            cache=SchedulerCache(_build_encoder(a)),
            queue=PriorityQueue(),
            binder=lambda pod, node: True,
            config=SchedulerConfig(
                batch_size=batch, batch_window_s=0.0, engine="sequential",
                disable_preemption=True, batched_commit=True,
                pipeline_commit=True, breaker_open_s=0.05,
                shard_devices=shard_devices, mesh_shape=args.mesh_shape,
            ),
        )

    def drain(s, budget_s=120.0):
        deadline = time.monotonic() + budget_s
        while (
            (s.queue.has_schedulable() or s.pipeline_pending)
            and time.monotonic() < deadline
        ):
            s.run_once(timeout=0.0)
        s.flush_pipeline()

    def feed(s, lo, hi):
        for i in range(lo, hi):
            s.queue.add(_pending_pod(a, i))
        drain(s)

    half = n_pods // 2
    ref = build(0)
    feed(ref, 0, half)
    t0 = time.monotonic()
    feed(ref, half, n_pods)
    healthy_seconds = time.monotonic() - t0

    s = build(args.shard_devices)
    full_width = s.mesh.size
    lost = sorted(mesh_device_ids(s.mesh))[full_width // 2]
    feed(s, 0, half)
    inj = device_faults.FaultInjector(seed=5)
    for site in (device_faults.SITE_DISPATCH, device_faults.SITE_FENCE,
                 device_faults.SITE_SCATTER):
        inj.arm(site, kind=device_faults.FAULT_PERSISTENT,
                device_index=lost)
    remove = device_faults.install_injector(inj)
    t0 = time.monotonic()
    try:
        feed(s, half, n_pods)
        loss_seconds = time.monotonic() - t0
        shrunk_width = s.mesh.size if s.mesh is not None else 0
    finally:
        remove()
    # the fault is gone: the half-open probe of the lost device restores
    time.sleep(s.config.breaker_open_s * 2)
    s.run_once(timeout=0.0)
    restored_width = s.mesh.size if s.mesh is not None else 0

    identical = (
        [(r.pod.name, r.node) for r in ref.results]
        == [(r.pod.name, r.node) for r in s.results]
    )
    inv = s.invariants
    drained_clean = inv.assert_drained() if inv is not None else None
    return {
        "identical": identical,
        "full_width": full_width,
        "shrunk_width": shrunk_width,
        "restored_width": restored_width,
        "lost_device": lost,
        "pods": n_pods,
        "placed": s._outcome_totals["placed"],
        "loss_window_pods_per_s": (
            round((n_pods - half) / loss_seconds, 1)
            if loss_seconds > 0 else 0.0
        ),
        # >0.4x is the acceptance line on REAL hardware (a 4/8 mesh
        # should hold ~0.5x); on the CPU virtual mesh the loss window
        # additionally pays the shrunken topology's XLA compiles, so
        # the ratio is reported for the TPU artifact, not asserted here
        "loss_vs_healthy_ratio": (
            round(healthy_seconds / loss_seconds, 3)
            if loss_seconds > 0 else 0.0
        ),
        # a pure shard loss must be ABSORBED by the ladder: the global
        # breaker (the whole-mesh CPU-adapter cliff) stays closed
        "global_breaker_opened": ("closed", "open") in list(
            s.device_health.transitions
        ),
        "invariant_violations": (
            inv.violations_total() if inv is not None else None
        ),
        "drained_clean": drained_clean,
    }


def _sharded_encode_check(args, n_nodes) -> dict:
    """The encode-fits half of the --sharded scenario: bulk-encode an
    n_nodes fleet, upload it SHARDED through the mesh-backed
    DeviceSnapshotCache, and prove per-device residency — each chip holds
    1/S of every node-axis tensor (the reason a 50k-node snapshot fits a
    mesh that no single chip could hold) — then run one sharded analytics
    reduction over the resident buffers as the compute proof."""
    import dataclasses

    import jax

    from kubernetes_tpu.codec.transfer import DeviceSnapshotCache
    from kubernetes_tpu.ops.analytics import (
        analytics_to_dict,
        cluster_analytics_auto,
    )
    from kubernetes_tpu.parallel.mesh import build_mesh

    a = _ns_with_nodes(args, n_nodes)
    t0 = time.monotonic()
    nodes = _bench_nodes(a)
    t_obj = time.monotonic() - t0
    t0 = time.monotonic()
    enc = _build_encoder(a, nodes)
    encode_s = time.monotonic() - t0
    cluster = enc.snapshot()
    total_bytes = sum(
        np.asarray(getattr(cluster, f.name)).nbytes
        for f in dataclasses.fields(cluster)
    )
    mesh, axis = build_mesh(args.shard_devices or None, args.mesh_shape)
    dsc = DeviceSnapshotCache(mesh=mesh, spec_axis=axis)
    t0 = time.monotonic()
    dev = dsc.update(cluster)
    jax.block_until_ready(dev.allocatable)
    upload_s = time.monotonic() - t0
    per_dev: dict = {}
    for f in dataclasses.fields(cluster):
        for sh in getattr(dev, f.name).addressable_shards:
            d = str(sh.device)
            per_dev[d] = per_dev.get(d, 0) + int(sh.data.nbytes)
    t0 = time.monotonic()
    analytics = analytics_to_dict(
        cluster_analytics_auto(
            *dsc.resident(("allocatable", "requested", "valid"))
        )
    )
    analytics_s = time.monotonic() - t0
    return {
        "nodes": n_nodes,
        "node_objects_seconds": round(t_obj, 3),
        "encode_seconds": round(encode_s, 3),
        "upload_seconds": round(upload_s, 3),
        "snapshot_bytes_total": int(total_bytes),
        "max_device_resident_bytes": max(per_dev.values()),
        "shards": mesh.size,
        "encode_ok": analytics["nodes"] == n_nodes,
        "analytics_seconds": round(analytics_s, 3),
        "utilization_cpu_mean": analytics["utilization"]["cpu"]["mean"],
    }


def run_sharded(args) -> dict:
    """--sharded scenario (ISSUE 9): the live multi-chip control plane.

    Phase 1 — identity at scale: the SAME pod stream through the real
    Scheduler twice (single-chip, then sharded over --shard-devices /
    --mesh-shape) at --sharded-nodes, pinning bit-identical per-cycle
    placements across chained batches.  Phase 2 — encode-fits: a
    --sharded-encode-nodes fleet encoded + uploaded sharded, reporting
    per-device resident bytes (each chip holds 1/S of the node tensors)
    and a sharded analytics launch over the resident buffers."""
    import jax

    from kubernetes_tpu.parallel.mesh import mesh_total

    n_dev = mesh_total(args.mesh_shape, args.shard_devices) or 8
    have = len(jax.devices())
    if have < n_dev:
        raise RuntimeError(
            f"--sharded needs {n_dev} devices, have {have} (on cpu the "
            "bench child forces the virtual-device count itself — pass "
            "--platform cpu)"
        )
    n_nodes = args.sharded_nodes
    n_pods = min(args.pods, 2048)
    batch = min(args.batch, 256)
    single = _sharded_live(args, n_nodes, n_pods, batch)
    sharded = _sharded_live(
        args, n_nodes, n_pods, batch,
        shard_devices=args.shard_devices, mesh_shape=args.mesh_shape,
    )
    identical = single.pop("placements") == sharded.pop("placements")
    ratio = (
        round(sharded["pods_per_s"] / single["pods_per_s"], 3)
        if single["pods_per_s"] else 0.0
    )
    encode = _sharded_encode_check(args, args.sharded_encode_nodes)
    # elastic ladder (ISSUE 10): shard lost mid-stream -> shrink ->
    # bit-identity held -> climb-back, at a scale that keeps the stage
    # inside its budget
    shrink = _shrink_identity_check(
        args, min(n_nodes, 500), min(n_pods, 512), min(batch, 128)
    )
    return {
        "identical": identical,
        "devices": n_dev,
        "mesh_shape": args.mesh_shape,
        "nodes": n_nodes,
        "pods": n_pods,
        "batch": batch,
        "engine": args.engine,
        "single_chip": single,
        "sharded": sharded,
        "sharded_vs_single_ratio": ratio,
        "encode": encode,
        "shrink_identity": shrink,
    }


def run_sharded_metric(args) -> dict:
    """Standalone --sharded entry: one JSON line in the bench contract.
    value 1.0 = sharded placements bit-identical to single-chip AND the
    large-fleet sharded encode landed AND the elastic ladder held (shrink
    on a mid-stream shard loss stayed bit-identical with the global
    breaker closed and zero invariant violations, and the mesh climbed
    back once the fault cleared)."""
    detail = run_sharded(args)
    shrink = detail["shrink_identity"]
    ok = (
        detail["identical"]
        and detail["encode"]["encode_ok"]
        and shrink["identical"]
        and shrink["invariant_violations"] == 0
        and shrink["drained_clean"] is True
        and not shrink["global_breaker_opened"]
        and shrink["shrunk_width"] == shrink["full_width"] // 2
        and shrink["restored_width"] == shrink["full_width"]
    )
    return {
        "metric": "sharded_live_identity",
        "value": 1.0 if ok else 0.0,
        "unit": "bool",
        "sharded_pods_per_s": detail["sharded"]["pods_per_s"],
        "sharded_vs_single_ratio": detail["sharded_vs_single_ratio"],
        "shrink_identity": shrink["identical"],
        "detail": detail,
    }


def _sharded_stage(args) -> dict:
    """The default report's `sharded` stage, scaled down to the run's
    size and executed in a SUBPROCESS: the virtual-device count is baked
    into XLA_FLAGS at backend init, and this child's backend is already
    up single-device."""
    if args.shard_devices < 2:
        # an explicit --shard-devices 0/1 means single-chip: skip cleanly
        # (forwarding it would argparse-exit the grandchild with no JSON
        # line and surface an opaque 'emitted no JSON line' error)
        raise RuntimeError(
            f"skipped: --shard-devices {args.shard_devices} < 2 "
            "(single-chip requested; no sharded leg to compare)"
        )
    env = dict(os.environ)
    env[_CHILD_ENV] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    remaining = (
        float(os.environ.get(_DEADLINE_ENV, time.time() + 480.0))
        - time.time()
    )
    if remaining < 180.0:
        # best-effort stage: bowing out beats forcing a >=60s grandchild
        # into a window the parent's watchdog will kill first, losing the
        # already-banked headline result
        raise RuntimeError(
            f"skipped: {remaining:.0f}s left before the run deadline "
            "< 180s stage floor"
        )
    budget = min(480.0, remaining - 120.0)
    env[_DEADLINE_ENV] = str(time.time() + budget)
    cmd = [
        sys.executable, os.path.abspath(__file__), "--sharded",
        "--platform", "cpu",
        "--engine", args.engine, "--workload", args.workload,
        "--pods", str(min(args.pods, 512)),
        "--batch", str(min(args.batch, 128)),
        "--shard-devices", str(args.shard_devices),
        "--sharded-nodes", str(min(args.nodes, 512)),
        "--sharded-encode-nodes",
        str(min(max(args.nodes * 2, 1024), 4096)),
    ]
    if args.mesh_shape:
        cmd += ["--mesh-shape", args.mesh_shape]
    proc = subprocess.run(
        cmd, env=env, stdout=subprocess.PIPE, timeout=budget + 30,
        text=True,
    )
    res = _last_json_line(proc.stdout)
    if not res:
        raise RuntimeError("sharded stage child emitted no JSON line")
    detail = res.get("detail", res)
    if "error" in detail:
        # a grandchild watchdog/error line must surface as sharded_error,
        # not as sharded_identity=false (a placement-divergence signal)
        raise RuntimeError(f"sharded stage child failed: {detail['error']}")
    return detail


def _autoscale_workload(args):
    """Deterministic duplicate-heavy autoscale inputs: a backlog of
    `autoscale_pods` requests drawn from `autoscale_classes` distinct
    controller-stamped vectors, and a random cpu x memory shape grid of
    `autoscale_shapes` candidates.  Integer units by construction
    (milliCPU / Mi / pod slots) — the count kernel's exactness contract,
    so the compressed and per-pod legs are bins-needed comparable."""
    rng = np.random.default_rng(20260804)
    r = 8
    n_classes = max(1, args.autoscale_classes)
    base = np.zeros((n_classes, r), np.float32)
    base[:, 0] = rng.integers(50, 4000, n_classes)       # milliCPU
    base[:, 1] = rng.integers(64, 8192, n_classes)       # memory (Mi)
    base[:, 3] = 1.0                                     # one pod slot
    reqs = base[rng.integers(0, n_classes, args.autoscale_pods)]
    s = max(1, args.autoscale_shapes)
    shapes = np.zeros((s, r), np.float32)
    shapes[:, 0] = rng.integers(4000, 128001, s)         # 4-128 cores
    shapes[:, 1] = rng.integers(16 * 1024, 512 * 1024 + 1, s)  # 16G-512G
    shapes[:, 3] = 110.0
    return reqs, shapes


def run_autoscale(args) -> dict:
    """--autoscale: the BASELINE fifth config — cluster-autoscaler
    what-if binpack of a pending backlog over a candidate-shape catalog
    (ISSUE 15).  Four legs:

      1. reference: the per-pod binpack_shapes scan over the backlog x
         a small shape slice (the pre-compression semantics);
      2. compressed: the class-compressed count kernel on the SAME
         inputs — bins-needed identity asserted, solve-time speedup
         banked (class-compression host cost included on its side);
      3. the full catalog sweep, compressed (shapes/s — the headline;
         --autoscale-shapes 10000 is the full BASELINE config, the CPU
         default is budget-scaled);
      4. sharded: the shape axis over the device mesh
         (what_if_sharded), identity-pinned vs the single-chip call —
         padded zero-capacity lanes must filter out.

    Legs 3 and 4 are best-effort: each bows out when the remaining
    watchdog budget could not absorb it (the _sharded_stage
    discipline), so the banked legs 1-2 are never lost to a deadline."""
    import jax

    from kubernetes_tpu.models.binpack import (
        binpack_shapes,
        binpack_shapes_compressed,
        compress_classes,
        what_if,
        what_if_sharded,
    )

    deadline = float(
        os.environ.get(_DEADLINE_ENV, str(time.time() + args.watchdog))
    )
    reqs, shapes = _autoscale_workload(args)
    max_bins = args.autoscale_bins
    sh_ref = shapes[: max(1, args.autoscale_ref_shapes)]
    detail: dict = {
        "pods": int(reqs.shape[0]),
        "shapes": int(shapes.shape[0]),
        "ref_shapes": int(sh_ref.shape[0]),
        "max_bins": int(max_bins),
        "device": str(jax.devices()[0]),
    }

    # ---- leg 1: per-pod reference (warm once, time the second call)
    b_ref, ok_ref = binpack_shapes(reqs, sh_ref, max_bins=max_bins)
    np.asarray(b_ref)
    t0 = time.monotonic()
    b_ref, ok_ref = binpack_shapes(reqs, sh_ref, max_bins=max_bins)
    b_ref, ok_ref = np.asarray(b_ref), np.asarray(ok_ref)
    t_ref = time.monotonic() - t0
    detail["reference_seconds"] = round(t_ref, 3)

    # ---- leg 2: class compression + count kernel on the same inputs
    t0 = time.monotonic()
    classes, counts = compress_classes(reqs, pad_to_pow2=True)
    t_compress = time.monotonic() - t0
    b_c, ok_c = binpack_shapes_compressed(
        classes, counts, sh_ref, max_bins=max_bins
    )
    np.asarray(b_c)
    t0 = time.monotonic()
    b_c, ok_c = binpack_shapes_compressed(
        classes, counts, sh_ref, max_bins=max_bins
    )
    b_c, ok_c = np.asarray(b_c), np.asarray(ok_c)
    t_comp = time.monotonic() - t0
    identical = bool(
        np.array_equal(b_ref, b_c) and np.array_equal(ok_ref, ok_c)
    )
    if not identical:
        raise AssertionError(
            "class-compressed what-if diverged from the per-pod "
            f"reference: bins {b_ref.tolist()} vs {b_c.tolist()}"
        )
    n_classes = int(np.sum(np.any(classes > 0, axis=-1)))
    speedup = t_ref / max(t_comp + t_compress, 1e-9)
    detail.update({
        "classes": n_classes,
        "compression_x": round(reqs.shape[0] / max(n_classes, 1), 1),
        "compress_seconds": round(t_compress, 3),
        "compressed_seconds": round(t_comp, 3),
        "speedup_x": round(speedup, 2),
        "identical": identical,
        "ref_bins": b_ref.tolist(),
    })

    # ---- leg 3: the full sweep, compressed (deadline-guarded: the
    # per-shape cost just measured predicts the sweep; bow out rather
    # than let the watchdog kill the banked speedup)
    est = (t_comp / max(sh_ref.shape[0], 1)) * shapes.shape[0] * 1.5
    remaining = deadline - time.time()
    if remaining < est + 60.0:
        # NOTE: shapes_per_s is deliberately NOT set — a banked 0.0
        # would read as a perf regression at the --baseline gate, and
        # a budget bow-out is not one (the gate skips absent paths)
        detail["sweep_skipped"] = (
            f"estimated {est:.0f}s sweep > {remaining:.0f}s remaining "
            "- 60s floor"
        )
    else:
        t0 = time.monotonic()
        b_full, ok_full = binpack_shapes_compressed(
            classes, counts, shapes, max_bins=max_bins
        )
        b_full, ok_full = np.asarray(b_full), np.asarray(ok_full)
        t_full = time.monotonic() - t0
        shapes_per_s = shapes.shape[0] / max(t_full, 1e-9)
        fitting = np.flatnonzero(ok_full)
        detail.update({
            "sweep_seconds": round(t_full, 3),
            "shapes_per_s": round(shapes_per_s, 1),
            "shapes_fitting": int(len(fitting)),
            "best_shape_bins": (
                int(b_full[fitting].min()) if len(fitting) else None
            ),
        })

    # ---- leg 4: sharded shape axis (>= 2 devices; best-effort)
    n_dev = 1
    while n_dev * 2 <= min(len(jax.devices()), args.shard_devices):
        n_dev *= 2
    remaining = deadline - time.time()
    if n_dev < 2:
        detail["sharded_skipped"] = (
            f"{len(jax.devices())} device(s) visible, shard_devices="
            f"{args.shard_devices} (need >= 2)"
        )
    elif remaining < max(60.0, t_ref * 3):
        detail["sharded_skipped"] = (
            f"{remaining:.0f}s left before the run deadline"
        )
    else:
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:n_dev]), ("shapes",))
        # a deliberately non-multiple shape count so the pad lanes are
        # exercised on every run, not only in the unit test
        sh_shard = shapes[: max(n_dev + 1, sh_ref.shape[0])]
        single = what_if(reqs, sh_shard, max_bins=max_bins)
        sharded = what_if_sharded(reqs, sh_shard, mesh, max_bins=max_bins)
        detail["sharded"] = {
            "devices": n_dev,
            "shapes": int(sh_shard.shape[0]),
            "identical": sharded == single,
        }
        if sharded != single:
            raise AssertionError(
                f"sharded what-if diverged: {sharded} vs {single}"
            )
    return detail


def run_autoscale_metric(args) -> dict:
    """Standalone --autoscale entry: one JSON line in the bench
    contract; the headline value is the compressed-vs-per-pod solve
    speedup (the ISSUE 15 acceptance line), with the sweep rate and
    identity flags alongside."""
    detail = run_autoscale(args)
    out = {
        "metric": "autoscale_speedup_x",
        "value": detail["speedup_x"],
        "unit": "x",
        "autoscale_identity": detail["identical"],
        "autoscale_sharded_identity": (
            detail.get("sharded", {}).get("identical")
        ),
        "detail": detail,
    }
    if "shapes_per_s" in detail:
        # absent when the sweep bowed out: a banked 0.0 would trip the
        # --baseline gate for a budget decision, not a regression
        out["autoscale_shapes_per_s"] = detail["shapes_per_s"]
    return out


def _autoscale_stage(args) -> dict:
    """The default report's `autoscale` stage: the --autoscale legs at
    CI scale in a SUBPROCESS (the sharded leg needs the virtual-device
    count baked into backend init — the _sharded_stage pattern),
    deadline-guarded so this best-effort stage can never cost the
    banked headline result."""
    env = dict(os.environ)
    env[_CHILD_ENV] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    remaining = (
        float(os.environ.get(_DEADLINE_ENV, time.time() + 480.0))
        - time.time()
    )
    if remaining < 180.0:
        raise RuntimeError(
            f"skipped: {remaining:.0f}s left before the run deadline "
            "< 180s stage floor"
        )
    budget = min(300.0, remaining - 120.0)
    env[_DEADLINE_ENV] = str(time.time() + budget)
    cmd = [
        sys.executable, os.path.abspath(__file__), "--autoscale",
        "--platform", "cpu",
        "--autoscale-pods", str(min(args.autoscale_pods, 20000)),
        "--autoscale-classes", str(min(args.autoscale_classes, 128)),
        "--autoscale-shapes", str(min(args.autoscale_shapes, 256)),
        "--autoscale-ref-shapes", str(min(args.autoscale_ref_shapes, 4)),
        "--autoscale-bins", str(min(args.autoscale_bins, 1024)),
        "--shard-devices", str(args.shard_devices),
    ]
    proc = subprocess.run(
        cmd, env=env, stdout=subprocess.PIPE, timeout=budget + 30,
        text=True,
    )
    res = _last_json_line(proc.stdout)
    if not res:
        raise RuntimeError("autoscale stage child emitted no JSON line")
    detail = res.get("detail", res)
    if "error" in detail:
        raise RuntimeError(f"autoscale stage child failed: {detail['error']}")
    return detail


def run_tiered_metric(args) -> dict:
    """Standalone --tiered entry: one JSON line in the bench contract."""
    detail = run_tiered(args)
    return {
        "metric": "express_lane_p99_ms",
        "value": detail["express_p99_ms"],
        "unit": "ms",
        "cold_start_seconds": detail["cold_start_seconds"],
        "bulk_tput_ratio": detail["bulk_tput_ratio"],
        "detail": detail,
    }


def run_density(args) -> dict:
    """Sustained-density mode (VERDICT r4 #8): the reference's 30k-pod
    density config against a LIVE control plane — 1k hollow nodes, pods
    arriving in waves with churn, per-interval pods/s recorded
    (ref test/integration/scheduler_perf/scheduler_test.go:90-96,133-178)."""
    from kubernetes_tpu.runtime.density import run_sustained_density

    return run_sustained_density(
        nodes=args.nodes, pods=args.pods, batch=args.batch,
        interval_s=args.density_interval, churn_fraction=args.density_churn,
        engine=args.engine, arrival_rate=args.density_arrival_rate,
    )


# --------------------------------------------------------------- child mode


def run_child(args) -> None:
    """One attempt, one JSON line, no retries.  The parent orchestrator
    interprets the line; a failure here simply means the parent falls back
    to its banked CPU result."""
    on_cpu = args.platform == "cpu" or os.environ.get("JAX_PLATFORMS") == "cpu"
    if args.autoscale and on_cpu and args.shard_devices >= 2:
        # the autoscale sharded leg shards the shape axis over virtual
        # cpu devices, forced before any jax touch like --sharded below
        from kubernetes_tpu.utils.jaxenv import set_host_device_count

        set_host_device_count(max(args.shard_devices, 8))
    if args.sharded and on_cpu:
        # the virtual-device count is read ONCE at backend init: force it
        # before any jax touch (real accelerators bring their own devices)
        from kubernetes_tpu.parallel.mesh import mesh_total
        from kubernetes_tpu.utils.jaxenv import set_host_device_count

        set_host_device_count(
            max(mesh_total(args.mesh_shape, args.shard_devices), 8)
        )
    deadline = float(os.environ.get(_DEADLINE_ENV,
                                    str(time.time() + args.watchdog)))
    lock = None
    if not on_cpu:  # cpu runs don't touch the tunnel; no serialization needed
        lock_budget = max(10.0, min(args.lock_timeout, deadline - time.time() - 120))
        lock = _acquire_device_lock(lock_budget)
        if lock is None:
            _emit(_error_line(
                "device-lock",
                TimeoutError(f"could not acquire {_LOCK_PATH} in {lock_budget:.0f}s"),
            ))
            return

    # whole-run watchdog: a wedged tunnel can HANG (nanosleep, no error)
    # rather than fail — backend init and even mid-run transfers have no
    # timeout of their own.  Guarantees the parent always gets one JSON
    # line from this child instead of silence.
    import threading

    remaining = deadline - time.time()

    def _watchdog_fire():
        fired = _emit(_error_line(
            "watchdog",
            TimeoutError(f"no result within {remaining:.0f}s (tunnel wedge?)"),
        ))
        if fired:
            os._exit(2)

    if remaining <= 0:
        _watchdog_fire()
        return
    wd = threading.Timer(remaining, _watchdog_fire)
    wd.daemon = True
    wd.start()

    try:
        try:
            import jax

            if args.platform:
                # the image's sitecustomize overrides env at interpreter
                # start — only an in-process config update actually
                # switches the backend
                jax.config.update("jax_platforms", args.platform)
            # persistent compile cache: the sequential-scan compile is
            # minutes through the axon tunnel; cache it across processes
            from kubernetes_tpu.utils.jaxenv import enable_compile_cache

            enable_compile_cache()
            # backend init in a worker thread: a wedged tunnel HANGS here
            # (hrtimer_nanosleep) instead of raising, so poll with a
            # deadline
            init_done: dict = {}

            def _init():
                try:
                    init_done["devices"] = jax.devices()
                    # pre-warm with a trivial kernel AND a fetch inside the
                    # same deadline: a tunnel that wedges at first USE (init
                    # succeeds, compute hangs) is caught here, not after the
                    # 5k-node encode; the fetch also pays the one-time D2H
                    # setup cost outside the timed window
                    import jax.numpy as jnp

                    probe = np.asarray(jnp.arange(8.0) * 2.0)
                    init_done["probe"] = float(probe[-1])
                except Exception as ie:  # noqa: BLE001
                    init_done["error"] = ie

            init_budget = min(args.init_timeout, max(10.0, deadline - time.time() - 60))
            t_init = threading.Thread(target=_init, daemon=True)
            t_init.start()
            t_init.join(init_budget)
            if t_init.is_alive():
                raise TimeoutError(
                    f"UNAVAILABLE: backend init exceeded {init_budget:.0f}s"
                )
            if "error" in init_done:
                raise init_done["error"]
        except Exception as e:  # backend init failed (tunnel wedged / no lease)
            _emit(_error_line("backend-init", e))
            return

        try:
            if args.overload:
                result = run_overload(args)
            elif args.density:
                result = run_density(args)
            elif args.tiered:
                result = run_tiered_metric(args)
            elif args.megacycle:
                result = run_megacycle_metric(args)
            elif args.autoscale_live:
                result = run_autoscale_live(args)
            elif args.autoscale:
                result = run_autoscale_metric(args)
            elif args.replicas:
                result = run_replicas_metric(args)
            elif args.sharded:
                result = run_sharded_metric(args)
            elif args.scenario:
                result = run_scenario_metric(args)
            else:
                result = run(args)
        except Exception as e:  # compile/runtime failure mid-run
            _emit(_error_line("run", e))
            return
        _write_trace_artifact(args)
        _write_cluster_artifact(args)
        _write_timeline_artifact(args)
        _emit(result)
    finally:
        if lock is not None:
            try:
                lock.close()
            except Exception:
                pass


# ---------------------------------------------------------- parent orchestration


def _write_trace_artifact(args) -> None:
    """--trace-out: dump the process-wide flight recorder (the cycle
    spans every live-path Scheduler recorded during this run) as Chrome
    trace-event JSON — the per-run artifact that makes a bench number's
    phase claims inspectable in Perfetto.  Best-effort: a trace-write
    failure must never eat the result line."""
    path = getattr(args, "trace_out", None)
    if not path:
        return
    try:
        from kubernetes_tpu.runtime.flightrecorder import RECORDER

        with open(path, "w") as f:
            json.dump(RECORDER.chrome_trace(), f)
        sys.stderr.write(
            f"bench: wrote {len(RECORDER.spans())} cycle spans to {path}\n"
        )
    except Exception as e:  # noqa: BLE001
        sys.stderr.write(f"bench: --trace-out failed: {e}\n")


def _write_cluster_artifact(args) -> None:
    """--cluster-out: dump the process-default telemetry hub's
    /debug/cluster payload (the bounded analytics time series the
    live-path Scheduler collected) as JSON.  Best-effort like the trace
    artifact — a write failure must never eat the result line."""
    path = getattr(args, "cluster_out", None)
    if not path:
        return
    try:
        from kubernetes_tpu.runtime.telemetry import get_default

        payload = get_default().debug_payload()
        with open(path, "w") as f:
            json.dump(payload, f)
        sys.stderr.write(
            f"bench: wrote {len(payload['samples'])} telemetry samples "
            f"to {path}\n"
        )
    except Exception as e:  # noqa: BLE001
        sys.stderr.write(f"bench: --cluster-out failed: {e}\n")


def _write_timeline_artifact(args) -> None:
    """--timeline-out: dump the process-default metrics timeline store
    (ISSUE 20) as JSONL, plus a dependency-free static HTML report
    (inline SVG sparklines with the annotation lanes) next to it at
    <path>.html.  A scenario run already exported the JSONL inside
    run_scenario — re-exporting the same store here is idempotent and
    keeps ONE artifact path for every bench mode.  Best-effort like the
    trace/cluster artifacts."""
    path = getattr(args, "timeline_out", None)
    if not path:
        return
    try:
        from kubernetes_tpu.runtime import timeline as timeline_mod

        store = timeline_mod.get_default()
        n = store.export_jsonl(path)
        html_path = path + ".html"
        payload = store.debug_payload()
        with open(html_path, "w") as f:
            f.write(timeline_mod.render_html(
                payload, title=f"kubernetes_tpu timeline — {path}"
            ))
        sys.stderr.write(
            f"bench: wrote {n} timeline records to {path} "
            f"(+ report {html_path})\n"
        )
    except Exception as e:  # noqa: BLE001
        sys.stderr.write(f"bench: --timeline-out failed: {e}\n")


def _last_json_line(text: str):
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


def _child_cmd(args, platform: str | None) -> list:
    cmd = [
        sys.executable, os.path.abspath(__file__),
        "--nodes", str(args.nodes), "--pods", str(args.pods),
        "--batch", str(args.batch), "--workload", args.workload,
        "--existing", str(args.existing),
        "--engine", args.engine, "--warmup", str(args.warmup),
        "--init-timeout", str(args.init_timeout),
        "--lock-timeout", str(args.lock_timeout),
    ]
    if getattr(args, "trace_out", None):
        cmd += ["--trace-out", args.trace_out]
    if getattr(args, "ledger_out", None):
        cmd += ["--ledger-out", args.ledger_out]
    if getattr(args, "cluster_out", None):
        cmd += ["--cluster-out", args.cluster_out]
    if getattr(args, "quality_out", None):
        cmd += ["--quality-out", args.quality_out]
    if getattr(args, "timeline_out", None):
        cmd += ["--timeline-out", args.timeline_out]
    if args.density:
        cmd += ["--density",
                "--density-interval", str(args.density_interval),
                "--density-churn", str(args.density_churn)]
        if args.density_arrival_rate is not None:
            cmd += ["--density-arrival-rate",
                    str(args.density_arrival_rate)]
    if args.overload:
        cmd += ["--overload",
                "--overload-factor", str(args.overload_factor),
                "--overload-duration", str(args.overload_duration)]
    if args.tiered:
        cmd += ["--tiered"]
    if args.megacycle:
        cmd += ["--megacycle"]
    cmd += ["--megacycle-max", str(args.megacycle_max)]
    if args.autoscale:
        cmd += ["--autoscale"]
    cmd += ["--autoscale-pods", str(args.autoscale_pods),
            "--autoscale-classes", str(args.autoscale_classes),
            "--autoscale-shapes", str(args.autoscale_shapes),
            "--autoscale-ref-shapes", str(args.autoscale_ref_shapes),
            "--autoscale-bins", str(args.autoscale_bins)]
    if args.autoscale_live:
        cmd += ["--autoscale-live",
                "--autoscale-live-pods", str(args.autoscale_live_pods)]
        if args.autoscale_ledger_out:
            cmd += ["--autoscale-ledger-out", args.autoscale_ledger_out]
    if args.replicas:
        cmd += ["--replicas", str(args.replicas)]
    if args.sharded:
        cmd += ["--sharded",
                "--sharded-nodes", str(args.sharded_nodes),
                "--sharded-encode-nodes", str(args.sharded_encode_nodes)]
    if args.scenario:
        cmd += ["--scenario", args.scenario]
        if args.scenario_trace:
            cmd += ["--scenario-trace", args.scenario_trace]
    cmd += ["--scenario-pods", str(args.scenario_pods),
            "--scenario-nodes", str(args.scenario_nodes),
            "--scenario-rate", str(args.scenario_rate),
            "--scenario-compression", str(args.scenario_compression),
            "--scenario-seed", str(args.scenario_seed)]
    # always forwarded (like --mesh-shape): the default report's sharded
    # stage must honor an explicit --shard-devices (including 0 = skip),
    # not have the child re-default it
    cmd += ["--shard-devices", str(args.shard_devices)]
    if args.mesh_shape:
        cmd += ["--mesh-shape", args.mesh_shape]
    cmd += ["--tier-deadline", str(args.tier_deadline)]
    if platform:
        cmd += ["--platform", platform]
    return cmd


def orchestrate(args) -> None:
    deadline = time.time() + args.watchdog
    banked: dict = {"result": None}

    def _on_signal(signum, frame):  # noqa: ARG001
        res = banked["result"] or _error_line(
            "signal", f"terminated by signal {signum} before any result")
        det = res.setdefault("detail", {})
        det.setdefault("note", f"emitted from signal {signum} handler")
        _emit(res)
        os._exit(1)

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    # ---- phase 1: the CPU number, banked FIRST.  A CPU child is safe to
    # kill on timeout (no tunnel state), so a hard subprocess timeout is fine.
    cpu_budget = min(args.cpu_budget, max(60.0, deadline - time.time() - 120.0))
    env = dict(os.environ)
    env[_CHILD_ENV] = "1"
    env[_DEADLINE_ENV] = str(time.time() + cpu_budget)
    env["JAX_PLATFORMS"] = "cpu"
    cpu_args = argparse.Namespace(**vars(args))
    cpu_cap = int(os.environ.get("KTPU_BENCH_CPU_BATCH_CAP", "2048"))
    cpu_args.batch = min(args.batch, cpu_cap)
    sys.stderr.write(f"bench: phase 1 (cpu, budget {cpu_budget:.0f}s)\n")
    sys.stderr.flush()
    try:
        proc = subprocess.run(
            _child_cmd(cpu_args, "cpu"), env=env, stdout=subprocess.PIPE,
            timeout=cpu_budget + 30, text=True,
        )
        cpu_res = _last_json_line(proc.stdout)
    except subprocess.TimeoutExpired as e:
        cpu_res = _last_json_line(e.stdout.decode() if isinstance(e.stdout, bytes)
                                  else (e.stdout or ""))
        if cpu_res is None:
            cpu_res = _error_line("cpu-timeout",
                                  f"cpu phase exceeded {cpu_budget:.0f}s")
    except Exception as e:  # noqa: BLE001
        cpu_res = _error_line("cpu-phase", e)
    if cpu_res is None:
        cpu_res = _error_line("cpu-phase", "cpu child emitted no JSON line")
    banked["result"] = cpu_res
    sys.stderr.write(
        f"bench: banked cpu result: {cpu_res.get('value')} {cpu_res.get('unit')}\n")
    sys.stderr.flush()

    # ---- phase 2: exactly ONE TPU attempt inside whatever budget remains.
    remaining = deadline - time.time()
    tpu_min = args.tpu_min_budget
    if (args.platform == "cpu" or args.density or args.overload
            or args.tiered or args.sharded or args.megacycle
            or args.scenario or args.autoscale_live):
        # explicit cpu-only run, or density/overload/tiered/sharded/
        # megacycle/scenario/autoscale-live mode (control-plane
        # benchmarks — the host runtime dominates, not the device; the
        # sharded identity pin runs on the virtual cpu mesh)
        remaining = 0
    if remaining < tpu_min:
        det = banked["result"].setdefault("detail", {})
        det["tpu_skipped"] = (
            f"{remaining:.0f}s left < {tpu_min:.0f}s minimum for one attempt")
        _emit(banked["result"])
        return
    env = dict(os.environ)
    env[_CHILD_ENV] = "1"
    env.pop("JAX_PLATFORMS", None)
    env[_DEADLINE_ENV] = str(deadline - 30.0)  # child self-reports before us
    sys.stderr.write(f"bench: phase 2 (tpu, budget {remaining:.0f}s)\n")
    sys.stderr.flush()
    tpu_res = None
    tpu_note = None
    try:
        proc = subprocess.Popen(
            _child_cmd(args, None), env=env, stdout=subprocess.PIPE, text=True,
        )
        try:
            out, _ = proc.communicate(timeout=max(10.0, deadline - time.time() - 10.0))
            tpu_res = _last_json_line(out)
        except subprocess.TimeoutExpired:
            # do NOT SIGKILL a process that may be mid-device-use: that
            # wedges the tunnel lease for hours.  SIGTERM, short grace,
            # then abandon — we are about to exit anyway.
            proc.terminate()
            try:
                out, _ = proc.communicate(timeout=10.0)
                tpu_res = _last_json_line(out)
            except subprocess.TimeoutExpired:
                tpu_note = "tpu child unresponsive at deadline (abandoned, not killed)"
    except Exception as e:  # noqa: BLE001
        tpu_note = f"tpu phase error: {type(e).__name__}: {e}"

    cpu_val = banked["result"].get("value", 0.0)
    if tpu_res and tpu_res.get("value", 0.0) > 0:
        det = tpu_res.setdefault("detail", {})
        det["cpu_reference"] = {
            "value": cpu_val,
            "latency_ms": banked["result"].get("detail", {}).get("latency_ms"),
            # the tier + sharded stages run in the CPU child only (budget
            # protection); their figures still ride the emitted TPU
            # artifact here
            "latency_tiers": banked["result"].get("detail", {}).get(
                "latency_tiers"
            ),
            "sharded": banked["result"].get("detail", {}).get("sharded"),
            "megacycle": banked["result"].get("detail", {}).get(
                "megacycle"
            ),
        }
        _emit(tpu_res)
        return
    det = banked["result"].setdefault("detail", {})
    if tpu_res is not None:
        det["tpu_error"] = tpu_res.get("detail", {})
    if tpu_note:
        det["tpu_note"] = tpu_note
    _emit(banked["result"])


# ------------------------------------------------- perf-regression gate
#
# `--baseline BENCH_rNN.json` turns the pile of banked bench artifacts
# into a gate: load a prior artifact, compare the tracked trajectory
# figures against the current run (or `--compare-to` another artifact,
# offline), emit a delta report, and exit non-zero on an out-of-band
# regression.  Tolerance bands are per-metric weights scaled by one
# `--baseline-tolerance` knob, so CI can run the same gate with a
# generous band on shared runners while a TPU trajectory check runs
# tight.

# (name, artifact paths tried in order, direction, tolerance weight).
# Direction says which way is BETTER; the band only gates the worse
# direction (a faster run never "regresses" by being too good).
_BASELINE_CHECKS = (
    ("pods_per_s", ("value",), "higher", 1.0),
    ("live_path_pods_per_s",
     ("live_path_pods_per_s", "detail.live_path.pods_per_s"),
     "higher", 1.0),
    ("p99_ms",
     ("p99_schedule_latency_ms", "detail.latency_ms.p99"),
     "lower", 1.5),
    ("overlap_efficiency",
     ("live_path_overlap_efficiency",
      "detail.live_path.overlap_efficiency"),
     "higher", 1.0),
    ("cold_start_seconds",
     ("cold_start_seconds", "detail.cold_start_seconds"),
     "lower", 2.0),
    ("node_encode_speedup", ("node_encode_speedup",), "higher", 1.0),
    ("express_p99_ms", ("express_p99_ms",), "lower", 1.5),
    # megacycle (ISSUE 12): the chained-launch throughput and the host
    # seconds it exists to shrink — a regression in the K-deep path
    # (lost chaining, a per-sub-batch fence sneaking back) moves these
    ("megacycle_pods_per_s",
     ("megacycle_pods_per_s", "detail.megacycle.best_pods_per_s"),
     "higher", 1.0),
    ("megacycle_host_s_per_pod",
     ("megacycle_host_s_per_pod",
      "detail.megacycle.host_s_per_pod_at_max_k"),
     "lower", 1.5),
    # placement quality (ISSUE 13): margin is BAND-gated — a collapse
    # (every decision a coin flip) and an explosion (scores diverged)
    # both mean the scoring function changed out from under us; the
    # observatory's hot-path cost gates lower-is-better like a latency
    ("placement_margin_p50",
     ("placement_margin_p50", "detail.quality.margin_p50"),
     "band", 1.0),
    ("quality_overhead_ratio",
     ("quality_overhead_ratio", "detail.quality.overhead_ratio"),
     "lower", 1.5),
    # queue-sharded replicas (ISSUE 14): throughput scaling vs one
    # replica must not collapse (a re-serialized commit path, a lock
    # held across the device window), and the optimistic conflict rate
    # must not explode (a broken generation fence scanning — and
    # losing — every cycle)
    ("replica_scaling_x",
     ("replica_scaling_x", "detail.replicas.scaling_x"),
     "higher", 1.0),
    ("replica_conflict_rate",
     ("replica_conflict_rate", "detail.replicas.conflict_rate_at_max_n"),
     "lower", 1.5),
    # capacity planning (ISSUE 15): the class-compressed what-if must
    # keep beating the per-pod reference (a lost compression — e.g. the
    # count kernel silently falling back to per-pod semantics — moves
    # this), and the catalog sweep rate must not collapse
    ("autoscale_speedup_x",
     ("autoscale_speedup_x", "detail.autoscale.speedup_x"),
     "higher", 1.0),
    ("autoscale_shapes_per_s",
     ("autoscale_shapes_per_s", "detail.autoscale.shapes_per_s"),
     "higher", 1.0),
    # scenario engine (ISSUE 18): recovery from a rolling drain must not
    # degrade — displaced pods reschedule within the banked tail (a
    # regression here means the displaced requeue path slowed or broke)
    # and goodput during the event holds its ratio to the pre-event rate
    ("scenario_reschedule_p99_ms",
     ("scenario_reschedule_p99_ms", "detail.scenario.reschedule_ms.p99"),
     "lower", 2.0),
    ("scenario_goodput_ratio",
     ("scenario_goodput_ratio", "detail.scenario.goodput_ratio"),
     "higher", 1.5),
)

# phase-second growth is noisy at smoke scale: a phase only regresses
# past BOTH a relative band (2x the base tolerance) and an absolute
# floor, so a 20ms phase doubling on a busy runner doesn't fail a build
_PHASE_ABS_FLOOR_S = 0.5


def _artifact_path(d: dict, dotted: str):
    """Raw value at a dotted path, or None when absent."""
    cur = d
    for part in dotted.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


def _artifact_get(d: dict, dotted: str):
    """Numeric value at a dotted path, or None when absent/non-numeric."""
    cur = _artifact_path(d, dotted)
    return float(cur) if isinstance(cur, (int, float)) else None


def load_artifact(path: str) -> dict:
    """A bench artifact from disk.  Accepts both the raw one-JSON-line
    form bench emits and the driver's banked wrapper (BENCH_rNN.json:
    {n, cmd, rc, tail, parsed} — the artifact lives under "parsed")."""
    with open(path) as f:
        d = json.load(f)
    if isinstance(d, dict) and isinstance(d.get("parsed"), dict):
        d = d["parsed"]
    if not isinstance(d, dict) or "value" not in d:
        raise ValueError(
            f"{path} is not a bench artifact (no 'value' field)"
        )
    return d


def compare_artifacts(baseline: dict, current: dict,
                      tolerance: float = 0.2) -> dict:
    """Delta report between two bench artifacts.  Each tracked metric
    present in BOTH artifacts is checked against its tolerance band
    (weight x `tolerance`, capped at 95%); detail.phases is compared
    with the looser 2x band + an absolute floor.  Returns the report —
    `regressions` lists every check that failed its band."""
    tolerance = max(0.0, float(tolerance))
    checks = []
    regressions = []
    for name, paths, direction, weight in _BASELINE_CHECKS:
        base = cur = None
        for p in paths:
            if base is None:
                base = _artifact_get(baseline, p)
            if cur is None:
                cur = _artifact_get(current, p)
        if base is None or cur is None:
            continue
        # ratio gates need a positive baseline; the two-sided band gate
        # also accepts base == 0 (a legitimately tie-dominated margin
        # baseline must still catch margins EXPLODING — see below)
        if base <= 0 and direction != "band":
            continue
        if base < 0:
            continue
        tol = tolerance * weight
        if direction == "higher":
            # the cap only applies where a band floor <= 0 would be
            # meaningless; a lower-is-better ceiling past +100% is valid
            # (and deliberate: cold start's x2 weight under a generous
            # CI tolerance must stay the LOOSEST gate, not clip tight)
            tol = min(0.95, tol)
            band = [round(base * (1 - tol), 4), None]
            bad = cur < base * (1 - tol)
        elif direction == "band":
            # two-sided: the metric must stay NEAR the baseline —
            # either escape direction is a regression (placement
            # margin: collapse and explosion both mean the scoring
            # changed).  The band half-width scales on max(base, 0.05)
            # so a tie-dominated 0.0 margin baseline still gates a
            # margin explosion instead of degenerating to [0, 0].
            tol = min(0.95, tol)
            half = tol * max(base, 0.05)
            band = [round(base - half, 4), round(base + half, 4)]
            bad = cur < base - half or cur > base + half
        else:
            band = [None, round(base * (1 + tol), 4)]
            bad = cur > base * (1 + tol)
        checks.append({
            "name": name,
            "baseline": base,
            "current": cur,
            # a zero baseline (band-gated metrics admit it) has no
            # meaningful ratio; the band carries the verdict
            "ratio": round(cur / base, 4) if base > 0 else None,
            "direction": direction,
            "band": band,
            "regression": bad,
        })
        if bad:
            regressions.append(name)
    phases = {}
    base_ph = _artifact_path(baseline, "detail.phases")
    cur_ph = _artifact_path(current, "detail.phases")
    if not isinstance(base_ph, dict) or not isinstance(cur_ph, dict):
        base_ph = cur_ph = None
    if base_ph and cur_ph:
        for k in sorted(set(base_ph) & set(cur_ph)):
            b, c = base_ph[k], cur_ph[k]
            if not isinstance(b, (int, float)) or not isinstance(
                c, (int, float)
            ):
                continue
            bad = (
                b > 0
                and c > b * (1 + 2 * tolerance)
                and c - b > _PHASE_ABS_FLOOR_S
            )
            phases[k] = {
                "baseline": b,
                "current": c,
                "ratio": round(c / b, 4) if b > 0 else None,
                "regression": bad,
            }
            if bad:
                regressions.append(f"phase:{k}")
    return {
        "tolerance": tolerance,
        "checks": checks,
        "phases": phases,
        "regressions": regressions,
        "baseline_metric": baseline.get("metric"),
        "current_metric": current.get("metric"),
    }


def _emit_perf_delta(args, delta: dict, baseline_path: str,
                     current_desc: str):
    """Write the delta report + stderr summary; returns (exit code,
    report) — 1 on any regression, the gate contract.  The ONE report
    dict serves both --perf-delta-out and the emitted JSON line, so the
    two can never disagree."""
    report = {
        "metric": "perf_delta",
        "value": 0.0 if delta["regressions"] else 1.0,
        "unit": "bool",
        "detail": {
            "baseline": baseline_path,
            "current": current_desc,
            **delta,
        },
    }
    if args.perf_delta_out:
        with open(args.perf_delta_out, "w") as f:
            json.dump(report, f, indent=1)
    for c in delta["checks"]:
        sys.stderr.write(
            "bench: perf-delta %-22s base=%-10g cur=%-10g ratio=%s%s\n"
            % (c["name"], c["baseline"], c["current"],
               "%.3f" % c["ratio"] if c["ratio"] is not None else "n/a",
               "  REGRESSION" if c["regression"] else "")
        )
    if delta["regressions"]:
        sys.stderr.write(
            f"bench: perf-delta REGRESSION on {delta['regressions']} "
            f"(tolerance {delta['tolerance']})\n"
        )
    else:
        sys.stderr.write(
            f"bench: perf-delta clean vs {baseline_path} "
            f"({len(delta['checks'])} checks, tolerance "
            f"{delta['tolerance']})\n"
        )
    return (1 if delta["regressions"] else 0), report


def run_baseline_compare(args) -> None:
    """Offline gate: `--baseline OLD --compare-to NEW` compares two
    artifacts without running anything (the CI perf-delta step; also the
    self-compare acceptance — an artifact vs itself must exit 0).  Emits
    the delta report as the run's one JSON line."""
    try:
        baseline = load_artifact(args.baseline)
        current = load_artifact(args.compare_to)
    except (OSError, ValueError) as e:
        _emit(_error_line("baseline-load", e))
        sys.exit(2)
    delta = compare_artifacts(baseline, current, args.baseline_tolerance)
    code, report = _emit_perf_delta(
        args, delta, args.baseline, args.compare_to
    )
    _emit(report)
    sys.exit(code)


def run_replay(args) -> None:
    """--replay <ledger>: offline bit-identity gate.  Reconstructs every
    recorded cycle's snapshot (codec delta chain), re-executes it through
    a freshly built engine (the recorded config from the ledger header),
    and compares winners bit-for-bit — the determinism contract the
    offline weight-tuning loop (ROADMAP item 4) builds on.  Emits exactly
    one JSON line; exits 1 on any mismatch."""
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    from kubernetes_tpu.runtime.autoscaler import (
        replay_actuations, sniff_actuation_ledger,
    )
    from kubernetes_tpu.runtime.ledger import replay

    if sniff_actuation_ledger(args.replay):
        # autoscaler actuation JSONL (not a binary cycle ledger): re-run
        # the pure decide() over every recorded (plan, state) and compare
        # canonical JSON — the actuation-side half of the offline gate
        t0 = time.monotonic()
        try:
            out = replay_actuations(args.replay)
        except Exception as e:  # noqa: BLE001 — the JSON line must emit
            _emit({
                "metric": "actuation_replay_bit_identical",
                "value": 0.0, "unit": "bool",
                "detail": {"error": f"{type(e).__name__}: {e}",
                           "ledger": args.replay},
            })
            sys.exit(1)
        out["seconds"] = round(time.monotonic() - t0, 3)
        out["ledger"] = args.replay
        _emit({
            "metric": "actuation_replay_bit_identical",
            "value": 1.0 if out["verified"] else 0.0,
            "unit": "bool",
            "detail": out,
        })
        sys.exit(0 if out["verified"] else 1)

    t0 = time.monotonic()
    try:
        out = replay(args.replay, engine=args.replay_engine)
    except Exception as e:  # noqa: BLE001 — the JSON line must emit
        _emit({
            "metric": "ledger_replay_bit_identical",
            "value": 0.0,
            "unit": "bool",
            "detail": {"error": f"{type(e).__name__}: {e}",
                       "ledger": args.replay},
        })
        sys.exit(1)
    out["seconds"] = round(time.monotonic() - t0, 3)
    out["ledger"] = args.replay
    _emit({
        "metric": "ledger_replay_bit_identical",
        "value": 1.0 if out["bit_identical"] else 0.0,
        "unit": "bool",
        "detail": out,
    })
    if not out["bit_identical"]:
        sys.exit(1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=5000)
    ap.add_argument("--pods", type=int, default=10000)
    ap.add_argument("--batch", type=int, default=2048)
    ap.add_argument("--existing", type=int, default=0,
                    help="pods already running before the measured run "
                    "(scheduler_bench_test.go's {0,1000}-existing matrix "
                    "dimension)")
    ap.add_argument(
        "--workload",
        choices=("plain", "node-affinity", "pod-affinity",
                 "pod-anti-affinity"),
        default="plain",
        help="scheduler_bench_test.go matrix variant; every workload "
        "honors --engine (both engines carry in-batch affinity state)",
    )
    ap.add_argument(
        "--engine", choices=("speculative", "sequential"), default="speculative",
        help="speculative = parallel placement + conflict repair (fast path); "
        "sequential = exact one-at-a-time commit semantics",
    )
    ap.add_argument("--warmup", type=int, default=2,
                    help="warmup batches (compile + first-fetch setup)")
    ap.add_argument("--density", action="store_true",
                    help="sustained-density mode: live control plane, "
                    "hollow nodes, pods arriving with churn, per-interval "
                    "pods/s (ref scheduler_perf 30k-pod config; use "
                    "--nodes 1000 --pods 30000)")
    ap.add_argument("--density-interval", type=float, default=5.0,
                    help="per-interval throughput bucket seconds")
    ap.add_argument("--density-churn", type=float, default=0.1,
                    help="fraction of scheduled pods deleted + replaced")
    ap.add_argument("--density-arrival-rate", type=float, default=None,
                    help="paced pod arrival (pods/s) instead of deep-queue "
                    "waves: below saturation this measures the true per-pod "
                    "latency distribution vs the <=5s e2e SLO")
    ap.add_argument("--overload", action="store_true",
                    help="overload scenario: measure saturated throughput, "
                    "then offer --overload-factor x that rate against a "
                    "bounded shedding queue with adaptive batching; "
                    "reports goodput, shed rate, p99, recovery")
    ap.add_argument("--overload-factor", type=float, default=2.0,
                    help="offered load as a multiple of measured saturated "
                    "throughput")
    ap.add_argument("--overload-duration", type=float, default=10.0,
                    help="sustained storm window seconds (pod count capped "
                    "at 200k)")
    ap.add_argument("--tiered", action="store_true",
                    help="latency-tier scenario: saturating bulk backlog + "
                    "paced express arrivals through the two-lane "
                    "scheduler; reports per-tier p50/p99, bulk throughput "
                    "ratio vs single-lane, and a compile-inclusive "
                    "cold_start_seconds (the compile-cache figure)")
    ap.add_argument("--megacycle", action="store_true",
                    help="megacycle mode (ISSUE 12): sweep "
                    "megacycleBatches K = 1, 2, 4, ... through the live "
                    "path — pods/s + host seconds per pod per K, with "
                    "every K's placements pinned identical to K=1")
    ap.add_argument("--megacycle-max", type=int, default=8,
                    help="deepest K the --megacycle sweep (and the "
                    "default report's scaled-down megacycle stage, "
                    "capped at 4 there) reaches")
    ap.add_argument("--autoscale", action="store_true",
                    help="capacity-planning what-if scenario (ISSUE 15):"
                    " class-compressed binpack of a duplicate-heavy "
                    "backlog over a candidate-shape catalog — banks the "
                    "compressed-vs-per-pod solve speedup (bins-needed "
                    "identity asserted), the catalog sweep rate, and "
                    "the sharded shape-axis identity leg")
    ap.add_argument("--autoscale-pods", type=int, default=50000,
                    help="backlog size for --autoscale (the BASELINE "
                    "fifth config's 50k)")
    ap.add_argument("--autoscale-classes", type=int, default=256,
                    help="distinct request classes in the --autoscale "
                    "backlog (duplicate-heavy: pods/classes is the "
                    "scan-axis compression)")
    ap.add_argument("--autoscale-shapes", type=int, default=2048,
                    help="candidate shapes the compressed sweep "
                    "evaluates (10000 = the full BASELINE config; the "
                    "default is CPU-budget-scaled)")
    ap.add_argument("--autoscale-ref-shapes", type=int, default=4,
                    help="shape slice the per-pod reference leg times "
                    "(it is ~pods/classes slower per shape)")
    ap.add_argument("--autoscale-bins", type=int, default=2048,
                    help="max bins per shape lane (must cover the "
                    "backlog's node demand for a shape to report ok)")
    ap.add_argument("--autoscale-live", action="store_true",
                    help="guarded autoscaler actuation campaign (ISSUE "
                    "19): the diurnal-breathe scenario with the LIVE "
                    "controller enacting the capacity plan (grows AND "
                    "shrinks, zero lost pods/violations, actuation "
                    "ledger replayed bit-identically), plus the "
                    "plan-oscillation flap guard and the stuck-drain "
                    "rollback chaos legs")
    ap.add_argument("--autoscale-live-pods", type=int, default=160,
                    help="arrivals in the --autoscale-live breathe "
                    "trace (rate is pods/20 so the diurnal span stays "
                    "~20s whatever the size)")
    ap.add_argument("--autoscale-ledger-out", default=None,
                    help="where --autoscale-live records the JSONL "
                    "actuation ledger (default: a temp dir; the leg "
                    "replays it inline either way; bench.py --replay "
                    "<path> re-verifies it offline)")
    ap.add_argument("--replicas", type=int, default=0,
                    help="replica mode (ISSUE 14): sweep N = 1, 2, ... "
                    "queue-sharded scheduler replicas through the live "
                    "path — pods/s + optimistic conflict rate per N, "
                    "plus a multi-tenant storm asserting no tenant "
                    "starves and no popped pod is lost; 0 = off (the "
                    "default report still runs a scaled-down N=2 stage)")
    ap.add_argument(
        "--scenario", default=None,
        choices=["drain", "zone", "diurnal", "trace", "autoscale"],
        help="trace-driven lifecycle campaign (runtime/scenario.py) "
             "against the live scheduler: a synthetic (or --scenario-trace "
             "file) arrival trace replayed under a virtual clock with the "
             "named chaos composed mid-trace — rolling drain, zone outage, "
             "diurnal load swing — scored by the invariant checker (zero "
             "lost pods, zero violations) plus displaced-reschedule p99 / "
             "goodput-during-event / time-to-drain; --ledger-out records "
             "the window for --replay re-verification")
    ap.add_argument("--scenario-pods", type=int, default=600,
                    help="arrivals in the scenario trace")
    ap.add_argument("--scenario-nodes", type=int, default=24,
                    help="cluster size for the scenario")
    ap.add_argument("--scenario-rate", type=float, default=120.0,
                    help="mean arrival rate, pods per virtual second")
    ap.add_argument("--scenario-compression", type=float, default=1.0,
                    help="virtual seconds per wall second (60 replays an "
                         "hour-long trace in a minute)")
    ap.add_argument("--scenario-seed", type=int, default=0,
                    help="seed for the synthetic trace AND the chaos rng")
    ap.add_argument("--scenario-trace", default=None,
                    help="external trace file (CSV/JSON, Alibaba/Google "
                         "column aliases) for --scenario trace")
    ap.add_argument("--sharded", action="store_true",
                    help="multi-chip live-path scenario (ISSUE 9): the "
                    "same pod stream through the real Scheduler single-"
                    "chip and sharded over --shard-devices, pinning "
                    "bit-identical placements at --sharded-nodes scale, "
                    "plus a --sharded-encode-nodes sharded encode-fits "
                    "check (per-device resident bytes).  On cpu the "
                    "child forces the virtual-device count itself")
    ap.add_argument("--shard-devices", type=int, default=None,
                    help="devices to shard the node axis across (pow2; "
                    "config shardDevices; default: the --mesh-shape "
                    "total, else 8)")
    ap.add_argument("--mesh-shape", default=None,
                    help="mesh topology: 'N' (1D node mesh) or 'OxI' "
                    "(e.g. '2x4', two-level dcn x ici; config meshShape)")
    ap.add_argument("--sharded-nodes", type=int, default=20000,
                    help="fleet size for the sharded-vs-single-chip live "
                    "identity run")
    ap.add_argument("--sharded-encode-nodes", type=int, default=50000,
                    help="fleet size for the sharded encode-fits check "
                    "(each device holds 1/S of every node tensor)")
    ap.add_argument("--tier-deadline", type=float, default=0.08,
                    help="tiered scenario's bulk cycle_deadline_s (the "
                    "express-p99 lever: an express pod waits out at most "
                    "the bulk cycle in flight); must exceed the "
                    "platform's fixed per-cycle host cost or AIMD pins "
                    "to the floor width")
    ap.add_argument("--lock-timeout", type=float, default=300.0, help="seconds")
    ap.add_argument("--init-timeout", type=float, default=600.0,
                    help="seconds before a hung backend init fails the single "
                    "TPU attempt.  All 12 recorded r02/r03 failures were init "
                    "timeouts at 180s — a cold tunnel can need many minutes")
    ap.add_argument("--watchdog", type=float, default=1500.0,
                    help="hard whole-run deadline; sized INSIDE the driver's "
                    "observed ~35-40min outer window (r04 post-mortem: the "
                    "3000s default planned against the wrong deadline and "
                    "the driver killed the bench before any JSON line)")
    ap.add_argument("--cpu-budget", type=float, default=900.0,
                    help="phase-1 cap: the CPU number is banked first")
    ap.add_argument("--tpu-min-budget", type=float, default=420.0,
                    help="skip the TPU attempt when less than this remains "
                    "(compile cache makes a warm attempt ~5-7min)")
    ap.add_argument(
        "--trace-out", default=None,
        help="write the run's scheduling-cycle spans (the flight "
        "recorder ring) as Chrome trace-event JSON here — loadable in "
        "Perfetto / chrome://tracing.  In orchestrated mode the child "
        "that measured writes it (a TPU attempt overwrites the CPU "
        "phase's file, so the artifact matches the emitted number)",
    )
    ap.add_argument(
        "--ledger-out", default=None,
        help="record the live-path stage's scheduling cycles to this "
        "decision-ledger file (runtime/ledger.py): every cycle's inputs "
        "(snapshot delta, encoded batch, rotation base) and winners, "
        "replayable with --replay.  In orchestrated mode the child that "
        "measured writes it, next to the --trace-out artifact",
    )
    ap.add_argument(
        "--cluster-out", default=None,
        help="write the run's cluster-telemetry time series (the "
        "/debug/cluster payload: utilization/fragmentation/imbalance/"
        "occupancy samples, HBM + compile facts, SLO burn rates) as "
        "JSON here — the artifact CI uploads next to the Chrome trace "
        "and the decision ledger",
    )
    ap.add_argument(
        "--quality-out", default=None,
        help="write the run's placement-quality payload (the "
        "/debug/quality body: winner margins, feasible counts, FFD-"
        "counterfactual regret, drift-detector state and per-cycle "
        "samples) as JSON here — the artifact CI uploads next to the "
        "trace/ledger/cluster files",
    )
    ap.add_argument(
        "--timeline-out", default=None,
        help="write the run's metrics timeline (the /debug/timeline "
        "payload: every registered metric family sampled per interval, "
        "typed event annotations, anomaly firings) as JSONL here, plus "
        "a dependency-free static HTML report at <path>.html — the "
        "longitudinal artifact CI uploads next to the trace/ledger/"
        "cluster files",
    )
    ap.add_argument(
        "--replay", default=None, metavar="LEDGER",
        help="replay a recorded decision ledger: reconstruct each "
        "cycle's snapshot, re-execute it through a freshly built engine "
        "and assert bit-identical winners; emits one JSON line and "
        "exits non-zero on any mismatch",
    )
    ap.add_argument(
        "--replay-engine", default=None,
        choices=("sequential", "speculative"),
        help="engine to replay through.  Default: the recorded one, "
        "which must reproduce the recorded winners bit-for-bit; "
        "CROSS-engine replay is a comparison tool (the engines match "
        "semantics, but argmax-tie rotation can pick different "
        "winners on tie-heavy workloads)",
    )
    ap.add_argument(
        "--baseline", default=None, metavar="ARTIFACT",
        help="perf-regression gate: load a prior bench artifact (raw "
        "one-line form or the driver's BENCH_rNN.json wrapper) and "
        "compare the tracked figures — pods/s, p99, phase breakdown, "
        "overlap efficiency, cold start — against this run's result "
        "(or --compare-to, offline); writes --perf-delta-out and exits "
        "non-zero on an out-of-band regression",
    )
    ap.add_argument(
        "--compare-to", default=None, metavar="ARTIFACT",
        help="with --baseline: compare this artifact instead of running "
        "the bench (the CI perf-delta step; a self-compare exits 0)",
    )
    ap.add_argument(
        "--baseline-tolerance", type=float, default=0.2,
        help="base tolerance band for --baseline (default 0.2 = 20%%); "
        "per-metric weights scale it (p99 x1.5, cold start x2), phases "
        "use 2x plus a 0.5s absolute floor.  CI runs generous bands on "
        "shared runners; trajectory checks run tight",
    )
    ap.add_argument(
        "--perf-delta-out", default=None,
        help="write the --baseline delta report JSON here (CI uploads "
        "it next to the trace/ledger/cluster artifacts)",
    )
    ap.add_argument(
        "--platform",
        default=None,
        help="force a jax platform (e.g. cpu); default = environment (TPU)",
    )
    args = ap.parse_args()

    if args.compare_to and not args.baseline:
        ap.error("--compare-to requires --baseline")

    explicit_shard_cfg = (
        args.mesh_shape or args.shard_devices is not None
    )
    if args.mesh_shape:
        # --mesh-shape alone implies its total; a malformed shape or a
        # count/shape conflict fails fast here with the friendly message,
        # before any leg runs or child spawns
        from kubernetes_tpu.parallel.mesh import mesh_total

        try:
            total = mesh_total(args.mesh_shape, 0)
        except ValueError as e:
            ap.error(str(e))
        if args.shard_devices is None:
            args.shard_devices = total
        elif total != args.shard_devices:
            ap.error(f"--shard-devices {args.shard_devices} != "
                     f"--mesh-shape {args.mesh_shape!r} total {total}")
    elif args.shard_devices is None:
        args.shard_devices = 8  # no jax import on default runs
    if args.sharded and args.shard_devices < 2:
        ap.error("--sharded needs --shard-devices >= 2 (0 = single-chip "
                 "is the config default, not a comparable sharded leg)")
    if explicit_shard_cfg and args.shard_devices >= 2:
        # pow2/<=512 validation belongs at parse time too: build_mesh
        # would only reject the count AFTER the single-chip leg drained
        # (or, on a default run, after the sharded stage spawned a
        # grandchild that argparse-exits with no JSON line)
        from kubernetes_tpu.parallel.mesh import validate_device_count

        try:
            validate_device_count(args.shard_devices)
        except ValueError as e:
            ap.error(str(e))

    if args.replay:
        run_replay(args)
    elif args.baseline and args.compare_to:
        run_baseline_compare(args)
    elif os.environ.get(_CHILD_ENV) == "1":
        run_child(args)
    else:
        orchestrate(args)
        if args.baseline:
            # live gate: the run's emitted artifact vs the prior one.
            # The result line already printed (the one-line contract),
            # so the delta rides --perf-delta-out + stderr; the exit
            # code is the gate
            try:
                baseline = load_artifact(args.baseline)
            except (OSError, ValueError) as e:
                sys.stderr.write(f"bench: --baseline load failed: {e}\n")
                sys.exit(2)
            if _EMIT_RESULT is None:
                sys.stderr.write(
                    "bench: --baseline: no result emitted to compare\n"
                )
                sys.exit(2)
            delta = compare_artifacts(
                baseline, _EMIT_RESULT, args.baseline_tolerance
            )
            code, _ = _emit_perf_delta(
                args, delta, args.baseline, "live-run"
            )
            sys.exit(code)


if __name__ == "__main__":
    main()
