"""Density benchmark: scheduler_perf analog on real TPU.

Reference harness: test/integration/scheduler_perf/scheduler_test.go — 100
nodes x 3k pods with an enforced minimum of 30 pods/s and a warning threshold
of 100 pods/s (scheduler_test.go:34-38).  The north star (BASELINE.json) is
>=10k pods/s on a 5k-node snapshot with full predicate parity, single v5e-1.

This benchmark builds a 5k-node cluster (20 deployments behind services, so
resource fit + spreading + zone blending + taints/selector paths are all
live), then schedules 10k pods through the sequential-commit device program in
batches, chaining device-resident cluster state between batches (requested /
nonzero / spread counts never leave HBM) while the host performs the
cache-commit bookkeeping for every placement.

Robustness (the axon tunnel to the single TPU chip can be wedged or leased
elsewhere): device access is serialized through a file lock, TPU backend-init
or compile failures trigger a fresh-interpreter retry (re-exec, since a failed
jax backend poisons the process), and after the retry budget the benchmark
falls back to CPU with the TPU error recorded in the JSON detail.  Exactly ONE
JSON line is always printed — even on total failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

_ATTEMPT_ENV = "KTPU_BENCH_ATTEMPT"
_TPU_ERROR_ENV = "KTPU_BENCH_TPU_ERROR"
_TPU_LOG_ENV = "KTPU_BENCH_TPU_LOG"  # JSON list of per-attempt failures
_DEADLINE_ENV = "KTPU_BENCH_DEADLINE"  # wall-clock; survives the re-exec
_LOCK_PATH = "/tmp/ktpu_device.lock"

import threading as _threading

_EMITTED = False
_EMIT_LOCK = _threading.Lock()


def _emit(result: dict) -> bool:
    """Exactly-one-JSON-line contract: the first caller prints, every later
    caller (e.g. the watchdog racing a just-finished run) no-ops."""
    global _EMITTED
    with _EMIT_LOCK:
        if _EMITTED:
            return False
        _EMITTED = True
        print(json.dumps(result))
        sys.stdout.flush()
        return True


def _attempt_log() -> list:
    """Per-attempt TPU failure history, accumulated across re-execs via an
    env var so the final JSON (success OR fallback) shows what each device
    attempt saw — the audit trail VERDICT r2 asked for."""
    try:
        return json.loads(os.environ.get(_TPU_LOG_ENV, "[]"))
    except ValueError:
        return []


def _log_attempt(attempt: int, err: BaseException) -> None:
    log = _attempt_log()
    log.append({
        "attempt": attempt,
        "t": round(time.time(), 1),
        "error": f"{type(err).__name__}: {err}"[:500],
    })
    os.environ[_TPU_LOG_ENV] = json.dumps(log)


def _error_line(stage: str, err: BaseException) -> dict:
    detail = {
        "error": f"{type(err).__name__}: {err}"[:2000],
        "stage": stage,
        "attempt": int(os.environ.get(_ATTEMPT_ENV, "0")),
    }
    if _attempt_log():
        detail["tpu_attempts"] = _attempt_log()
    return {
        "metric": "pods_scheduled_per_sec_5k_nodes",
        "value": 0.0,
        "unit": "pods/s",
        "vs_baseline": 0.0,
        "vs_floor": 0.0,
        "vs_north_star": 0.0,
        "detail": detail,
    }


_RETRYABLE = (
    "UNAVAILABLE",
    "DEADLINE",
    "INTERNAL",
    "RESOURCE_EXHAUSTED",
    "JaxRuntimeError",
    "XlaRuntimeError",
    "backend",
    "tunnel",
    "RPC",
    "timed out",
)


def _is_transient(err: BaseException) -> bool:
    """Only tunnel/backend failures warrant a fresh-process retry; a
    deterministic host-side bug should surface immediately."""
    s = f"{type(err).__name__}: {err}"
    if "not in the list of known backends" in s:
        return False  # plugin registration failure: permanent within this image
    return any(k in s for k in _RETRYABLE)


def _reexec(attempt: int, err: BaseException, max_attempts: int, backoff: float,
            init_timeout: float) -> None:
    """Retry in a fresh interpreter (a failed jax backend poisons this one).

    After the retry budget, re-exec once more with JAX_PLATFORMS=cpu so the
    run still yields a labeled number instead of nothing.
    """
    msg = f"{type(err).__name__}: {err}"[:1000]
    _log_attempt(attempt, err)
    # A TPU attempt only makes sense if the backoff + a full init budget +
    # slack for the timed run fits inside the remaining watchdog window;
    # otherwise the watchdog would kill the attempt mid-init and the driver
    # would get an error line instead of the CPU-fallback number.
    remaining = float(os.environ.get(_DEADLINE_ENV, "0")) - time.time()
    # cap: with long --retries budgets the uncapped 2**k curve would spend
    # the whole window sleeping instead of probing a recovering tunnel
    delay = min(backoff * (2 ** attempt), 600.0)
    on_cpu_already = os.environ.get("JAX_PLATFORMS", "") == "cpu"
    if (attempt < max_attempts and not on_cpu_already
            and remaining < delay + init_timeout + 240):
        sys.stderr.write(
            f"bench: {remaining:.0f}s left < one more TPU attempt "
            f"({delay:.0f}s backoff + {init_timeout:.0f}s init); "
            "skipping to cpu fallback\n")
        attempt = max_attempts  # fall through to the cpu branch below
    if attempt < max_attempts:
        # real spread: a wedged tunnel needs minutes, not back-to-back
        # re-inits (VERDICT r2)
        sys.stderr.write(
            f"bench: device attempt {attempt} failed ({msg}); "
            f"retrying in {delay:.0f}s\n")
        sys.stderr.flush()
        time.sleep(delay)
        os.environ[_ATTEMPT_ENV] = str(attempt + 1)
    elif os.environ.get("JAX_PLATFORMS", "") != "cpu":
        sys.stderr.write(f"bench: TPU retries exhausted ({msg}); falling back to cpu\n")
        sys.stderr.flush()
        os.environ[_ATTEMPT_ENV] = str(attempt + 1)
        os.environ[_TPU_ERROR_ENV] = msg
        os.environ["JAX_PLATFORMS"] = "cpu"
        # the fallback is the last resort: give it a FRESH watchdog budget
        # (a late CPU number beats a watchdog error line)
        os.environ.pop(_DEADLINE_ENV, None)
    else:
        _emit(_error_line("cpu-fallback", err))
        sys.exit(0)
    os.execv(sys.executable, [sys.executable] + sys.argv)


def _acquire_device_lock(timeout_s: float):
    """Serialize device processes: concurrent axon clients wedge the tunnel.

    Polls with LOCK_NB up to timeout_s so a wedged lock holder cannot make
    this process hang forever without printing its JSON line; returns None on
    timeout (caller emits a diagnostic line).
    """
    import fcntl

    f = open(_LOCK_PATH, "w")
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
            return f
        except OSError:
            if time.monotonic() >= deadline:
                f.close()
                return None
            time.sleep(2.0)


def run(args) -> dict:
    import jax

    from tests.fixtures import make_node, make_pod
    from kubernetes_tpu.codec import SnapshotEncoder
    from kubernetes_tpu.models.batched import (
        batch_has_pod_affinity,
        encode_batch_affinity,
        encode_batch_ports,
        make_sequential_scheduler,
    )
    from kubernetes_tpu.models.speculative import make_speculative_scheduler

    zone = "failure-domain.beta.kubernetes.io/zone"
    enc = SnapshotEncoder()
    t0 = time.monotonic()
    for i in range(args.nodes):
        enc.add_node(
            make_node(
                f"node-{i}",
                cpu="32",
                mem="256Gi",
                pods=110,
                labels={zone: f"zone-{i % 8}", "tier": "a" if i % 3 else "b"},
                taints=[{"key": "dedicated", "value": "x", "effect": "NoSchedule"}]
                if i % 50 == 0
                else [],
            )
        )
    n_deploy = 20
    for d in range(n_deploy):
        enc.add_spread_selector("default", {"app": f"dep-{d}"})
    t_nodes = time.monotonic() - t0

    def pending_pod(i):
        """One pending pod in the selected workload shape — the
        scheduler_bench_test.go:39-131 matrix: plain (BenchmarkScheduling),
        node-affinity, pod-affinity, pod-anti-affinity variants."""
        d = i % n_deploy
        if args.workload == "node-affinity":
            # BenchmarkSchedulingNodeAffinity: required In-match on a label
            return make_pod(
                f"pod-{i}", cpu="100m", mem="256Mi",
                labels={"app": f"dep-{d}"},
                affinity={"nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [{"matchExpressions": [
                            # selective: only the ~2/3 tier-a nodes match
                            {"key": "tier", "operator": "In",
                             "values": ["a"]}
                        ]}]}}},
                owner=("ReplicaSet", f"rs-{d}"),
            )
        if args.workload == "pod-affinity":
            # BenchmarkSchedulingPodAffinity: zone-level required affinity
            # to the workload's own label (co-locate with mates)
            return make_pod(
                f"pod-{i}", cpu="100m", mem="256Mi",
                labels={"app": f"dep-{d}"},
                affinity={"podAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [{
                        "labelSelector": {
                            "matchLabels": {"app": f"dep-{d}"}},
                        "topologyKey":
                            "failure-domain.beta.kubernetes.io/zone",
                    }]}},
                owner=("ReplicaSet", f"rs-{d}"),
            )
        if args.workload == "pod-anti-affinity":
            # BenchmarkSchedulingPodAntiAffinity: hostname-level required
            # anti-affinity (one per node per group)
            return make_pod(
                f"pod-{i}", cpu="100m", mem="256Mi",
                labels={"app": f"dep-{d}"},
                affinity={"podAntiAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [{
                        "labelSelector": {
                            "matchLabels": {"app": f"dep-{d}"}},
                        "topologyKey": "kubernetes.io/hostname",
                    }]}},
                owner=("ReplicaSet", f"rs-{d}"),
            )
        return make_pod(
            f"pod-{i}",
            cpu="100m",
            mem="256Mi",
            labels={"app": f"dep-{d}"},
            node_selector={"tier": "a"} if d % 4 == 0 else None,
            owner=("ReplicaSet", f"rs-{d}"),
        )

    # both engines carry in-batch affinity state (the speculative engine
    # batch-updates the scan's per-topology-pair extras between repair
    # rounds — VERDICT r3 #3), so every workload honors --engine
    engine = args.engine
    make_engine = (
        make_speculative_scheduler
        if engine == "speculative"
        else make_sequential_scheduler
    )
    fn = make_engine(
        unsched_taint_key=enc.interner.intern("node.kubernetes.io/unschedulable"),
        zone_key_id=enc.getzone_key,
    )

    # warmup/compile on one batch shape; device-put the snapshot ONCE —
    # the static leaves stay resident and chain through every batch (the
    # tunnel otherwise re-uploads ~70MB of label/taint/topology tensors
    # per call)
    def build_aff_state(pods):
        """In-batch affinity carry, identical for warmup and timed batches
        (aff_state toggles the jit variant: warm and timed MUST agree, and
        a tail batch must not retrace — build it whenever the workload
        carries pod affinity, whatever the batch size)."""
        if batch_has_pod_affinity(pods):
            return encode_batch_affinity(enc, pods)
        return None

    pods = [pending_pod(i) for i in range(args.batch)]
    warm_aff = build_aff_state(pods)
    batch = enc.encode_pods(pods)
    ports = encode_batch_ports(enc, pods)
    cluster = jax.device_put(enc.snapshot())
    warm = cluster
    for i in range(args.warmup):
        # chain the device state exactly like the timed loop (incl. the
        # in-batch affinity variant), and FETCH the result: on the
        # tunnel-attached TPU the first device->host copy after compile
        # pays a multi-second one-time setup cost (block_until_ready alone
        # does not surface it)
        hosts, warm = fn(warm, batch, ports, np.int32(i * args.batch),
                         aff_state=warm_aff)
        np.asarray(hosts)

    # timed run: chain device state, host does cache-commit bookkeeping.
    # Dispatch is async — batch k+1's encode+launch overlaps the fetch of
    # batch k's hosts, so the tunnel RTT and the host commit loop hide
    # behind device compute (spread counts for batch k+1 then lag one
    # batch, the same staleness the speculative engine already accepts
    # within a batch).
    import dataclasses

    row_names = {row: name for name, row in enc.node_rows.items()}
    scheduled = 0
    unschedulable = 0
    t0 = time.monotonic()
    state = cluster
    last = 0
    in_flight = None  # (pods, hosts_device)

    def commit(pods, hosts_dev):
        nonlocal scheduled, unschedulable
        tf = time.monotonic()
        hosts = np.asarray(hosts_dev)  # blocks on device compute + D2H copy
        tb = time.monotonic()
        phases["fetch"] += tb - tf
        for j, pod in enumerate(pods):
            r = int(hosts[j])
            if r < 0:
                unschedulable += 1
                continue
            committed = dataclasses.replace(
                pod, spec=dataclasses.replace(pod.spec, node_name=row_names[r])
            )
            enc.add_pod(committed)
            scheduled += 1
        phases["commit"] += time.monotonic() - tb

    # workload generation (the reference's RC create strategy, runners.go)
    # happens outside the measured window — the timed section is the
    # scheduler: encode -> device -> commit
    prebuilt = {}
    for start in range(0, args.pods, args.batch):
        n = min(args.batch, args.pods - start)
        pods = [pending_pod(start + j) for j in range(n)]
        if n < args.batch:  # pad the tail batch: same shape, no recompile
            pods += [pending_pod(start) for _ in range(args.batch - n)]
        prebuilt[start] = (n, pods)

    # "dispatch" is the async enqueue only; device compute + the D2H copy
    # surface in "fetch" (the np.asarray sync point); "commit" is pure host
    # bookkeeping
    # affinity workloads evaluate REQUIRED predicates against the encoder's
    # committed-pod pair tensors: batch k MUST be committed before batch
    # k+1 encodes, or placements go blind to the previous batch and violate
    # (anti-)affinity.  Plain workloads keep the overlap (only spread
    # SCORES go one batch stale there, which the engine already accepts).
    overlap_commit = args.workload in ("plain", "node-affinity")
    phases = {"encode": 0.0, "dispatch": 0.0, "fetch": 0.0, "commit": 0.0}
    for start in range(0, args.pods, args.batch):
        n, pods = prebuilt[start]
        if not overlap_commit and in_flight is not None:
            commit(*in_flight)
            in_flight = None
        tp = time.monotonic()
        # in-batch affinity carry (models/batched.py BatchAffinityState) so
        # co-batched mates see each other — built BEFORE encode_pods, as
        # the scheduler runtime does (novel topology keys must register
        # before the TP-wide tensors are cut)
        aff_state = build_aff_state(pods)
        batch = enc.encode_pods(pods)
        if n < args.batch:
            valid = np.array(batch.valid, bool)  # padded width, not args.batch
            valid[n:] = False
            batch = dataclasses.replace(batch, valid=valid)
        ports = encode_batch_ports(enc, pods)
        phases["encode"] += time.monotonic() - tp
        tp = time.monotonic()
        hosts, state = fn(state, batch, ports, np.int32(last),
                          aff_state=aff_state)
        if hasattr(hosts, "copy_to_host_async"):
            hosts.copy_to_host_async()
        phases["dispatch"] += time.monotonic() - tp
        last += n
        if in_flight is not None:
            commit(*in_flight)
        in_flight = (pods[:n], hosts)
    if in_flight is not None:
        commit(*in_flight)
    jax.block_until_ready(state.requested)
    dt = time.monotonic() - t0

    pods_per_s = scheduled / dt if dt > 0 else 0.0
    detail = {
        "nodes": args.nodes,
        "pods_scheduled": scheduled,
        "unschedulable": unschedulable,
        "batch": args.batch,
        "engine": engine,
        "workload": args.workload,
        "seconds": round(dt, 3),
        "node_encode_seconds": round(t_nodes, 3),
        "phases": {k: round(v, 3) for k, v in phases.items()},
        "device": str(jax.devices()[0]),
        "attempt": int(os.environ.get(_ATTEMPT_ENV, "0")),
    }
    if os.environ.get(_TPU_ERROR_ENV):
        detail["tpu_error"] = os.environ[_TPU_ERROR_ENV]
    if _attempt_log():
        detail["tpu_attempts"] = _attempt_log()
    return {
        "metric": "pods_scheduled_per_sec_5k_nodes",
        "value": round(pods_per_s, 1),
        "unit": "pods/s",
        # vs_baseline keeps the historical meaning (ratio to the reference's
        # 30 pods/s enforced floor, scheduler_test.go:34-38); the two explicit
        # fields keep it honest (VERDICT r3 #10): floor != target.
        "vs_baseline": round(pods_per_s / 30.0, 2),
        "vs_floor": round(pods_per_s / 30.0, 2),
        "vs_north_star": round(pods_per_s / 10000.0, 3),
        "detail": detail,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=5000)
    ap.add_argument("--pods", type=int, default=10000)
    ap.add_argument("--batch", type=int, default=2048)
    ap.add_argument(
        "--workload",
        choices=("plain", "node-affinity", "pod-affinity",
                 "pod-anti-affinity"),
        default="plain",
        help="scheduler_bench_test.go matrix variant; every workload "
        "honors --engine (both engines carry in-batch affinity state)",
    )
    ap.add_argument(
        "--engine", choices=("speculative", "sequential"), default="speculative",
        help="speculative = parallel placement + conflict repair (fast path); "
        "sequential = exact one-at-a-time commit semantics",
    )
    ap.add_argument("--warmup", type=int, default=2,
                    help="warmup batches (compile + first-fetch setup)")
    ap.add_argument("--retries", type=int, default=3, help="fresh-process TPU retries")
    ap.add_argument("--retry-backoff", type=float, default=45.0,
                    help="base seconds; attempt k sleeps "
                    "min(base * 2^k, 600)")
    ap.add_argument("--lock-timeout", type=float, default=600.0, help="seconds")
    ap.add_argument("--init-timeout", type=float, default=600.0,
                    help="seconds before a hung backend init counts as a "
                    "transient failure (re-exec retry).  All 12 recorded "
                    "r02/r03 failures were init timeouts at 180s — a cold "
                    "tunnel can need many minutes (VERDICT r3 #1b)")
    ap.add_argument("--watchdog", type=float, default=3000.0,
                    help="hard whole-run deadline; emits a diagnostic JSON "
                    "line and exits instead of hanging the driver")
    ap.add_argument(
        "--platform",
        default=None,
        help="force a jax platform (e.g. cpu); default = environment (TPU)",
    )
    args = ap.parse_args()

    attempt = int(os.environ.get(_ATTEMPT_ENV, "0"))
    on_cpu = args.platform == "cpu" or os.environ.get("JAX_PLATFORMS") == "cpu"
    cpu_cap = int(os.environ.get("KTPU_BENCH_CPU_BATCH_CAP", "2048"))
    if on_cpu and args.batch > cpu_cap:
        # r04 re-tune: after the group-level spread + zero-weight-skip
        # kernel cuts, CPU throughput rises monotonically to batch 2048
        # (512: ~960, 1024: ~1100, 2048: ~1170 pods/s) and falls at 4096
        # (extra repair rounds); 2048 matches the TPU sweet spot too
        args.batch = cpu_cap
    lock = None
    if not on_cpu:  # cpu runs don't touch the tunnel; no serialization needed
        lock = _acquire_device_lock(args.lock_timeout)
        if lock is None:
            _emit(
                _error_line(
                    "device-lock",
                    TimeoutError(
                        f"could not acquire {_LOCK_PATH} in {args.lock_timeout}s"
                    ),
                )
            )
            return
    # whole-run watchdog: a wedged tunnel can HANG (nanosleep, no error)
    # rather than fail — backend init and even mid-run transfers have no
    # timeout of their own.  The watchdog guarantees the driver always gets
    # one JSON line instead of an rc=124.
    import threading

    # the deadline is wall-clock in an env var so retry re-execs inherit the
    # REMAINING budget instead of restarting it (the driver's own timeout is
    # the thing this must stay inside)
    if _DEADLINE_ENV not in os.environ:
        os.environ[_DEADLINE_ENV] = str(time.time() + args.watchdog)
    remaining = float(os.environ[_DEADLINE_ENV]) - time.time()

    def _watchdog_fire():
        fired = _emit(_error_line(
            "watchdog",
            TimeoutError(
                f"no result within {args.watchdog}s (tunnel wedge?)"
            ),
        ))
        if fired:  # a completed run already emitted -> let it exit normally
            os._exit(2)

    if remaining <= 0:
        if not on_cpu:
            # budget can be eaten before jax is even imported (e.g. a long
            # device-lock poll in a re-exec'd child); no device is in use
            # yet, so the safe move is the cpu fallback with a fresh budget,
            # not a watchdog error line
            sys.stderr.write("bench: deadline spent before backend init; "
                             "going straight to cpu fallback\n")
            os.environ[_ATTEMPT_ENV] = str(attempt + 1)
            os.environ[_TPU_ERROR_ENV] = "deadline exhausted pre-init"
            os.environ["JAX_PLATFORMS"] = "cpu"
            os.environ.pop(_DEADLINE_ENV, None)
            if lock is not None:
                lock.close()
            os.execv(sys.executable, [sys.executable] + sys.argv)
        _watchdog_fire()
        return
    wd = threading.Timer(remaining, _watchdog_fire)
    wd.daemon = True
    wd.start()

    try:
        try:
            import jax

            if args.platform:
                jax.config.update("jax_platforms", args.platform)
            elif os.environ.get("JAX_PLATFORMS") == "cpu":
                # the cpu-fallback re-exec sets the env var, but the image's
                # sitecustomize overrides env at interpreter start — only an
                # in-process config update actually switches the backend
                jax.config.update("jax_platforms", "cpu")
            # persistent compile cache: the sequential-scan compile is minutes
            # through the axon tunnel; cache it across processes/rounds
            from kubernetes_tpu.utils.jaxenv import enable_compile_cache

            enable_compile_cache()
            # backend init in a worker thread: a wedged tunnel HANGS here
            # (hrtimer_nanosleep) instead of raising, so poll with a deadline
            # and treat a stuck init as transient (fresh-process retry)
            init_done: dict = {}

            def _init():
                try:
                    init_done["devices"] = jax.devices()
                    # pre-warm with a trivial kernel AND a fetch inside the
                    # same deadline: a tunnel that wedges at first USE (init
                    # succeeds, compute hangs) is caught here, not after the
                    # 5k-node encode; the fetch also pays the one-time D2H
                    # setup cost outside the timed window
                    import jax.numpy as jnp

                    probe = np.asarray(jnp.arange(8.0) * 2.0)
                    init_done["probe"] = float(probe[-1])
                except Exception as ie:  # noqa: BLE001
                    init_done["error"] = ie

            t_init = threading.Thread(target=_init, daemon=True)
            t_init.start()
            t_init.join(args.init_timeout)
            if t_init.is_alive():
                raise TimeoutError(
                    f"UNAVAILABLE: backend init exceeded {args.init_timeout}s"
                )
            if "error" in init_done:
                raise init_done["error"]
        except Exception as e:  # backend init failed (tunnel wedged / no lease)
            if args.platform or not _is_transient(e):
                _emit(_error_line("backend-init", e))
                return
            if lock is not None:
                lock.close()  # release before exec; the child re-acquires
            _reexec(attempt, e, args.retries, args.retry_backoff, args.init_timeout)
            return  # unreachable

        try:
            result = run(args)
        except Exception as e:  # compile/runtime failure mid-run
            if args.platform or not _is_transient(e):
                _emit(_error_line("run", e))
                return
            if lock is not None:
                lock.close()
            _reexec(attempt, e, args.retries, args.retry_backoff, args.init_timeout)
            return  # unreachable
        _emit(result)
    finally:
        if lock is not None:
            try:
                lock.close()
            except Exception:
                pass


if __name__ == "__main__":
    main()
