"""Density benchmark: scheduler_perf analog on real TPU.

Reference harness: test/integration/scheduler_perf/scheduler_test.go — 100
nodes x 3k pods with an enforced minimum of 30 pods/s and a warning threshold
of 100 pods/s (scheduler_test.go:34-38).  The north star (BASELINE.json) is
>=10k pods/s on a 5k-node snapshot with full predicate parity, single v5e-1.

This benchmark builds a 5k-node cluster (20 deployments behind services, so
resource fit + spreading + zone blending + taints/selector paths are all
live), then schedules 10k pods through the sequential-commit device program in
batches, chaining device-resident cluster state between batches (requested /
nonzero / spread counts never leave HBM) while the host performs the
cache-commit bookkeeping for every placement.

Prints ONE JSON line: pods scheduled per second, vs_baseline = value / 30
(the reference's enforced minimum).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, "/root/repo")

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=5000)
    ap.add_argument("--pods", type=int, default=10000)
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--warmup", type=int, default=1, help="warmup batches (compile)")
    ap.add_argument(
        "--platform",
        default=None,
        help="force a jax platform (e.g. cpu); default = environment (TPU)",
    )
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from tests.fixtures import make_node, make_pod
    from kubernetes_tpu.codec import SnapshotEncoder
    from kubernetes_tpu.models.batched import (
        encode_batch_ports,
        make_sequential_scheduler,
    )

    zone = "failure-domain.beta.kubernetes.io/zone"
    enc = SnapshotEncoder()
    t0 = time.monotonic()
    for i in range(args.nodes):
        enc.add_node(
            make_node(
                f"node-{i}",
                cpu="32",
                mem="256Gi",
                pods=110,
                labels={zone: f"zone-{i % 8}", "tier": "a" if i % 3 else "b"},
                taints=[{"key": "dedicated", "value": "x", "effect": "NoSchedule"}]
                if i % 50 == 0
                else [],
            )
        )
    n_deploy = 20
    for d in range(n_deploy):
        enc.add_spread_selector("default", {"app": f"dep-{d}"})
    t_nodes = time.monotonic() - t0

    def pending_pod(i):
        d = i % n_deploy
        return make_pod(
            f"pod-{i}",
            cpu="100m",
            mem="256Mi",
            labels={"app": f"dep-{d}"},
            node_selector={"tier": "a"} if d % 4 == 0 else None,
            owner=("ReplicaSet", f"rs-{d}"),
        )

    fn = make_sequential_scheduler(
        unsched_taint_key=enc.interner.intern("node.kubernetes.io/unschedulable"),
        zone_key_id=enc.zone_key,
    )

    # warmup/compile on one batch shape
    pods = [pending_pod(i) for i in range(args.batch)]
    batch = enc.encode_pods(pods)
    ports = encode_batch_ports(enc, pods, enc.dims.N)
    cluster = enc.snapshot()
    for _ in range(args.warmup):
        hosts, new_cluster = fn(cluster, batch, ports, np.int32(0))
        jax.block_until_ready(hosts)

    # timed run: chain device state, host does cache-commit bookkeeping
    import dataclasses

    row_names = {row: name for name, row in enc.node_rows.items()}
    scheduled = 0
    unschedulable = 0
    t0 = time.monotonic()
    state = cluster
    last = 0
    for start in range(0, args.pods, args.batch):
        pods = [pending_pod(start + j) for j in range(min(args.batch, args.pods - start))]
        batch = enc.encode_pods(pods)
        ports = encode_batch_ports(enc, pods, enc.dims.N)
        hosts, state = fn(state, batch, ports, np.int32(last))
        last += len(pods)
        hosts = np.asarray(hosts)
        # host-side cache commit (assume/confirm bookkeeping)
        for j, pod in enumerate(pods):
            r = int(hosts[j])
            if r < 0:
                unschedulable += 1
                continue
            committed = dataclasses.replace(
                pod, spec=dataclasses.replace(pod.spec, node_name=row_names[r])
            )
            enc.add_pod(committed)
            scheduled += 1
    jax.block_until_ready(state.requested)
    dt = time.monotonic() - t0

    pods_per_s = scheduled / dt if dt > 0 else 0.0
    result = {
        "metric": "pods_scheduled_per_sec_5k_nodes",
        "value": round(pods_per_s, 1),
        "unit": "pods/s",
        "vs_baseline": round(pods_per_s / 30.0, 2),
        "detail": {
            "nodes": args.nodes,
            "pods_scheduled": scheduled,
            "unschedulable": unschedulable,
            "batch": args.batch,
            "seconds": round(dt, 3),
            "node_encode_seconds": round(t_nodes, 3),
            "device": str(jax.devices()[0]),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
