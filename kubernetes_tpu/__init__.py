"""kubernetes_tpu — a TPU-native cluster-scheduling framework.

A from-scratch re-design of the capabilities of Kubernetes' kube-scheduler
(reference: Silveryfu/kubernetes, ~v1.15) for TPU hardware.  Instead of a
16-goroutine per-pod scan over nodes (ref pkg/scheduler/core/generic_scheduler.go:518),
cluster state is encoded as device-resident columnar tensors and the whole
Filter/Score pipeline runs as vmapped JAX/XLA kernels emitting a pods x nodes
feasibility mask and score matrix in a single launch.

Layer map (mirrors SURVEY.md section 1, re-designed TPU-first):

  api/        object model: Pod, Node, quantities, label selectors
              (ref staging/src/k8s.io/api + pkg/apis/core/types.go)
  codec/      tensor schema + snapshot encoder: the device mirror of
              NodeInfo / NodeInfoSnapshot (ref pkg/scheduler/nodeinfo/node_info.go:47-148,
              pkg/scheduler/internal/cache/interface.go:125-128)
  ops/        the compute kernels: predicates (Filter), priorities (Score),
              host selection (ref pkg/scheduler/algorithm/{predicates,priorities})
  models/     scheduling algorithms composed from ops: one-pod generic
              schedule, batched scan-commit, preemption, gang
              (ref pkg/scheduler/core/generic_scheduler.go)
  parallel/   device-mesh sharding of the node axis (pjit / shard_map / ICI
              collectives) — the TPU-native analog of the reference's
              goroutine parallelism and of multi-host scale-out
  runtime/    host-side control loop: scheduling queue, cache with
              assume/confirm/expire, event handlers, scheduleOne
              (ref pkg/scheduler/scheduler.go, internal/{queue,cache})
  extender/   the out-of-process seam: HTTP extender protocol server so a
              stock Go kube-scheduler can offload Filter/Score to this
              framework (ref pkg/scheduler/core/extender.go)
  cpuref/     pure-numpy golden implementation of every kernel, used by the
              parity test-suite (the analog of the reference's table-driven
              predicate/priority unit tests)
  utils/      tracing spans, metrics histograms, feature gates
"""

__version__ = "0.1.0"
