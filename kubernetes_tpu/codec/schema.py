"""Static tensor schema for device-resident cluster state.

Design (SURVEY.md section 7): cluster state is a columnar struct-of-arrays over
the node axis N, pending pods a struct-of-arrays over the batch axis B.  All
strings are interned int32 ids (codec/interner.py); all variable-length lists
are padded to static widths declared in `PadDims` so that a single jit
compilation serves every snapshot of the same padded shape.  Growing beyond a
pad width bumps the dim to the next power of two (one recompile, amortized to
zero — same trade XLA makes for any bucketed dynamic workload).

The mapping from the reference:
  NodeInfo (pkg/scheduler/nodeinfo/node_info.go:47-148)  -> rows of ClusterTensors
  NodeInfoSnapshot (internal/cache/interface.go:125-128) -> ClusterTensors + generation
  predicateMetadata topology-pair maps (algorithm/predicates/metadata.go:64-94)
      -> the [*, TP] topology-pair incidence tensors
  priorityMetadata selectors (algorithm/priorities/metadata.go)
      -> the spread-group count columns
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field, fields
from typing import Any, Dict, Optional

import jax
import numpy as np

PAD = -1  # universal padding id
WILDCARD = 0  # interner id of "" — wildcard IP for host ports

# A reserved pseudo-label key id representing the node-name field, used to
# fold NodeSelectorTerm.matchFields (metadata.name) into the same expression
# encoding as matchExpressions.  Interners reserve id 0 for ""; encoders
# intern this sentinel string first, so its id is always 1 (asserted there).
FIELD_NODE_NAME = "__field:metadata.name"
FIELD_NODE_NAME_ID = 1

# Taint effects (ref core/v1/types.go TaintEffect)
EFFECT_CODES = {"NoSchedule": 0, "PreferNoSchedule": 1, "NoExecute": 2}
# Toleration operators (ref core/v1/types.go TolerationOperator); empty
# operator defaults to Equal (toleration.go ToleratesTaint)
TOL_OP_CODES = {"Equal": 0, "": 0, "Exists": 1}
# Node-selector operators (ref core/v1/types.go NodeSelectorOperator)
SEL_OP_CODES = {"In": 0, "NotIn": 1, "Exists": 2, "DoesNotExist": 3, "Gt": 4, "Lt": 5}

# Resource columns. Fixed layout of the resource axis R; extended resources
# (device plugins etc.) occupy columns >= RES_EXT0.
# ref nodeinfo.Resource (node_info.go:139-148): MilliCPU, Memory,
# EphemeralStorage, AllowedPodNumber, ScalarResources.
RES_MILLICPU = 0
RES_MEMORY = 1
RES_EPHEMERAL = 2
RES_PODS = 3
RES_EXT0 = 4

# Predicate codes, in the reference's mandatory evaluation order
# (algorithm/predicates/predicates.go:142-151 predicatesOrdering).  The TPU
# path evaluates ALL of them in one launch; this order is used only to
# attribute the *first* failure reason for FitError parity
# (generic_scheduler.go podFitsOnNode short-circuit semantics).
PREDICATE_ORDER = (
    "CheckNodeCondition",
    "CheckNodeUnschedulable",
    "GeneralPredicates",      # = HostName + HostPorts + Resources + NodeSelector
    "PodFitsHost",
    "PodFitsHostPorts",
    "PodMatchNodeSelector",
    "PodFitsResources",
    "NoDiskConflict",
    "PodToleratesNodeTaints",
    "PodToleratesNodeNoExecuteTaints",
    "CheckNodeLabelPresence",
    "CheckServiceAffinity",
    "MaxEBSVolumeCount",
    "MaxGCEPDVolumeCount",
    "MaxCSIVolumeCount",
    "MaxAzureDiskVolumeCount",
    "MaxCinderVolumeCount",
    "CheckVolumeBinding",
    "NoVolumeZoneConflict",
    "CheckNodeMemoryPressure",
    "CheckNodePIDPressure",
    "CheckNodeDiskPressure",
    "MatchInterPodAffinity",
)
PRED_INDEX = {name: i for i, name in enumerate(PREDICATE_ORDER)}
NUM_PREDICATES = len(PREDICATE_ORDER)

# --- decision attribution (the explain/ledger axis) ---------------------
# The attribution launch collapses the per-plugin sub-masks into one
# first-failing-predicate code per (pod, node) in PREDICATE_ORDER — the
# reference's podFitsOnNode short-circuit attribution — plus one extra
# code for nodes every predicate passed but the extra mask vetoed (an
# extender filter verdict, a tensor Filter plugin, or a nominated-pod
# port/anti-affinity block).  The aggregate GeneralPredicates row never
# attributes: its constituents (host/ports/selector/resources) follow it
# in PREDICATE_ORDER and name the precise reason instead.
REASON_EXTENDER = NUM_PREDICATES
NUM_REASONS = NUM_PREDICATES + 1
REASON_EXTENDER_NAME = "ExtenderFilter"

# kubectl-describe-parity message per reason (the FitError reason strings
# of algorithm/predicates/error.go, phrased for the "N node(s) ..." event
# format); predicates without a bespoke string fall back to their name.
REASON_MESSAGES = {
    "CheckNodeCondition": "node(s) were not ready",
    "CheckNodeUnschedulable": "node(s) were unschedulable",
    "PodFitsHost": "node(s) didn't match the requested hostname",
    "PodFitsHostPorts": "node(s) didn't have free ports for the requested "
                        "pod ports",
    "PodMatchNodeSelector": "node(s) didn't match node selector",
    "PodFitsResources": "Insufficient resources",
    "NoDiskConflict": "node(s) had no available volume zone",
    "PodToleratesNodeTaints": "node(s) had taints that the pod didn't "
                              "tolerate",
    "PodToleratesNodeNoExecuteTaints": "node(s) had NoExecute taints that "
                                       "the pod didn't tolerate",
    "CheckVolumeBinding": "node(s) didn't find available persistent "
                          "volumes to bind",
    "NoVolumeZoneConflict": "node(s) had volume node affinity conflict",
    "CheckNodeMemoryPressure": "node(s) had memory pressure",
    "CheckNodePIDPressure": "node(s) had pid pressure",
    "CheckNodeDiskPressure": "node(s) had disk pressure",
    "MatchInterPodAffinity": "node(s) didn't match pod "
                             "affinity/anti-affinity",
    REASON_EXTENDER_NAME: "node(s) were filtered by an extender or plugin",
}


def reason_name(code: int) -> str:
    """Reason code (attribution counts axis) -> predicate/plugin name."""
    if 0 <= code < NUM_PREDICATES:
        return PREDICATE_ORDER[code]
    return REASON_EXTENDER_NAME


def reason_message(code: int) -> str:
    name = reason_name(code)
    return REASON_MESSAGES.get(name, f"node(s) failed {name}")

# Priority (score) functions.  The first eight are the default provider set
# (algorithmprovider/defaults/defaults.go defaultPriorities(): all weight 1;
# NodePreferAvoidPods weight 10000, register_priorities.go:87); the tail are
# registered-but-default-off functions selectable via Policy / providers /
# feature gates (MostRequested: ClusterAutoscalerProvider; NodeLabel +
# RequestedToCapacityRatio: policy arguments; ResourceLimits: the
# ResourceLimitsPriorityFunction feature gate).
PRIORITY_ORDER = (
    "SelectorSpreadPriority",
    "InterPodAffinityPriority",
    "LeastRequestedPriority",
    "BalancedResourceAllocation",
    "NodePreferAvoidPodsPriority",
    "NodeAffinityPriority",
    "TaintTolerationPriority",
    "ImageLocalityPriority",
    "MostRequestedPriority",
    "NodeLabelPriority",
    "RequestedToCapacityRatioPriority",
    "ResourceLimitsPriority",
)
PRIO_INDEX = {name: i for i, name in enumerate(PRIORITY_ORDER)}
NUM_PRIORITIES = len(PRIORITY_ORDER)
# attribution score-breakdown axis: every priority plugin plus one
# "Extra" slot for the extender-prioritize / tensor-Score contribution
SCORE_COMPONENTS = PRIORITY_ORDER + ("Extra",)
NUM_SCORE_COMPONENTS = len(SCORE_COMPONENTS)
DEFAULT_PRIORITY_WEIGHTS = np.array(
    [1.0, 1.0, 1.0, 1.0, 10000.0, 1.0, 1.0, 1.0, 0.0, 0.0, 0.0, 0.0],
    dtype=np.float32,
)

# Volume filter types for MaxVolumeCount predicates
# (predicates.go EBS/GCE/AzureDisk/Cinder VolumeFilterType + CSI)
VOL_EBS, VOL_GCE, VOL_CSI, VOL_AZURE, VOL_CINDER = 0, 1, 2, 3, 4
NUM_VOL_TYPES = 5


def _pow2(n: int, floor: int = 1) -> int:
    n = max(n, floor)
    return 1 << (n - 1).bit_length()


def aimd_pow2_widths(batch_size_min: int, batch_size: int) -> "list[int]":
    """The distinct pow2 ENCODE widths the AIMD batch sizer can visit while
    ramping from batch_size_min to batch_size: the additive-increase steps
    land on arbitrary integers, but encode_pods pads every batch to a pow2
    bucket, so these are exactly the XLA compile shapes the runtime pays.

    THE shared source for compile pre-warming — the scheduler's startup
    prewarm and bench.py's warmup sweep both import this, so the two can
    never drift (a width missing here is a mid-storm compile stall)."""
    lo = _pow2(max(1, batch_size_min))
    hi = _pow2(max(1, batch_size))
    # a floor above the cap (e.g. batch_size 8 with the default min 16)
    # still dispatches at the cap width — never return an empty ladder
    lo = min(lo, hi)
    out = []
    w = lo
    while w <= hi:
        out.append(w)
        w *= 2
    return out


@dataclass(frozen=True)
class PadDims:
    """Static pad widths.  Every field is a maximum-over-the-snapshot, rounded
    up to a power of two by `SnapshotEncoder.fit()`."""

    N: int = 8        # nodes (padded; `valid` masks the tail)
    B: int = 1        # pod batch
    R: int = 8        # resource columns (4 core + extended)
    L: int = 8        # labels per node
    T: int = 4        # taints per node
    P: int = 8        # occupied host-ports per node
    Q: int = 4        # host-ports per pod
    TT: int = 4       # tolerations per pod
    NS: int = 4       # plain nodeSelector (map) entries per pod
    S: int = 2        # required node-affinity terms per pod
    E: int = 4        # expressions per node-affinity term
    V: int = 4        # values per expression
    PS: int = 2       # preferred node-affinity terms per pod
    TP: int = 16      # topology-pair vocabulary size
    PT: int = 2       # required pod-affinity terms per pod
    AT: int = 2       # required pod-anti-affinity terms per pod
    G: int = 16       # spread-group vocabulary (services/RCs/RSs/SSs)
    GP: int = 4       # spread groups per pod
    I: int = 8        # images per node
    C: int = 4        # containers (images) per pod
    A: int = 2        # prefer-avoid owner uids per node
    DV: int = 4       # disk-conflict volume ids per pod
    DVN: int = 8      # disk-conflict volume ids per node
    VZ: int = 2       # volume zone-restriction terms per pod (bound PV labels)
    VB: int = 2       # volume binding-restriction terms per pod
    VT: int = NUM_VOL_TYPES  # attach-count filter columns (base types + one per
                      #   distinct CSI driver — csi_volume_predicate.go
                      #   counts and limits PER DRIVER)

    def bump(self, **kw: int) -> "PadDims":
        return dataclasses.replace(
            self, **{k: _pow2(v) for k, v in kw.items() if v > getattr(self, k)}
        )


def _dc_pytree(cls):
    """Register a plain dataclass of arrays as a jax pytree."""
    data = [f.name for f in fields(cls)]
    jax.tree_util.register_dataclass(cls, data_fields=data, meta_fields=[])
    return cls


@_dc_pytree
@dataclass
class ClusterTensors:
    """Struct-of-arrays cluster snapshot, node axis N.

    Dynamic fields (mutated by the on-device commit step of batched
    scheduling): requested, nonzero_req, port_used.
    Everything else is static per snapshot.
    """

    # -- resources (PodFitsResources, resource scores) --
    allocatable: Any        # f32[N, R]
    requested: Any          # f32[N, R]   (col RES_PODS counts pods)
    nonzero_req: Any        # f32[N, 2]   (milliCPU, memory) with nonzero defaults
    # -- node status / spec --
    valid: Any              # bool[N]     padding mask
    unschedulable: Any      # bool[N]     (.spec.unschedulable)
    not_ready: Any          # bool[N]     CheckNodeCondition (Ready!="True" | net unavailable)
    mem_pressure: Any       # bool[N]
    disk_pressure: Any      # bool[N]
    pid_pressure: Any       # bool[N]
    node_name_id: Any       # i32[N]
    # -- labels --
    label_keys: Any         # i32[N, L]  (PAD-filled)
    label_vals: Any         # i32[N, L]
    label_nums: Any         # f32[N, L]  numeric value of label (nan if not an int) for Gt/Lt
    # -- taints --
    taint_key: Any          # i32[N, T]
    taint_val: Any          # i32[N, T]
    taint_effect: Any       # i32[N, T]  (EFFECT_CODES, PAD)
    # -- host ports (occupied by existing pods) --
    port_pp: Any            # i32[N, P]  interned "proto/port" id, PAD empty
    port_ip: Any            # i32[N, P]  interned IP, WILDCARD = 0.0.0.0/""
    port_used: Any          # bool[N, P] slot occupancy
    # -- topology --
    topo_pairs: Any         # bool[N, TP] node belongs to topology pair tp
    #   (includes the synthetic GetZoneKey pair grouping nodes by region+zone)
    # -- spreading (SelectorSpread) --
    group_counts: Any       # f32[N, G]  zero-filled shape carrier (G = spread
                            #   groups); per-pod counts live in
                            #   PodBatch.spread_counts
    # -- inter-pod affinity state --
    pair_topo_key: Any      # i32[TP]    topology-key id of each pair (PAD unused)
    # -- images (ImageLocality) --
    image_id: Any           # i32[N, I]
    image_size: Any         # f32[N, I]  bytes
    # -- NodePreferAvoidPods --
    avoid_owner: Any        # i32[N, A]  controller-owner uid ids to avoid
    # -- volumes --
    vol_counts: Any         # f32[N, VT] attached unique volumes per filter
                            #   column (5 base types + per-CSI-driver)
    vol_limits: Any         # f32[N, VT] per-node attachable limits
    disk_vol_ids: Any       # i32[N, DVN] interned volume ids in use (NoDiskConflict)

    @property
    def n_nodes(self) -> int:
        return self.allocatable.shape[0]


@_dc_pytree
@dataclass
class PodBatch:
    """Struct-of-arrays pending-pod batch, batch axis B.

    The per-pod topology-pair tensors (forbidden_pairs, aff_term_pairs, ...)
    are the tensorization of predicateMetadata's topologyPairsMaps
    (algorithm/predicates/metadata.go:64-94): host code matches label
    selectors against existing pods (vectorized numpy) and the device reduces
    pair incidence per node.
    """

    valid: Any              # bool[B]
    req: Any                # f32[B, R]  resource request (col RES_PODS = 1)
    nonzero_req: Any        # f32[B, 2]
    limits2: Any            # f32[B, 2]  (milliCPU, memory) limits (ResourceLimitsPriority)
    priority: Any           # i32[B]
    best_effort: Any        # bool[B]    QoS BestEffort (no requests/limits at all)
    ns_id: Any              # i32[B]     namespace id
    owner_uid: Any          # i32[B]     controller owner uid id (PAD none)
    node_name_req: Any      # i32[B]     .spec.nodeName / PAD (PodFitsHost)
    # host ports requested
    port_pp: Any            # i32[B, Q]
    port_ip: Any            # i32[B, Q]
    port_valid: Any         # bool[B, Q]
    # tolerations
    tol_key: Any            # i32[B, TT]  (PAD slot invalid; WILDCARD key = all keys)
    tol_op: Any             # i32[B, TT]  TOL_OP_CODES
    tol_val: Any            # i32[B, TT]
    tol_effect: Any         # i32[B, TT]  EFFECT_CODES; PAD = matches all effects
    tol_valid: Any          # bool[B, TT]
    # plain nodeSelector map (AND of key==value)
    ns_keys: Any            # i32[B, NS]
    ns_vals: Any            # i32[B, NS]
    ns_valid: Any           # bool[B, NS]
    # required node affinity: OR over S terms of AND over E exprs
    has_req_affinity: Any   # bool[B]
    term_valid: Any         # bool[B, S]
    expr_key: Any           # i32[B, S, E]
    expr_op: Any            # i32[B, S, E]  SEL_OP_CODES
    expr_vals: Any          # i32[B, S, E, V]
    expr_nval: Any          # i32[B, S, E]  number of valid values
    expr_num: Any           # f32[B, S, E]  numeric value for Gt/Lt (nan if invalid)
    expr_valid: Any         # bool[B, S, E]
    # preferred node affinity (score): PS terms, each AND of E exprs, weighted
    pref_weight: Any        # f32[B, PS]
    pref_term_valid: Any    # bool[B, PS]
    pref_expr_key: Any      # i32[B, PS, E]
    pref_expr_op: Any       # i32[B, PS, E]
    pref_expr_vals: Any     # i32[B, PS, E, V]
    pref_expr_nval: Any     # i32[B, PS, E]
    pref_expr_num: Any      # f32[B, PS, E]
    pref_expr_valid: Any    # bool[B, PS, E]
    # inter-pod affinity (precomputed pair incidence)
    forbidden_pairs: Any    # bool[B, TP] existing anti-affinity violated here
    aff_term_pairs: Any     # bool[B, PT, TP] pairs satisfying required affinity term
    aff_term_valid: Any     # bool[B, PT]
    aff_term_self: Any      # bool[B, PT] term's selector matches the pod itself
    aff_term_topo_key: Any  # i32[B, PT]  topology key id of the term
    anti_term_pairs: Any    # bool[B, AT, TP] pairs violating pod's own anti-affinity
    anti_term_valid: Any    # bool[B, AT]
    anti_term_topo_key: Any # i32[B, AT]
    anti_term_self: Any     # bool[B, AT] term matches the pod itself (self-anti-affinity)
    pref_pair_weights: Any  # f32[B, TP] combined soft affinity weight per pair
    # spreading
    group_ids: Any          # i32[B, GP]
    group_valid: Any        # bool[B, GP]
    spread_counts: Any      # f32[B, N] existing pods per node matching ALL of
                            #   the pod's spread selectors (countMatchingPods
                            #   AND semantics, selector_spreading.go:165-187);
                            #   [B, 1] placeholder for spread-lean batches
    # CheckServiceAffinity (predicates.go:993-1067), policy-configured:
    svc_aff_fixed: Any      # i32[B, SA] value id the pod's nodeSelector pins
                            #   for configured label j (PAD = not pinned)
    svc_aff_d0: Any         # i32[B] node row of the FIRST same-ns pod whose
                            #   labels superset-match the pod's (-1 = none)
    svc_aff_d1: Any         # i32[B] first such pod on a DIFFERENT node than
                            #   d0 (-1 = none) — FilterOutPods(evaluated
                            #   node) reduces to d0-unless-thats-you-else-d1
    # images
    image_ids: Any          # i32[B, C]  (PAD empty)
    image_bytes: Any        # f32[B, C]  total size if known (0 otherwise)
    # volumes
    new_vol_counts: Any     # f32[B, VT] unique volumes the pod
                            #   references (per attach-count filter type)
    vol_overlap: Any        # f32[B, VT, N] of those, how many are already
                            #   mounted per node (subtract: they attach
                            #   nothing new); [B, VT, 1] lean placeholder
    disk_vol_ids: Any       # i32[B, DV] exclusive-use volume ids (NoDiskConflict)
    # volume topology restrictions, as hostname-pair sets (exact: the host
    # evaluates PV zone labels / nodeAffinity / binding candidates against
    # every node and emits the allowed-node pair set per volume)
    vol_zone_pairs: Any     # bool[B, VZ, TP] NoVolumeZoneConflict terms
    vol_zone_valid: Any     # bool[B, VZ]
    vol_bind_pairs: Any     # bool[B, VB, TP] CheckVolumeBinding terms
    vol_bind_valid: Any     # bool[B, VB]
    vol_fail_all: Any       # bool[B] unbound PVC with no candidate PV / missing PVC

    @property
    def n_pods(self) -> int:
        return self.req.shape[0]


@dataclass(frozen=True)
class FilterConfig:
    """Static knobs threaded through the kernels (part of the jit cache key).

    max_vols mirrors DefaultMaxEBSVolumes=39/aws, GCE/Azure=16
    (predicates.go:109-115); hard_pod_affinity_weight ref
    apis/config/types.go HardPodAffinitySymmetricWeight default 1.
    `enabled` selects the active predicate set (None = all): the analog of
    the provider/Policy predicate registry (factory/plugins.go); disabled
    predicates neither filter nor appear in failure attribution.
    """

    max_vols: tuple = (39.0, 16.0, 1e9, 16.0, 1e9)
    hard_pod_affinity_weight: float = 1.0
    # CheckNodeLabelPresence / CheckServiceAffinity are policy-configured and
    # default-off (defaults.go defaultPredicates has neither); encoded as
    # always-pass unless configured.
    label_presence_keys: tuple = ()
    label_presence_present: bool = True
    # CheckServiceAffinity homogeneity labels (interned key ids; the Policy
    # serviceAffinity argument, predicates.go:993-1067)
    service_affinity_labels: tuple = ()
    enabled: Optional[tuple] = None  # tuple of predicate names, or None=all


@dataclass(frozen=True)
class ScoreConfig:
    """Static arguments for the policy-driven priorities.

    label_prefs: ((key_id, presence, weight), ...) — NodeLabelPriority
    (priorities/node_label.go): presence=True scores 10 when the label
    exists.  rtc_shape: ((utilization%, score), ...) ascending — the
    RequestedToCapacityRatio piecewise-linear curve
    (priorities/requested_to_capacity_ratio.go).
    """

    label_prefs: tuple = ()
    rtc_shape: tuple = ((0.0, 10.0), (100.0, 0.0))
