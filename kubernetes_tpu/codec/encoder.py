"""Incremental snapshot encoder: API objects -> device tensors.

The TPU-native redesign of the scheduler cache's snapshot path
(ref pkg/scheduler/internal/cache/cache.go:210-222 UpdateNodeInfoSnapshot):
node and pod mutations update numpy arenas in place (the analog of the
generation-numbered NodeInfo list), and `snapshot()` emits a `ClusterTensors`
copy tagged with a generation counter.  String work (label interning, selector
matching against existing pods) happens here, vectorized over numpy columns,
so the device kernels see only integer ids — the tensorization of
predicateMetadata's topologyPairsMaps (algorithm/predicates/metadata.go:64-94).

Inter-pod-affinity bookkeeping: existing pods' (anti-)affinity terms are
grouped by signature (selector, namespaces, topologyKey, kind, weight) — pods
stamped out by one controller share one group — and each group maintains a
per-topology-pair member count.  Encoding an incoming pod evaluates each
group's selector against that one pod (cheap) instead of scanning every
existing pod (the same asymptotic trick as the reference's metadata maps).
"""

from __future__ import annotations

import contextlib
import dataclasses
from collections import Counter
from dataclasses import dataclass, field
from itertools import chain
from typing import Dict, List, NamedTuple, Optional, Sequence, Set, Tuple

import numpy as np

from kubernetes_tpu.api import labels as klabels
from kubernetes_tpu.api.types import (
    DEFAULT_MEMORY_REQUEST,
    DEFAULT_MILLI_CPU_REQUEST,
    Node,
    Pod,
    PodAffinityTerm,
    RESOURCE_CPU,
    RESOURCE_EPHEMERAL_STORAGE,
    RESOURCE_MEMORY,
    RESOURCE_PODS,
)
from kubernetes_tpu.codec.interner import Interner
from kubernetes_tpu.codec.schema import (
    ClusterTensors,
    EFFECT_CODES,
    FIELD_NODE_NAME,
    NUM_VOL_TYPES,
    PAD,
    PadDims,
    PodBatch,
    RES_EPHEMERAL,
    RES_EXT0,
    RES_MEMORY,
    RES_MILLICPU,
    RES_PODS,
    SEL_OP_CODES,
    TOL_OP_CODES,
    VOL_AZURE,
    VOL_CINDER,
    VOL_CSI,
    VOL_EBS,
    VOL_GCE,
    WILDCARD,
    _pow2,
)

def normalized_image(name: str) -> str:
    """priorities/image_locality.go:99-109 normalizedImageName: append the
    default tag when the reference has none after the last path segment."""
    if name.rfind(":") <= name.rfind("/"):
        return name + ":latest"
    return name


HOSTNAME_KEY = "kubernetes.io/hostname"
ZONE_KEY = "failure-domain.beta.kubernetes.io/zone"
REGION_KEY = "failure-domain.beta.kubernetes.io/region"
# synthetic topology key for GetZoneKey (pkg/util/node/node.go:126-143):
# the SelectorSpread zone reduce groups nodes by region+zone CONCAT, not the
# zone label alone.  The NUL prefix keeps it out of any user label vocabulary.
GETZONE_KEY = "\x00getzonekey"

# kinds of existing-pod affinity term groups
K_ANTI_REQ, K_ANTI_PREF, K_AFF_REQ, K_AFF_PREF = 0, 1, 2, 3

# attachable-volumes-* allocatable key -> attach-count column (ref the
# AttachVolumeLimit feature's allocatable keys); the one mapping both the
# per-node and bulk ingest paths consume (_vol_limit_col)
_VOL_LIMIT_COLS = {
    "attachable-volumes-aws-ebs": VOL_EBS,
    "attachable-volumes-gce-pd": VOL_GCE,
    "attachable-volumes-azure-disk": VOL_AZURE,
}


def _sel_requirements(raw_selector: Optional[dict]) -> Optional[klabels.Selector]:
    return klabels.selector_from_label_selector(raw_selector)


class PodsArena(NamedTuple):
    """Assigned-pod arena view for preemption what-ifs (see pods_snapshot)."""

    node: np.ndarray        # i32[M] node row (-1 unassigned)
    priority: np.ndarray    # i32[M]
    req: np.ndarray         # f32[M, R]
    nonzero: np.ndarray     # f32[M, 2]
    valid: np.ndarray       # bool[M] assigned & alive
    start: np.ndarray       # f64[M] status.startTime epoch seconds
    keys: List              # [M] (ns, name) or None
    uids: List              # [M] metadata.uid or ""


@dataclass
class _TermGroup:
    """One distinct (anti-)affinity term shared by many existing pods."""

    kind: int
    topo_key_id: int
    namespaces: frozenset            # namespace strings
    selector: klabels.Selector
    weight: float
    pair_counts: np.ndarray          # f32[TP-cap] matching member pods per topology pair
    members: int = 0


@dataclass
class _PodRecord:
    key: Tuple[str, str]
    labels: Dict[str, str]
    ns: str
    node_row: int                    # -1 unassigned
    m: int                           # pod-arena index
    req: np.ndarray                  # f32[R-cap]
    nonzero: np.ndarray              # f32[2]
    ports: List[Tuple[int, int]]     # (proto/port id, ip id)
    disk_vols: List[int]
    vol_counts: np.ndarray           # f32[VT] (unique per pod)
    cnt_vols: list = None            # per-type unique volume id sets
    priority: int = 0
    group_refs: List[Tuple] = field(default_factory=list)  # term-group signatures
    pod: Optional[Pod] = None        # the full object (victim deletion, host
                                     # what-if verification, PDB matching)
    start_time: float = 0.0          # status.startTime (preemption criterion 5)
    uid: str = ""                    # metadata.uid (extender MetaPod victims)


class SnapshotEncoder:
    """API objects -> numpy arenas -> incremental ClusterTensors snapshots.

    Dirty-row contract (the ONE place it is documented; the snapshot,
    transfer, and mutation paths all reference this):

      * Every mutation marks what it touched: node events mark their row
        via _mark_node_dirty (EVERY per-row field of that row may have
        changed); pod commits mark only their node row via _mark_pod_dirty
        (only the aggregate fields — requested/nonzero/ports/vols — may
        have changed).  Batch ingest (add_pods / add_nodes) marks once per
        batch.  Wholesale rewrites — arena retile/regrow, pad-dim or
        vocabulary growth, topology-key backfill, _reapply_pods_to_arena —
        call _mark_all_dirty instead: content correctness NEVER depends on
        a mutation site remembering to mark precisely, because imprecise
        sites must escalate to the full flag.

      * snapshot() consumes the marks: dirty rows re-encode copy-on-write
        per field, untouched fields return the SAME array object as the
        previous snapshot (consumers detect no-change by identity, so
        snapshot arrays are immutable by contract).  A set _snap_dirty_all
        forces a from-scratch rebuild of every field.

      * take_dirty_rows() is the transfer handshake: it accumulates the
        rows applied by snapshots since the previous take (plus pending
        marks) so the device cache can scatter-update exactly those rows.
        The accumulator survives snapshots that are consumed WITHOUT a
        device update (e.g. gang launches) — rows keep accumulating until
        taken.  Any full rebuild (arena regrow, _mark_all_dirty) poisons
        the accumulator: the next take returns None, meaning "resync every
        field; row identity may have moved".  Single-consumer: exactly one
        DeviceSnapshotCache may take; a second taker would starve the
        first of its rows.
    """

    def __init__(self, dims: Optional[PadDims] = None,
                 hard_pod_affinity_weight: float = 1.0):
        self.dims = dims or PadDims()
        self.interner = Interner()
        self.generation = 0
        # transient pod-batch pad-width override (the express lane's small
        # pre-compiled shape): when set, encode_pods and the batch helpers
        # pad to pow2(len(pods), override) WITHOUT growing the sticky
        # dims.B floor, so a 64-wide express batch keeps its own compiled
        # program next to the bulk lane's full-width one.  Set through
        # batch_width() only (restores on exit).
        self._batch_width: Optional[int] = None
        # HardPodAffinitySymmetricWeight (ref apis/config/types.go, default 1)
        self.hard_pod_affinity_weight = hard_pod_affinity_weight

        self._field_node_name = self.interner.intern(FIELD_NODE_NAME)
        assert self._field_node_name == 1, "FIELD_NODE_NAME_ID contract"
        self.hostname_key = self.interner.intern(HOSTNAME_KEY)
        self.zone_key = self.interner.intern(ZONE_KEY)
        self.region_key = self.interner.intern(REGION_KEY)
        self.getzone_key = self.interner.intern(GETZONE_KEY)
        # zone_key_id=5 default in ops/models signatures rides this order
        assert self.getzone_key == 5, "GETZONE_KEY intern-order contract"
        self.topo_keys: Set[int] = {self.hostname_key, self.zone_key, self.region_key}

        # topology-pair vocabulary
        self._pair_vocab: Dict[Tuple[int, int], int] = {}
        self._pair_topo_key: List[int] = []

        # resource columns beyond the core four
        self._res_cols: Dict[str, int] = {}

        # ---- node arena ----
        self._cap_n = self.dims.N
        self.node_rows: Dict[str, int] = {}
        self._row_node: Dict[int, Node] = {}
        self._free_rows: List[int] = []
        self._next_row = 0
        self._row_pods: Dict[int, Set[Tuple[str, str]]] = {}
        self._node_ports: Dict[int, Counter] = {}
        self._node_disk_vols: Dict[int, Counter] = {}
        # attachable-count volumes: per row per TYPE id refcounts, plus the
        # reverse id -> rows index (per-(pod,node) overlap tensors)
        self._node_cnt_vols: Dict[int, list] = {}
        self._cnt_vol_rows: list = [dict() for _ in range(self.dims.VT)]
        # per-CSI-driver attach-count columns (csi_volume_predicate.go
        # counts/limits PER DRIVER): driver name -> column >= NUM_VOL_TYPES
        self._vol_cols: Dict[str, int] = {}
        self._alloc_node_arena()

        # ---- existing-pod arena (vectorized selector matching) ----
        self._cap_m = 64
        self.pods: Dict[Tuple[str, str], _PodRecord] = {}
        self._free_m: List[int] = []
        self._next_m = 0
        self.p_alive = np.zeros(self._cap_m, dtype=bool)
        self.p_ns = np.full(self._cap_m, PAD, dtype=np.int32)
        self.p_node = np.full(self._cap_m, PAD, dtype=np.int32)
        self._label_cols: Dict[int, np.ndarray] = {}

        # affinity term groups of existing pods
        self.term_groups: Dict[Tuple, _TermGroup] = {}

        # spreading groups (services / RCs / RSs / StatefulSets)
        # ref priorities/selector_spreading.go getSelectors
        self._spread: List[Tuple[str, klabels.Selector]] = []  # (namespace, selector)
        self._spread_kinds: List[str] = []  # "Service" | "ReplicaSet" | ...
        # raw (namespace, matchLabels) of Service entries — the cpuref
        # what-if (preemption victim verification) needs dict selectors
        self._service_selectors: List[Tuple[str, Dict[str, str]]] = []

        # CheckServiceAffinity label keys (interned), empty = predicate off
        self.service_affinity_keys: List[int] = []

        # image -> number of nodes having it (for ImageLocality spread scaling,
        # ref priorities/image_locality.go scaledImageScore)
        self._image_nodes: Counter = Counter()

        # storage objects (PV/PVC/StorageClass), consumed by the volume
        # predicates and the volume binder (ref pkg/scheduler/volumebinder)
        self.pvs: Dict[str, object] = {}
        self.pvcs: Dict[Tuple[str, str], object] = {}
        self.storage_classes: Dict[str, object] = {}

        # template-row cache for encode_pods: pods stamped out by one
        # controller share an identical spec, so their encoded batch row is
        # identical.  Keyed by content; invalidated when the spread-group
        # registry or pad dims change.  Pods with (anti-)affinity are never
        # cached (their pair tensors depend on current cluster state).
        self._pod_row_cache: Dict[Tuple, Dict[str, np.ndarray]] = {}
        self._pod_cache_token: Tuple = ()
        self._req_memo: Dict[Tuple, Tuple[np.ndarray, np.ndarray]] = {}
        self._empty_vcounts: np.ndarray | None = None

        # ---- per-namespace usage/quota columns (ISSUE 14) ----
        # tenant axis for placement fairness: committed (node-assigned)
        # requests aggregated per namespace, maintained incrementally on
        # the same add/remove seams as a_requested, plus an optional
        # per-namespace quota row (+inf = unbounded).  The conflict
        # reconciler's dominant-resource-fairness tiebreak and quota
        # admission read these under the cache lock; they ride the
        # encoder (not ClusterTensors) so engine pytree shapes — and
        # therefore every compiled executable — are untouched.
        self.ns_rows: Dict[str, int] = {}
        self._cap_t = 8
        self.a_ns_usage = np.zeros((self._cap_t, self.dims.R), np.float32)
        self.a_ns_quota = np.full(
            (self._cap_t, self.dims.R), np.inf, np.float32
        )
        self.ns_quota_set = False  # any finite quota configured?

        # ---- incremental snapshot bookkeeping ----
        # see the class docstring for the dirty-row contract
        self._snap: Optional[ClusterTensors] = None
        self._snap_dirty_all = True
        self._dirty_node_rows: Set[int] = set()
        self._dirty_pod_rows: Set[int] = set()
        self._gc_dirty = True          # group_counts (pod/spread dependent)
        self._snap_pairs_len = -1      # pair_topo_key rebuild detector
        # rows refreshed by snapshots since the last take_dirty_rows();
        # None = a full rebuild happened (consumer must full-sync)
        self._snap_rows_acc: Optional[Set[int]] = set()

    # ---------------------------------------------------- dirty bookkeeping

    def _mark_all_dirty(self) -> None:
        self._snap_dirty_all = True
        self._gc_dirty = True

    def _mark_node_dirty(self, row: int) -> None:
        self._dirty_node_rows.add(row)

    def _mark_pod_dirty(self, row: int) -> None:
        if row >= 0:
            self._dirty_pod_rows.add(row)

    def take_dirty_rows(self) -> Optional[np.ndarray]:
        """Rows whose snapshot content may differ from what the transfer
        consumer last uploaded; None after a full rebuild.  Extra rows are
        harmless (the scatter rewrites identical values).  Semantics —
        accumulation across snapshots, rebuild poisoning, the single-
        consumer rule — are in the class docstring's dirty-row contract."""
        if self._snap_rows_acc is None or self._snap_dirty_all:
            self._snap_rows_acc = set()
            return None
        rows = self._snap_rows_acc | self._dirty_node_rows | self._dirty_pod_rows
        self._snap_rows_acc = set()
        return np.asarray(sorted(rows), np.int32)

    # ------------------------------- per-namespace usage/quota (ISSUE 14)

    def _ns_row(self, ns: str) -> int:
        """Tenant index of a namespace, allocating (and growing the
        usage/quota arrays, quota inf-padded) on first sight."""
        t = self.ns_rows.get(ns)
        if t is None:
            t = len(self.ns_rows)
            self.ns_rows[ns] = t
            while t >= self._cap_t:
                self._cap_t *= 2
                for attr, fill in (
                    ("a_ns_usage", 0.0), ("a_ns_quota", np.inf)
                ):
                    src = getattr(self, attr)
                    new = np.full(
                        (self._cap_t, src.shape[1]), fill, np.float32
                    )
                    new[: src.shape[0]] = src
                    setattr(self, attr, new)
        return t

    def set_namespace_quota(self, ns: str, limits: Dict) -> None:
        """Per-namespace placement quota: committed usage beyond this is
        vetoed by the conflict reconciler at commit (ISSUE 14).  `limits`
        maps resource name -> quantity (string, number, or Quantity);
        unnamed resources stay unbounded (+inf)."""
        from kubernetes_tpu.api.resource import parse_quantity

        t = self._ns_row(ns)
        row = np.full(self.dims.R, np.inf, np.float32)
        for name, q in (limits or {}).items():
            q = parse_quantity(q)
            col = self._res_col(name)
            # _res_col may have grown dims.R (and the ns arrays with it,
            # via the shared R-grow path): refresh the row buffer
            if row.shape[0] != self.dims.R:
                old = row
                row = np.full(self.dims.R, np.inf, np.float32)
                row[: old.shape[0]] = old
            row[col] = q.milli if name == RESOURCE_CPU else float(q)
        self.a_ns_quota[t, : row.shape[0]] = row
        self.ns_quota_set = bool(
            np.isfinite(self.a_ns_quota[: len(self.ns_rows)]).any()
        )

    def namespace_usage(self) -> Dict[str, dict]:
        """{namespace: {"usage": [R floats], "quota": [R floats|None]}} —
        the /debug/replicas tenant table (host-side, O(T*R))."""
        out: Dict[str, dict] = {}
        for ns, t in self.ns_rows.items():
            quota = self.a_ns_quota[t]
            out[ns] = {
                "usage": [round(float(x), 3) for x in self.a_ns_usage[t]],
                "quota": [
                    (round(float(x), 3) if np.isfinite(x) else None)
                    for x in quota
                ],
            }
        return out

    def capacity_totals(self) -> np.ndarray:
        """f32[R] cluster-wide allocatable totals over valid rows — the
        dominant-resource-fairness denominator."""
        return self.a_allocatable[self.a_valid].sum(axis=0)

    # ------------------------------------------------------------------ arena

    def _alloc_node_arena(self) -> None:
        d, n = self.dims, self._cap_n
        f32 = np.float32
        i32 = np.int32
        self.a_allocatable = np.zeros((n, d.R), f32)
        self.a_requested = np.zeros((n, d.R), f32)
        self.a_nonzero = np.zeros((n, 2), f32)
        self.a_valid = np.zeros(n, bool)
        self.a_unsched = np.zeros(n, bool)
        self.a_notready = np.zeros(n, bool)
        self.a_mempress = np.zeros(n, bool)
        self.a_diskpress = np.zeros(n, bool)
        self.a_pidpress = np.zeros(n, bool)
        self.a_name = np.full(n, PAD, i32)
        self.a_lkeys = np.full((n, d.L), PAD, i32)
        self.a_lvals = np.full((n, d.L), PAD, i32)
        self.a_lnums = np.full((n, d.L), np.nan, f32)
        self.a_tkey = np.full((n, d.T), PAD, i32)
        self.a_tval = np.full((n, d.T), PAD, i32)
        self.a_teff = np.full((n, d.T), PAD, i32)
        self.a_ppp = np.full((n, d.P), PAD, i32)
        self.a_pip = np.full((n, d.P), PAD, i32)
        self.a_pused = np.zeros((n, d.P), bool)
        self.a_topo = np.zeros((n, self.dims.TP), bool)
        self.a_img_id = np.full((n, d.I), PAD, i32)
        self.a_img_sz = np.zeros((n, d.I), f32)
        self.a_avoid = np.full((n, d.A), PAD, i32)
        self.a_volcnt = np.zeros((n, d.VT), f32)
        self.a_vollim = np.full((n, d.VT), np.inf, f32)
        self.a_dvol = np.full((n, d.DVN), PAD, i32)
        # per-topo-key per-node value/pair id (host-side helper columns)
        self._node_pair_id: Dict[int, np.ndarray] = {
            k: np.full(n, PAD, i32) for k in self.topo_keys
        }

    def _grow_nodes(self) -> None:
        old = self._cap_n
        # Double while small (few recompiles on the way up), then grow in
        # 25% steps rounded to a 512 lane-friendly multiple: at 5k nodes a
        # pow2 pad would run the whole pods x nodes grid at 8192 wide — 60%
        # wasted MXU/VPU work per launch — where 5120 wastes 2.4%.
        if old < 2048:
            new = old * 2
        else:
            new = -(-(old + old // 4) // 512) * 512
        self.dims = dataclasses.replace(self.dims, N=new)
        self._regrow_node_arena(old)

    def ensure_node_capacity(self, n: int) -> None:
        """Grow the node arena (normal growth-schedule steps) until it
        holds >= n rows.  The sharded Scheduler floors the arena at the
        mesh device count at startup: every width on the growth schedule
        (pow2 up to 2048, then 512-multiples) divides over a pow2 mesh of
        <= 512 devices once the arena is at least that wide, so the
        divisibility check in DeviceSnapshotCache.update can never fire
        mid-run from a fleet that stayed small.  Growth also continues
        until the width DIVIDES n: a non-standard PadDims.N base reaches
        a divisible width in a few doublings (12 -> 24 divides 8; each
        doubling adds a factor of two, and every 512-multiple above 2048
        divides any pow2 mesh of <= 512).  Bounded so a pathological
        (non-pow2) n is rejected as a config error HERE, at startup — not
        mid-cycle, where it would read as a device fault and flap the
        breaker into permanent CPU degradation."""
        if n <= 0:
            return
        # dry-run the growth schedule first: a pathological shard count is
        # rejected without allocating a single oversized arena
        target = self._cap_n
        for _ in range(64):
            if target >= n and target % n == 0:
                break
            target = (target * 2 if target < 2048
                      else -(-(target + target // 4) // 512) * 512)
        else:
            raise ValueError(
                f"node arena growth never reaches a width divisible over "
                f"{n} shards from base {self._cap_n} (use a pow2 shard "
                "count <= 512)"
            )
        while self._cap_n < target:
            self._grow_nodes()

    def _regrow_node_arena(self, old_cap: int) -> None:
        """Retile the node arena (bigger N or wider pad dims), preserving the
        overlapping region."""
        names = [a for a in dir(self) if a.startswith("a_")]
        keep = {a: getattr(self, a) for a in names}
        keep_pair = self._node_pair_id
        self._cap_n = self.dims.N
        self._alloc_node_arena()
        for a, src in keep.items():
            new = getattr(self, a)
            sl = tuple(slice(0, min(s, ns)) for s, ns in zip(src.shape, new.shape))
            new[sl] = src[sl]
        for k, col in keep_pair.items():
            if k in self._node_pair_id:
                n = min(old_cap, self._cap_n)
                self._node_pair_id[k][:n] = col[:n]
        self._mark_all_dirty()

    def _grow_pods(self) -> None:
        old = self._cap_m
        self._cap_m *= 2
        for name in ("p_alive", "p_ns", "p_node"):
            src = getattr(self, name)
            new = np.full(self._cap_m, False if src.dtype == bool else PAD, src.dtype)
            new[:old] = src
            setattr(self, name, new)
        for k, col in list(self._label_cols.items()):
            new = np.full(self._cap_m, PAD, np.int32)
            new[:old] = col
            self._label_cols[k] = new

    def _grow_pairs(self, min_tp: Optional[int] = None) -> None:
        """Topology-pair vocabulary outgrew TP: double it.  With `min_tp`,
        replay the doubling schedule to the final width in ONE realloc
        (the bulk ingest path registers a whole batch's pairs first, then
        resizes once; the per-miss caller doubles step by step)."""
        tp = self.dims.TP
        if min_tp is None:
            tp *= 2
        else:
            while tp < min_tp:
                tp *= 2
            if tp == self.dims.TP:
                return
        self.dims = dataclasses.replace(self.dims, TP=tp)
        new = np.zeros((self._cap_n, self.dims.TP), bool)
        new[:, : self.a_topo.shape[1]] = self.a_topo
        self.a_topo = new
        for g in self.term_groups.values():
            nc = np.zeros(self.dims.TP, np.float32)
            nc[: g.pair_counts.shape[0]] = g.pair_counts
            g.pair_counts = nc
        self._mark_all_dirty()

    # ------------------------------------------------------------- vocabulary

    def _pair_id(self, key_id: int, val_id: int) -> int:
        pid = self._pair_vocab.get((key_id, val_id))
        if pid is None:
            pid = len(self._pair_topo_key)
            self._pair_vocab[(key_id, val_id)] = pid
            self._pair_topo_key.append(key_id)
            if pid >= self.dims.TP:
                self._grow_pairs()
        return pid

    def register_topology_key(self, key: str) -> int:
        """Ensure `key` is tracked as a topology key; backfill existing nodes."""
        kid = self.interner.intern(key)
        if kid in self.topo_keys:
            return kid
        self.topo_keys.add(kid)
        self._mark_all_dirty()  # backfill below rewrites a_topo across rows
        self._node_pair_id[kid] = np.full(self._cap_n, PAD, np.int32)
        for name, row in self.node_rows.items():
            node = self._row_node[row]
            val = node.labels.get(key)
            if val is not None:
                pid = self._pair_id(kid, self.interner.intern(val))
                self.a_topo[row, pid] = True
                self._node_pair_id[kid][row] = pid
        return kid

    def _vol_limit_col(self, name: str) -> Optional[int]:
        """Attach-limit column for an attachable-volumes-* allocatable key,
        or None when the key constrains nothing (malformed empty-driver
        keys — the golden ignores them too).  May register a per-driver
        column (and so grow VT)."""
        col = _VOL_LIMIT_COLS.get(name)
        if col is None and name.startswith("attachable-volumes-csi-"):
            driver = name[len("attachable-volumes-csi-"):]
            col = self._vol_col(driver) if driver else None
        elif col is None and "csi" in name:
            col = VOL_CSI
        return col

    @staticmethod
    def _cond_bits(cond: Dict[str, str]) -> Tuple[bool, bool, bool, bool]:
        """(not_ready, mem_pressure, disk_pressure, pid_pressure) from a
        status.conditions map — CheckNodeConditionPredicate semantics
        (predicates.go: Ready!=True, OutOfDisk==True, or
        NetworkUnavailable==True fail the node).  The one decode both the
        per-node and bulk ingest paths consume."""
        return (
            cond.get("Ready", "True") != "True"
            or cond.get("OutOfDisk", "False") == "True"
            or cond.get("NetworkUnavailable", "False") == "True",
            cond.get("MemoryPressure", "False") == "True",
            cond.get("DiskPressure", "False") == "True",
            cond.get("PIDPressure", "False") == "True",
        )

    def _res_col(self, name: str) -> int:
        if name == RESOURCE_CPU:
            return RES_MILLICPU
        if name == RESOURCE_MEMORY:
            return RES_MEMORY
        if name == RESOURCE_EPHEMERAL_STORAGE:
            return RES_EPHEMERAL
        if name == RESOURCE_PODS:
            return RES_PODS
        col = self._res_cols.get(name)
        if col is None:
            col = RES_EXT0 + len(self._res_cols)
            if col >= self.dims.R:
                old = self.dims.R
                self.dims = dataclasses.replace(self.dims, R=_pow2(col + 1))
                for attr in ("a_allocatable", "a_requested"):
                    src = getattr(self, attr)
                    new = np.zeros((self._cap_n, self.dims.R), np.float32)
                    new[:, :old] = src
                    setattr(self, attr, new)
                # the tenant usage/quota columns track dims.R in lockstep
                # (quota pads +inf = the new resource starts unbounded)
                for attr, fill in (
                    ("a_ns_usage", 0.0), ("a_ns_quota", np.inf)
                ):
                    src = getattr(self, attr)
                    new = np.full(
                        (self._cap_t, self.dims.R), fill, np.float32
                    )
                    new[:, :old] = src
                    setattr(self, attr, new)
                for rec in self.pods.values():
                    r = np.zeros(self.dims.R, np.float32)
                    r[:old] = rec.req
                    rec.req = r
                self._mark_all_dirty()
            self._res_cols[name] = col
        return col

    def _req_vector(self, requests: Dict) -> np.ndarray:
        v = np.zeros(self.dims.R, np.float32)
        for name, q in requests.items():
            col = self._res_col(name)
            v[col] = q.milli if name == RESOURCE_CPU else float(q)
        v[RES_PODS] = 1.0
        return v

    # --------------------------------------- read-only accessors (ISSUE 15)

    def res_col_readonly(self, name: str) -> "Optional[int]":
        """Resource name -> column index WITHOUT interning: core columns
        map directly, extended resources resolve only if some committed
        pod/node already established them, else None.  The capacity
        planner's catalog encoder routes through here — a side
        observer must never grow dims.R or dirty the arena."""
        if name == RESOURCE_CPU:
            return RES_MILLICPU
        if name == RESOURCE_MEMORY:
            return RES_MEMORY
        if name == RESOURCE_EPHEMERAL_STORAGE:
            return RES_EPHEMERAL
        if name == RESOURCE_PODS:
            return RES_PODS
        return self._res_cols.get(name)

    def backlog_req_vector(self, pod: Pod) -> np.ndarray:
        """READ-ONLY f32[R] request vector for a NOT-YET-PLACED pod (the
        capacity planner's backlog encoding): same column layout and
        units as _req_vector, but unknown extended resources are
        dropped instead of growing the resource axis — encoding a
        backlog must not mutate the arena, mark rows dirty, or perturb
        the interner (placement bit-identity planner on/off rides on
        this)."""
        v = np.zeros(self.dims.R, np.float32)
        for name, q in pod.resource_request().items():
            col = self.res_col_readonly(name)
            if col is None:
                continue
            v[col] = q.milli if name == RESOURCE_CPU else float(q)
        v[RES_PODS] = 1.0
        return v

    # ----------------------------------------------------------------- nodes

    def add_node(self, node: Node) -> int:
        if node.name in self.node_rows:
            return self.update_node(node)
        if self._free_rows:
            row = self._free_rows.pop()
        else:
            row = self._next_row
            self._next_row += 1
            while row >= self._cap_n:
                self._grow_nodes()
        self.node_rows[node.name] = row
        self._node_ports[row] = Counter()
        self._node_disk_vols[row] = Counter()
        self._write_node_row(row, node)
        self._mark_node_dirty(row)
        self.generation += 1
        return row

    def update_node(self, node: Node) -> int:
        row = self.node_rows[node.name]
        old = self._row_node.get(row)
        if old is not None:
            for img in old.status.images:
                if img.names:
                    self._image_nodes[img.names[0]] -= 1
        # topology labels may change: lift resident pods' pair contributions
        # off the old pairs, rewrite the row, then re-apply on the new pairs
        resident = [
            self.pods[key] for key in self._row_pods.get(row, ()) if key in self.pods
        ]
        for rec in resident:
            self._shift_pod_pairs(rec, add=False)
        self._write_node_row(row, node)
        for rec in resident:
            self._shift_pod_pairs(rec, add=True)
        self._mark_node_dirty(row)
        self.generation += 1
        return row

    def remove_node(self, name: str) -> None:
        row = self.node_rows.pop(name)
        node = self._row_node.pop(row, None)
        if node is not None:
            for img in node.status.images:
                if img.names:
                    self._image_nodes[img.names[0]] -= 1
        # detach pods still charged to this row (the informer's pod deletes
        # arrive separately, ref cache.go RemoveNode keeps pod entries):
        # their term-group pair contributions and arena links must not leak
        # into whichever node reuses the row.  Group *membership* stays (the
        # pod still exists); only the per-pair placement contribution goes.
        for key in list(self._row_pods.get(row, ())):
            rec = self.pods.get(key)
            if rec is None:
                continue
            self._shift_pod_pairs(rec, add=False)
            rec.node_row = -1
            self.p_node[rec.m] = PAD
            # the detached pod no longer holds committed capacity: its
            # tenant usage retires with the row's aggregates below
            self.a_ns_usage[
                self._ns_row(rec.ns), : rec.req.shape[0]
            ] -= rec.req
        self._row_pods.pop(row, None)
        # zero the aggregates so row reuse starts clean
        self.a_requested[row, :] = 0.0
        self.a_nonzero[row, :] = 0.0
        self.a_volcnt[row, :] = 0.0
        self._node_ports[row] = Counter()
        self._node_disk_vols[row] = Counter()
        # drop this row from the attachable-volume reverse index
        old_cnts = self._node_cnt_vols.pop(row, None)
        if old_cnts is not None:
            for t, ctr in enumerate(old_cnts):
                for vid in ctr:
                    rows = self._cnt_vol_rows[t].get(vid)
                    if rows is not None:
                        rows.discard(row)
                        if not rows:
                            del self._cnt_vol_rows[t][vid]
        self._rebuild_node_ports(row)
        self._rebuild_node_vols(row)
        self.a_valid[row] = False
        self.a_topo[row, :] = False
        for col in self._node_pair_id.values():
            col[row] = PAD
        self._free_rows.append(row)
        self._mark_node_dirty(row)
        self._gc_dirty = True  # detached pods left p_node
        self.generation += 1

    def add_nodes(self, nodes: Sequence[Node]) -> List[int]:
        """Batched add_node: a columnar encode of many NEW node rows that
        produces byte-identical arena state to calling add_node(n) for each
        node in order (pinned by tests/test_bulk_nodes.py), amortizing the
        per-node numpy overhead — the cold-start / failover re-sync wall
        (node_encode_seconds in bench.py):

          * per-row numpy slice writes (~40 per node in _write_node_row)
            collapse into one fancy-indexed scatter per FIELD per batch;
          * string interning runs through the per-node registration pass
            in add_node's exact order (name, labels, taints, GetZoneKey,
            images, avoid), so interner ids, resource/volume columns, and
            the topology-pair vocabulary are assigned identically;
          * pad-dim growth (L/T/I, N) happens ONCE up front for the whole
            batch instead of regrowing per offending node (bump() rounds
            to pow2 of the max, so final dims match the sequential loop);
          * dirty-row marks and the generation counter advance once per
            batch, not once per node.

        Batches containing a duplicate name or a name already resident
        take the exact per-node path (those are update batches, where the
        old-row teardown must interleave per node).  Returns the assigned
        rows, same values the per-node loop would return."""
        nodes = list(nodes)
        if not nodes:
            return []
        names = [n.name for n in nodes]
        if len(set(names)) != len(names) or any(
            n in self.node_rows for n in names
        ):
            return [self.add_node(n) for n in nodes]

        # -- pass 0: pad-dim growth to fit the whole batch
        d0 = self.dims
        grow = {}
        max_l = max(len(n.metadata.labels) for n in nodes)
        max_t = max(len(n.spec.taints) for n in nodes)
        max_i = max(len(n.status.images) for n in nodes)
        if max_l > d0.L:
            grow["L"] = max_l
        if max_t > d0.T:
            grow["T"] = max_t
        if max_i > d0.I:
            grow["I"] = max_i
        if grow:
            self.dims = self.dims.bump(**grow)
            self._regrow_node_arena(self._cap_n)
            self._reapply_pods_to_arena()

        # -- pass 1: row allocation (free rows first — the same pop order
        # the per-node loop uses).  The arena is pre-sized to the FINAL
        # capacity by replaying _grow_nodes' growth schedule arithmetic
        # without the intermediate reallocs (one regrow, not ~13 at 5k
        # nodes; the final cap — and therefore every arena shape — is
        # byte-identical to the sequential loop's)
        n_new = len(nodes) - min(len(self._free_rows), len(nodes))
        if n_new:
            max_row = self._next_row + n_new - 1
            cap = self._cap_n
            while max_row >= cap:
                cap = cap * 2 if cap < 2048 else -(-(cap + cap // 4) // 512) * 512
            if cap != self._cap_n:
                self.dims = dataclasses.replace(self.dims, N=cap)
                self._regrow_node_arena(self._cap_n)
        rows: List[int] = []
        reused: List[int] = []    # rows recycled off the free list (these
        #                           carry stale content needing row resets)
        node_rows = self.node_rows
        row_node = self._row_node
        node_ports = self._node_ports
        node_dvols = self._node_disk_vols
        free_rows = self._free_rows
        # Counter.__new__ skips the __init__/update call chain; a Counter
        # is a plain dict subclass, so the uninitialized instance IS the
        # empty Counter (== Counter(), same type, same methods)
        counter_new = Counter.__new__
        for node in nodes:
            if free_rows:
                row = free_rows.pop()
                reused.append(row)
            else:
                row = self._next_row
                self._next_row += 1
            rows.append(row)
            node_rows[node.metadata.name] = row
            row_node[row] = node
            node_ports[row] = counter_new(Counter)
            node_dvols[row] = counter_new(Counter)

        # -- pass 2: vocabulary registration + integer row data, per node
        # in add_node's exact order.  This pass only touches dicts/lists
        # (interner, _res_cols/_vol_cols, pair vocabulary — all of whose
        # id-assignment order must match the per-node loop); every numpy
        # write waits for pass 3, AFTER any R/VT/TP growth has settled.
        it = self.interner
        intern = it.intern
        intern_many = it.intern_many
        # topology-pair registration without per-miss a_topo doubling: the
        # vocabulary appends here in the per-node order _pair_id would
        # use, and the (N x TP) incidence tensor resizes ONCE after the
        # loop by replaying the doubling schedule (identical final TP; the
        # sequential loop pays up to ~9 full-width reallocs at 5k nodes)
        pv = self._pair_vocab
        pv_get = pv.get
        ptk = self._pair_topo_key
        gz_memo: Dict[Tuple[str, str], str] = {}
        name_ids: List[int] = []
        # condition/unschedulable EXCEPTIONS only (healthy schedulable
        # fleets append nothing; pass 3 scatters just the outliers over a
        # False default)
        unsched_k: List[int] = []
        notready_k: List[int] = []
        mempress_k: List[int] = []
        diskpress_k: List[int] = []
        pidpress_k: List[int] = []
        alloc_n: List[int] = []       # per-node resource-entry count
        alloc_c: List[int] = []
        alloc_v: List[float] = []
        lim_k: List[int] = []         # attachable-volume limit writes
        lim_c: List[int] = []
        lim_v: List[float] = []
        lab_n: List[int] = []         # per-node label count (k/j columns
        lab_kid: List[int] = []       #   derive via np.repeat/arange)
        lab_vid: List[int] = []
        tnt_k: List[int] = []
        tnt_j: List[int] = []
        tnt_kid: List[int] = []
        tnt_vid: List[int] = []
        tnt_eff: List[int] = []
        topo_k: List[int] = []        # (batch idx, pair id) True incidences
        topo_pid: List[int] = []
        pair_cols: Dict[int, List[int]] = {k: [] for k in self.topo_keys}
        topo_key_strs = [
            (kid, it.string(kid), pair_cols[kid].append)
            for kid in self.topo_keys
        ]
        topo_k_app = topo_k.append
        topo_pid_app = topo_pid.append
        img_k: List[int] = []
        img_j: List[int] = []
        img_id: List[int] = []
        img_sz: List[float] = []
        img_names: List[str] = []     # _image_nodes increments, batched
        av_k: List[int] = []
        av_j: List[int] = []
        av_id: List[int] = []
        # allocatable-dict memo: stamped node fleets share one allocatable
        # content, so the exact Fraction math (milli/__float__, ~6us/node
        # at 5k) and column resolution run once per DISTINCT content;
        # values are (res cols, res vals, limit cols, limit vals)
        alloc_memo: Dict[Tuple, Tuple] = {}
        res_memo: Dict[str, int] = {}
        # image-name cap simulation: the per-node loop caps each row's
        # flattened image NAMES at the dims.I in effect when that node is
        # written (I bumps lazily off the image COUNT of the node itself),
        # so a many-names node written before the bumping node truncates
        # at the old width — replay that schedule for byte-identity
        run_i = d0.I
        import json

        ready_only = {"Ready": "True"}
        for k, node in enumerate(nodes):
            cond = node.status.conditions
            if node.spec.unschedulable:
                unsched_k.append(k)
            if cond != ready_only:  # != the healthy-fleet shape: decode
                nr, mp, dp, pp = self._cond_bits(cond)
                if nr:
                    notready_k.append(k)
                if mp:
                    mempress_k.append(k)
                if dp:
                    diskpress_k.append(k)
                if pp:
                    pidpress_k.append(k)
            # whole-dict memo: a stamped fleet shares one allocatable
            # content (parse_quantity canonicalizes values to shared
            # instances with cached hashes, so the tuple key hashes in
            # ~0.5us and dict equality takes the identity fast path)
            akey = tuple(node.status.allocatable.items())
            hit = alloc_memo.get(akey)
            if hit is None:
                cols: List[int] = []
                vals: List[float] = []
                lcols: List[int] = []
                lvals: List[float] = []
                for name, q in node.status.allocatable.items():
                    if name.startswith("attachable-volumes-"):
                        col = self._vol_limit_col(name)
                        if col is not None:
                            lcols.append(col)
                            lvals.append(float(q))
                        continue
                    col = res_memo.get(name)
                    if col is None:
                        col = res_memo[name] = self._res_col(name)
                    cols.append(col)
                    vals.append(
                        q.milli if name == RESOURCE_CPU else float(q)
                    )
                hit = alloc_memo[akey] = (cols, vals, lcols, lvals)
            cols, vals, lcols, lvals = hit
            alloc_n.append(len(cols))
            alloc_c.extend(cols)
            alloc_v.extend(vals)
            if lcols:
                lim_k.extend([k] * len(lcols))
                lim_c.extend(lcols)
                lim_v.extend(lvals)
            # one stacked intern for everything this node names, in
            # _write_node_row's exact order (name, label k/v pairs, taint
            # key/value pairs, GetZoneKey combo, image names, avoid uids)
            # so novel-id assignment is position-identical to the loop
            labels = node.metadata.labels
            lab_items = sorted(labels.items())
            taints = node.spec.taints
            region = labels.get(REGION_KEY, "")
            zone = labels.get(ZONE_KEY, "")
            imgs = node.status.images
            capped_imgs: "List[Tuple[str, float]] | Tuple" = ()
            if imgs:
                if len(imgs) > run_i:
                    run_i = _pow2(len(imgs))
                capped_imgs = []
                j = 0
                for img in imgs:
                    for name in img.names:
                        if j >= run_i:
                            break
                        capped_imgs.append((name, float(img.size_bytes)))
                        j += 1
            # (slot, uid) pairs: empty uids CONSUME a slot but write
            # nothing, matching _write_node_row's enumerate-then-filter
            uids: "List[Tuple[int, str]] | Tuple" = ()
            ann = node.metadata.annotations.get(
                "scheduler.alpha.kubernetes.io/preferAvoidPods"
            )
            if ann:
                try:
                    avoid = json.loads(ann)
                    raw = [
                        e.get("podSignature", {})
                        .get("podController", {})
                        .get("uid", "")
                        for e in avoid.get("preferAvoidPods", [])
                    ]
                    uids = [(j, u) for j, u in enumerate(raw[: self.dims.A]) if u]
                except (ValueError, AttributeError):
                    uids = []
            nl = len(lab_items)
            nt = len(taints)
            # the name interns FIRST (as _write_node_row does) and alone:
            # it is the one always-novel string, so the stacked
            # intern_many below usually takes its all-hits fast path
            name_ids.append(intern(node.metadata.name))
            strs: List[str] = []
            if nl:
                strs.extend(chain.from_iterable(lab_items))
            if nt:
                strs.extend(
                    chain.from_iterable((t.key, t.value) for t in taints)
                )
            if region or zone:
                gzk = (region, zone)
                gz = gz_memo.get(gzk)
                if gz is None:
                    gz = gz_memo[gzk] = region + ":\x00:" + zone
                strs.append(gz)
            if capped_imgs:
                strs.extend(nm for nm, _ in capped_imgs)
            if uids:
                strs.extend(u for _, u in uids)
            ids = intern_many(strs)
            # slice-unpack the stacked ids (C-speed strides, not per-item
            # python appends): keys at even offsets, values at odd
            lab_n.append(nl)
            if nl:
                lab_kid.extend(ids[0:2 * nl:2])
                lab_vid.extend(ids[1:1 + 2 * nl:2])
            base = 2 * nl
            if nt:
                tnt_k.extend([k] * nt)
                tnt_j.extend(range(nt))
                tnt_kid.extend(ids[base:base + 2 * nt:2])
                tnt_vid.extend(ids[base + 1:base + 2 * nt:2])
                for t in taints:
                    tnt_eff.append(EFFECT_CODES.get(t.effect, 0))
            pos = base + 2 * nt
            # topology pairs: label values are interned by now, so the
            # pair-vocabulary registration order matches the per-node loop
            labels_get = labels.get
            for kid, key_str, col_append in topo_key_strs:
                val = labels_get(key_str)
                if val is not None:
                    key2 = (kid, intern(val))
                    pid = pv_get(key2)
                    if pid is None:
                        pid = len(ptk)
                        pv[key2] = pid
                        ptk.append(kid)
                    topo_k_app(k)
                    topo_pid_app(pid)
                    col_append(pid)
                else:
                    col_append(PAD)
            if region or zone:
                key2 = (self.getzone_key, ids[pos])
                pid = pv_get(key2)
                if pid is None:
                    pid = len(ptk)
                    pv[key2] = pid
                    ptk.append(self.getzone_key)
                topo_k_app(k)
                topo_pid_app(pid)
                pos += 1
            for j, (nm, sz) in enumerate(capped_imgs):
                img_k.append(k)
                img_j.append(j)
                img_id.append(ids[pos])
                pos += 1
                img_sz.append(sz)
                img_names.append(nm)
            for j, _u in uids:
                av_k.append(k)
                av_j.append(j)
                av_id.append(ids[pos])
                pos += 1
        if img_names:
            self._image_nodes.update(img_names)
        # replay _grow_pairs' doubling schedule in one realloc
        self._grow_pairs(min_tp=len(ptk))

        # -- pass 3: columnar arena writes (arrays fetched AFTER pass 2 —
        # R/VT/TP growth replaces them).  Row resets apply ONLY to rows
        # recycled off the free list: those keep their previous label/
        # taint/allocatable content until overwritten (remove_node clears
        # only the aggregates), so exactly the slices _write_node_row
        # rewrites are reset.  FRESH rows skip resets entirely — the arena
        # default (PAD/0/inf/nan/False from _alloc_node_arena) is
        # byte-identical to the reset value — and a no-reuse batch is a
        # contiguous row range, so the full-batch column writes go through
        # slice assignment instead of per-element fancy indexing.
        # Port/volume row rebuilds are SKIPPED: a new row's counters are
        # empty and its port/vol slices are already PAD/False (fresh from
        # _alloc, or reset by remove_node before the row was freed).
        i32, f32 = np.int32, np.float32
        if reused:
            rows_arr = np.asarray(rows, np.intp)
            idx: "slice | np.ndarray" = rows_arr
            row0 = 0
            r = np.asarray(reused, np.intp)
            self.a_unsched[r] = False
            self.a_notready[r] = False
            self.a_mempress[r] = False
            self.a_diskpress[r] = False
            self.a_pidpress[r] = False
            self.a_allocatable[r] = 0.0
            self.a_vollim[r] = np.inf
            self.a_lkeys[r] = PAD
            self.a_lvals[r] = PAD
            self.a_lnums[r] = np.nan
            self.a_tkey[r] = PAD
            self.a_tval[r] = PAD
            self.a_teff[r] = PAD
            self.a_topo[r] = False
            self.a_img_id[r] = PAD
            self.a_img_sz[r] = 0.0
            self.a_avoid[r] = PAD
        else:
            rows_arr = None
            row0 = rows[0]
            idx = slice(row0, row0 + len(rows))

        def rowsel(ks):
            ka = np.asarray(ks, np.intp)
            return ka + row0 if rows_arr is None else rows_arr[ka]

        def scatter2(dst, ks, js, vals, dtype):
            dst[rowsel(ks), np.asarray(js, np.intp)] = np.asarray(vals, dtype)

        self.a_valid[idx] = True
        self.a_name[idx] = np.asarray(name_ids, i32)
        # condition/unschedulable outliers over the False default
        if unsched_k:
            self.a_unsched[rowsel(unsched_k)] = True
        if notready_k:
            self.a_notready[rowsel(notready_k)] = True
        if mempress_k:
            self.a_mempress[rowsel(mempress_k)] = True
        if diskpress_k:
            self.a_diskpress[rowsel(diskpress_k)] = True
        if pidpress_k:
            self.a_pidpress[rowsel(pidpress_k)] = True
        if alloc_c:
            # the batch-index column derives from the per-node counts
            # (np.repeat beats 5k python [k]*n extends)
            alloc_k_arr = np.repeat(
                np.arange(len(nodes), dtype=np.intp),
                np.asarray(alloc_n, np.intp),
            )
            self.a_allocatable[
                alloc_k_arr + row0 if rows_arr is None else rows_arr[alloc_k_arr],
                np.asarray(alloc_c, np.intp),
            ] = np.asarray(alloc_v, f32)
        if lim_k:
            scatter2(self.a_vollim, lim_k, lim_c, lim_v, f32)
        if lab_kid:
            lab_n_arr = np.asarray(lab_n, np.intp)
            lab_k_arr = np.repeat(
                np.arange(len(nodes), dtype=np.intp), lab_n_arr
            )
            # per-node slot index: 0..nl-1 per node, C-speed
            starts = np.cumsum(lab_n_arr) - lab_n_arr
            lab_j_arr = (
                np.arange(len(lab_kid), dtype=np.intp)
                - np.repeat(starts, lab_n_arr)
            )
            lr = lab_k_arr + row0 if rows_arr is None else rows_arr[lab_k_arr]
            self.a_lkeys[lr, lab_j_arr] = np.asarray(lab_kid, i32)
            self.a_lvals[lr, lab_j_arr] = np.asarray(lab_vid, i32)
            # numeric label column (Gt/Lt operands): one parse per
            # DISTINCT value id, gathered C-speed over the whole batch
            vid_arr = np.asarray(lab_vid, np.intp)
            lut = np.full(int(vid_arr.max()) + 1, np.nan, f32)
            s = it.string
            for vid in set(lab_vid):
                v = s(vid)
                try:
                    lut[vid] = float(int(v))
                except ValueError:
                    pass
            self.a_lnums[lr, lab_j_arr] = lut[vid_arr]
        if tnt_k:
            scatter2(self.a_tkey, tnt_k, tnt_j, tnt_kid, i32)
            scatter2(self.a_tval, tnt_k, tnt_j, tnt_vid, i32)
            scatter2(self.a_teff, tnt_k, tnt_j, tnt_eff, i32)
        if topo_k:
            self.a_topo[rowsel(topo_k), np.asarray(topo_pid, np.intp)] = True
        for kid, vals in pair_cols.items():
            self._node_pair_id[kid][idx] = np.asarray(vals, i32)
        if img_k:
            scatter2(self.a_img_id, img_k, img_j, img_id, i32)
            scatter2(self.a_img_sz, img_k, img_j, img_sz, f32)
        if av_k:
            scatter2(self.a_avoid, av_k, av_j, av_id, i32)

        self._dirty_node_rows.update(rows)
        self.generation += len(nodes)
        return rows

    def update_nodes(self, nodes: Sequence[Node]) -> List[int]:
        """Bulk upsert for informer re-list / failover re-sync.  NEW nodes
        flush through the columnar add_nodes path (consecutive runs keep
        arrival order, so interner/vocabulary id assignment matches the
        per-node loop); resident nodes whose stored object compares EQUAL
        are skipped outright — no row write, no dirty mark, no generation
        bump (a re-listed unchanged node is not a change; this is the warm
        re-encode fast path bench.py reports) — and changed nodes take
        update_node.  Returns each node's arena row."""
        nodes = list(nodes)
        rows: List[int] = [-1] * len(nodes)
        run: List[int] = []

        def flush():
            if run:
                for i, r in zip(run, self.add_nodes([nodes[i] for i in run])):
                    rows[i] = r
                run.clear()

        for i, node in enumerate(nodes):
            row = self.node_rows.get(node.name)
            if row is None:
                run.append(i)
                continue
            flush()
            if self._row_node.get(row) == node:
                rows[i] = row
            else:
                rows[i] = self.update_node(node)
        flush()
        return rows

    def _write_node_row(self, row: int, node: Node) -> None:
        d = self.dims
        it = self.interner
        self._row_node[row] = node
        # pad-dim growth checks
        grow = {}
        if len(node.labels) > d.L:
            grow["L"] = len(node.labels)
        if len(node.spec.taints) > d.T:
            grow["T"] = len(node.spec.taints)
        if len(node.status.images) > d.I:
            grow["I"] = len(node.status.images)
        if grow:
            self.dims = self.dims.bump(**grow)
            self._regrow_node_arena(self._cap_n)
            self._reapply_pods_to_arena()
        self.a_valid[row] = True
        self.a_name[row] = it.intern(node.name)
        self.a_unsched[row] = node.spec.unschedulable
        (
            self.a_notready[row],
            self.a_mempress[row],
            self.a_diskpress[row],
            self.a_pidpress[row],
        ) = self._cond_bits(node.status.conditions)
        # allocatable (+ per-node attachable-volume limits, ref the
        # AttachVolumeLimit feature's attachable-volumes-* allocatable keys)
        self.a_allocatable[row, :] = 0.0
        self.a_vollim[row, :] = np.inf
        for name, q in node.status.allocatable.items():
            if name.startswith("attachable-volumes-"):
                col = self._vol_limit_col(name)
                if col is not None:
                    self.a_vollim[row, col] = float(q)
                continue
            col = self._res_col(name)
            self.a_allocatable[row, col] = (
                q.milli if name == RESOURCE_CPU else float(q)
            )
        # labels
        self.a_lkeys[row, :] = PAD
        self.a_lvals[row, :] = PAD
        self.a_lnums[row, :] = np.nan
        for j, (k, v) in enumerate(sorted(node.labels.items())):
            self.a_lkeys[row, j] = it.intern(k)
            self.a_lvals[row, j] = it.intern(v)
            try:
                self.a_lnums[row, j] = float(int(v))
            except ValueError:
                pass
        # taints
        self.a_tkey[row, :] = PAD
        self.a_tval[row, :] = PAD
        self.a_teff[row, :] = PAD
        for j, t in enumerate(node.spec.taints):
            self.a_tkey[row, j] = it.intern(t.key)
            self.a_tval[row, j] = it.intern(t.value)
            self.a_teff[row, j] = EFFECT_CODES.get(t.effect, 0)
        # topology pairs
        self.a_topo[row, :] = False
        for kid in self.topo_keys:
            key = it.string(kid)
            val = node.labels.get(key)
            col = self._node_pair_id[kid]
            if val is not None:
                pid = self._pair_id(kid, it.intern(val))
                self.a_topo[row, pid] = True
                col[row] = pid
            else:
                col[row] = PAD
        # GetZoneKey pair (util/node/node.go:126-143): region + ":\x00:" + zone,
        # present when either label is non-empty; this is the grouping unit of
        # the SelectorSpread zone reduce (two same-named zones in different
        # regions are distinct).
        region = node.labels.get(REGION_KEY, "")
        zone = node.labels.get(ZONE_KEY, "")
        if region or zone:
            gz_pid = self._pair_id(
                self.getzone_key, it.intern(region + ":\x00:" + zone)
            )
            self.a_topo[row, gz_pid] = True
        # images: EVERY name of an image is a lookup key (the reference's
        # imageStates maps each entry of image.Names to the same state)
        self.a_img_id[row, :] = PAD
        self.a_img_sz[row, :] = 0.0
        j = 0
        for img in node.status.images:
            for name in img.names:
                if j >= self.dims.I:
                    break
                self.a_img_id[row, j] = it.intern(name)
                self.a_img_sz[row, j] = float(img.size_bytes)
                self._image_nodes[name] += 1
                j += 1
        # prefer-avoid-pods annotation
        # ref api/v1/pod/util.go GetAvoidPodsFromNodeAnnotations + priorities/
        # node_prefer_avoid_pods.go: annotation lists controller refs to avoid.
        self.a_avoid[row, :] = PAD
        import json

        ann = node.metadata.annotations.get("scheduler.alpha.kubernetes.io/preferAvoidPods")
        if ann:
            try:
                avoid = json.loads(ann)
                uids = [
                    e.get("podSignature", {})
                    .get("podController", {})
                    .get("uid", "")
                    for e in avoid.get("preferAvoidPods", [])
                ]
                for j, u in enumerate(uids[: d.A]):
                    if u:
                        self.a_avoid[row, j] = it.intern(u)
            except (ValueError, AttributeError):
                pass
        self._rebuild_node_ports(row)
        self._rebuild_node_vols(row)

    def _reapply_pods_to_arena(self) -> None:
        """After an arena retile, re-accumulate pod aggregates into node rows."""
        self.a_requested[:, :] = 0.0
        self.a_nonzero[:, :] = 0.0
        self.a_volcnt[:, :] = 0.0
        self.a_ns_usage[:, :] = 0.0
        self._node_cnt_vols.clear()
        self._cnt_vol_rows = [dict() for _ in range(self.dims.VT)]
        for rec in self.pods.values():
            if rec.node_row >= 0:
                self.a_requested[rec.node_row, : rec.req.shape[0]] += rec.req
                self.a_nonzero[rec.node_row] += rec.nonzero
                self.a_ns_usage[
                    self._ns_row(rec.ns), : rec.req.shape[0]
                ] += rec.req
                if rec.cnt_vols:
                    cnts = self._node_cnt_vols.setdefault(
                        rec.node_row,
                        [Counter() for _ in range(self.dims.VT)],
                    )
                    for t, ids in enumerate(rec.cnt_vols):
                        for vid in ids:
                            cnts[t][vid] += 1
                            self._cnt_vol_rows[t].setdefault(
                                vid, set()
                            ).add(rec.node_row)
                        self.a_volcnt[rec.node_row, t] = len(cnts[t])
        for row in self._node_ports:
            self._rebuild_node_ports(row)
            self._rebuild_node_vols(row)
        self._mark_all_dirty()

    def _rebuild_node_ports(self, row: int) -> None:
        self.a_ppp[row, :] = PAD
        self.a_pip[row, :] = PAD
        self.a_pused[row, :] = False
        ports = self._node_ports.get(row, Counter())
        if len(ports) > self.dims.P:
            self.dims = self.dims.bump(P=len(ports))
            self._regrow_node_arena(self._cap_n)
            self._reapply_pods_to_arena()
            return
        for j, (pp, ip) in enumerate(sorted(ports)):
            self.a_ppp[row, j] = pp
            self.a_pip[row, j] = ip
            self.a_pused[row, j] = True

    def _rebuild_node_vols(self, row: int) -> None:
        self.a_dvol[row, :] = PAD
        vols = self._node_disk_vols.get(row, Counter())
        if len(vols) > self.dims.DVN:
            self.dims = self.dims.bump(DVN=len(vols))
            self._regrow_node_arena(self._cap_n)
            self._reapply_pods_to_arena()
            return
        for j, v in enumerate(sorted(vols)):
            self.a_dvol[row, j] = v

    # ------------------------------------------------------------------ pods

    def _pod_ports(self, pod: Pod) -> List[Tuple[int, int]]:
        out = []
        for p in pod.host_ports():
            pp = self.interner.intern(f"{p.protocol or 'TCP'}/{p.host_port}")
            ip = p.host_ip
            if ip in ("", "0.0.0.0"):
                ipid = 0
            else:
                ipid = self.interner.intern(ip)
            out.append((pp, ipid))
        return out

    def _vol_col(self, csi_driver: str) -> int:
        """Attach-count column for a CSI driver ('' = the generic CSI
        column).  New drivers widen the VT axis — node arenas, per-record
        vectors, and the per-node/per-id bookkeeping all regrow, the same
        discipline _res_col applies to extended resources."""
        if not csi_driver:
            return VOL_CSI
        col = self._vol_cols.get(csi_driver)
        if col is not None:
            return col
        col = NUM_VOL_TYPES + len(self._vol_cols)
        if col >= self.dims.VT:
            old = self.dims.VT
            self.dims = dataclasses.replace(self.dims, VT=_pow2(col + 1))
            grow = self.dims.VT - old
            for attr, fill in (("a_volcnt", 0.0), ("a_vollim", np.inf)):
                src_arr = getattr(self, attr)
                new = np.full((self._cap_n, self.dims.VT), fill, np.float32)
                new[:, :old] = src_arr
                setattr(self, attr, new)
            self._cnt_vol_rows += [dict() for _ in range(grow)]
            for counters in self._node_cnt_vols.values():
                counters.extend(Counter() for _ in range(grow))
            wide_empty = np.zeros(self.dims.VT, np.float32)
            wide_empty.setflags(write=False)
            self._empty_vcounts = wide_empty
            for rec in self.pods.values():
                if not rec.cnt_vols:  # () sentinel (no volumes) stays ()
                    rec.vol_counts = wide_empty  # keep records shared
                    continue
                v = np.zeros(self.dims.VT, np.float32)
                v[: rec.vol_counts.shape[0]] = rec.vol_counts
                rec.vol_counts = v
                rec.cnt_vols = list(rec.cnt_vols) + [
                    set() for _ in range(grow)
                ]
            self._mark_all_dirty()
        self._vol_cols[csi_driver] = col
        return col

    def _pod_vols(self, pod: Pod) -> Tuple[List[int], List[int], np.ndarray, list]:
        """(disk-conflict CHECK tokens, disk-conflict ADVERTISE tokens,
        per-filter-type UNIQUE new volume counts, per-type unique id sets).

        ref predicates.go NoDiskConflict (isVolumeConflict :295-328) and
        MaxVolumeCount filters.  Counts dedupe by volume identity
        (filterVolumes keys a map by unique id).  Conflict tokens encode
        the read-only allowance: GCE-PD / RBD / ISCSI mounts that are BOTH
        read-only don't conflict, so volume V advertises "V#any" (+"V#rw"
        when read-write) and checks "V#any" when read-write but only
        "V#rw" when read-only; EBS conflicts regardless (one token).
        """
        if not pod.spec.volumes:  # hot path: most pods mount nothing
            # shared read-only zero vector + empty cnt_ids sentinel: the
            # cache-commit path calls this once per bound pod, and per-call
            # allocation of VT sets dominated the commit profile.  Every
            # consumer iterates cnt_ids with enumerate, so () is safe; the
            # zeros array is marked unwriteable and replaced per-record on
            # VT regrow (_vol_col), so sharing cannot alias a mutation.
            z = self._empty_vcounts
            if z is None or z.shape[0] != self.dims.VT:
                z = np.zeros(self.dims.VT, np.float32)
                z.setflags(write=False)
                self._empty_vcounts = z
            return [], [], z, ()
        disk: List[int] = []       # check tokens (the pod's own mounts)
        disk_adv: List[int] = []   # advertise tokens (what a node shows)
        cnt_ids: list = [set() for _ in range(self.dims.VT)]

        def allow_ro(base: str, ro: bool) -> None:
            it = self.interner
            disk_adv.append(it.intern(base + "#any"))
            if not ro:
                disk_adv.append(it.intern(base + "#rw"))
            disk.append(it.intern(base + ("#rw" if ro else "#any")))

        for v in pod.spec.volumes:
            if "gcePersistentDisk" in v:
                g = v["gcePersistentDisk"]
                base = "gce/" + g.get("pdName", "")
                allow_ro(base, bool(g.get("readOnly")))
                cnt_ids[VOL_GCE].add(self.interner.intern(base))
            elif "awsElasticBlockStore" in v:
                vid = self.interner.intern("ebs/" + v["awsElasticBlockStore"].get("volumeID", ""))
                disk.append(vid)
                disk_adv.append(vid)
                cnt_ids[VOL_EBS].add(vid)
            elif "rbd" in v:
                # identity = monitor OVERLAP + pool + image (predicates.go
                # :264-272 haveOverlap): one token per monitor, so any
                # shared monitor collides
                r = v["rbd"]
                # no monitors -> no tokens (haveOverlap([], x) is false)
                for mon in r.get("monitors", []) or ():
                    allow_ro(
                        "rbd/%s/%s/%s" % (mon, r.get("pool", "rbd"), r.get("image", "")),
                        bool(r.get("readOnly")),
                    )
            elif "iscsi" in v:
                # identity = IQN alone (predicates.go:253-262 — multi-path
                # target portals reach the same LUNs)
                r = v["iscsi"]
                allow_ro("iscsi/%s" % r.get("iqn", ""),
                         bool(r.get("readOnly")))
            elif "azureDisk" in v:
                cnt_ids[VOL_AZURE].add(
                    self.interner.intern("azd/" + v["azureDisk"].get("diskName", ""))
                )
            elif "cinder" in v:
                cnt_ids[VOL_CINDER].add(
                    self.interner.intern("cinder/" + v["cinder"].get("volumeID", ""))
                )
            elif "persistentVolumeClaim" in v:
                # resolve the claim to count the bound PV's attachment type
                pvc = self.pvcs.get(
                    (pod.namespace, v["persistentVolumeClaim"].get("claimName", ""))
                )
                if pvc is not None and pvc.volume_name:
                    pv = self.pvs.get(pvc.volume_name)
                    if pv is not None:
                        from kubernetes_tpu.api import storage as kstorage

                        col = {
                            kstorage.SRC_EBS: VOL_EBS,
                            kstorage.SRC_GCE: VOL_GCE,
                            kstorage.SRC_CSI: VOL_CSI,
                            kstorage.SRC_AZURE: VOL_AZURE,
                            kstorage.SRC_CINDER: VOL_CINDER,
                        }.get(pv.source_kind)
                        if col is not None:
                            if pv.source_kind == kstorage.SRC_CSI:
                                # per-driver accounting: each CSI driver
                                # gets its own count/limit column
                                col = self._vol_col(pv.csi_driver)
                                if col >= len(cnt_ids):
                                    cnt_ids.extend(
                                        set() for _ in
                                        range(col + 1 - len(cnt_ids))
                                    )
                            prefix = {
                                VOL_EBS: "ebs/", VOL_GCE: "gce/",
                                VOL_CSI: "csi/", VOL_AZURE: "azd/",
                                VOL_CINDER: "cinder/",
                            }.get(col, "csi/")
                            ident = pv.source_id or ("pvname/" + pv.name)
                            cnt_ids[col].add(
                                self.interner.intern(prefix + ident)
                            )
        if len(cnt_ids) < self.dims.VT:  # a driver column appeared mid-scan
            cnt_ids.extend(set() for _ in range(self.dims.VT - len(cnt_ids)))
        counts = np.asarray([len(ids) for ids in cnt_ids], np.float32)
        return disk, disk_adv, counts, cnt_ids

    def _nonzero(self, pod: Pod) -> np.ndarray:
        cpu = 0.0
        mem = 0.0
        for c in pod.spec.containers:
            cpu += (
                c.requests[RESOURCE_CPU].milli
                if RESOURCE_CPU in c.requests
                else DEFAULT_MILLI_CPU_REQUEST
            )
            mem += (
                float(c.requests[RESOURCE_MEMORY])
                if RESOURCE_MEMORY in c.requests
                else DEFAULT_MEMORY_REQUEST
            )
        return np.array([cpu, mem], np.float32)

    def add_pod(self, pod: Pod) -> None:
        """Add an assigned (or assumed) pod: accumulate into its node's row and
        the vectorized pod index (ref internal/cache/cache.go AddPod/AssumePod)."""
        key = (pod.namespace, pod.name)
        if key in self.pods:
            self.remove_pod(pod)
        if self._free_m:
            m = self._free_m.pop()
        else:
            m = self._next_m
            self._next_m += 1
            if m >= self._cap_m:
                self._grow_pods()
        node_row = self.node_rows.get(pod.spec.node_name, -1)
        # (req, nonzero) memo keyed by container request content: cache
        # commits of controller-stamped identical pods skip the exact
        # Fraction summation (~60us/pod).  rec.req arrays are never mutated
        # in place (the R-regrow path replaces them), so sharing is safe.
        # unsorted items(): two insertion orders of the same content just
        # occupy two memo slots mapping to equal arrays — correct either
        # way, and skipping 3 sorts/pod matters at 10k commits/s
        rk = (
            tuple(tuple(c.requests.items()) for c in pod.spec.containers),
            () if not pod.spec.init_containers else tuple(
                tuple(c.requests.items())
                for c in pod.spec.init_containers
            ),
        )
        hit = self._req_memo.get(rk)
        if hit is None or hit[0].shape[0] != self.dims.R:
            if len(self._req_memo) > 4096:
                self._req_memo.clear()
            hit = (self._req_vector(pod.resource_request()), self._nonzero(pod))
            self._req_memo[rk] = hit
        req, nonzero = hit
        ports = self._pod_ports(pod)
        disk_check, disk_adv, vcounts, cnt_ids = self._pod_vols(pod)
        disk = disk_adv  # the NODE advertises; rec stores what to retract
        rec = _PodRecord(
            key=key,
            labels=dict(pod.labels),
            ns=pod.namespace,
            node_row=node_row,
            m=m,
            req=req,
            nonzero=nonzero,
            ports=ports,
            disk_vols=disk,
            vol_counts=vcounts,
            cnt_vols=cnt_ids,
            priority=pod.spec.priority,
            pod=pod,
            start_time=pod.status.start_time,
            uid=pod.metadata.uid,
        )
        self.pods[key] = rec
        self.p_alive[m] = True
        self.p_ns[m] = self.interner.intern(pod.namespace)
        self.p_node[m] = node_row
        for k, v in pod.labels.items():
            kid = self.interner.intern(k)
            col = self._label_cols.get(kid)
            if col is None:
                col = np.full(self._cap_m, PAD, np.int32)
                self._label_cols[kid] = col
            col[m] = self.interner.intern(v)
        if node_row >= 0:
            self._row_pods.setdefault(node_row, set()).add(key)
            self.a_requested[node_row, : req.shape[0]] += req
            self.a_nonzero[node_row] += nonzero
            # tenant usage column (ISSUE 14): committed requests only —
            # an unassigned pod exerts no placement-fairness pressure
            self.a_ns_usage[
                self._ns_row(pod.namespace), : req.shape[0]
            ] += req
            if ports:  # rebuilds are row-wide sorts: skip when untouched
                for pp_ip in ports:
                    self._node_ports[node_row][pp_ip] += 1
                self._rebuild_node_ports(node_row)
            if disk:
                for dv in disk:
                    self._node_disk_vols[node_row][dv] += 1
                self._rebuild_node_vols(node_row)
            # attachable-count state dedupes by volume identity: the node's
            # used count is the number of DISTINCT ids per type
            if cnt_ids:
                cnts = self._node_cnt_vols.get(node_row)
                if cnts is None:
                    cnts = self._node_cnt_vols[node_row] = [
                        Counter() for _ in range(self.dims.VT)
                    ]
                for t, ids in enumerate(cnt_ids):
                    for vid in ids:
                        cnts[t][vid] += 1
                        self._cnt_vol_rows[t].setdefault(vid, set()).add(
                            node_row
                        )
                    self.a_volcnt[node_row, t] = len(cnts[t])
        self._register_pod_terms(pod, rec)
        self._mark_pod_dirty(node_row)
        self._gc_dirty = True
        self.generation += 1

    def add_pods(self, pods: Sequence[Pod]) -> None:
        """Batched add_pod: one pass that produces byte-identical arena
        state to calling add_pod(p) for each pod in order, amortizing the
        per-pod numpy overhead (the host-commit wall of the live control
        plane):

          * row aggregates apply as ONE ordered np.add.at scatter instead
            of 2B row-slice adds (same accumulation order -> identical
            floats);
          * the pod-arena columns (alive/ns/node, label columns) write via
            fancy indexing, grouped per label key;
          * port/volume row rebuilds (row-wide sorts) run once per TOUCHED
            row after all pods applied, not once per pod;
          * the generation counter advances by len(pods) in one step.

        Equivalence is pinned by tests/test_batched_commit.py."""
        if not pods:
            return
        # Replacement batches take the exact per-pod path: duplicate keys
        # within the batch would corrupt the two-pass layout, and replacing
        # already-resident keys would reorder the -old/+new float
        # accumulation on shared node rows (per-pod interleaves per pod;
        # the batched passes would group all removes first), breaking the
        # byte-identical contract in the low-order bits.  The hot path —
        # assuming a cycle's freshly-scheduled winners — never replaces.
        batch_keys = [(p.namespace, p.name) for p in pods]
        if len(set(batch_keys)) != len(batch_keys) or any(
            k in self.pods for k in batch_keys
        ):
            for pod in pods:
                self.add_pod(pod)
            return
        # -- pass 1: arena-slot allocation (growth first, so all later
        # vectorized writes target the final arrays)
        ms: List[int] = []
        for pod in pods:
            if self._free_m:
                m = self._free_m.pop()
            else:
                m = self._next_m
                self._next_m += 1
                if m >= self._cap_m:
                    self._grow_pods()
            ms.append(m)
        # -- pass 2: per-pod records + bookkeeping collection
        recs: List[_PodRecord] = []
        rows: List[int] = []
        ns_ids: List[int] = []
        label_writes: Dict[int, Tuple[List[int], List[int]]] = {}
        touched_ports: Set[int] = set()
        touched_vols: Set[int] = set()
        vol_rows: Set[int] = set()
        for pod, m in zip(pods, ms):
            key = (pod.namespace, pod.name)
            node_row = self.node_rows.get(pod.spec.node_name, -1)
            rk = (
                tuple(tuple(c.requests.items()) for c in pod.spec.containers),
                () if not pod.spec.init_containers else tuple(
                    tuple(c.requests.items())
                    for c in pod.spec.init_containers
                ),
            )
            hit = self._req_memo.get(rk)
            if hit is None or hit[0].shape[0] != self.dims.R:
                if len(self._req_memo) > 4096:
                    self._req_memo.clear()
                hit = (self._req_vector(pod.resource_request()), self._nonzero(pod))
                self._req_memo[rk] = hit
            req, nonzero = hit
            ports = self._pod_ports(pod)
            disk_check, disk_adv, vcounts, cnt_ids = self._pod_vols(pod)
            rec = _PodRecord(
                key=key,
                labels=dict(pod.labels),
                ns=pod.namespace,
                node_row=node_row,
                m=m,
                req=req,
                nonzero=nonzero,
                ports=ports,
                disk_vols=disk_adv,
                vol_counts=vcounts,
                cnt_vols=cnt_ids,
                priority=pod.spec.priority,
                pod=pod,
                start_time=pod.status.start_time,
                uid=pod.metadata.uid,
            )
            self.pods[key] = rec
            recs.append(rec)
            rows.append(node_row)
            ns_ids.append(self.interner.intern(pod.namespace))
            for k, v in pod.labels.items():
                kid = self.interner.intern(k)
                tgt = label_writes.setdefault(kid, ([], []))
                tgt[0].append(m)
                tgt[1].append(self.interner.intern(v))
            # term registration stays IN the per-pod pass: it interns the
            # term's selector/topology strings, and id assignment must
            # follow add_pod's per-pod order (ns, labels, terms) or
            # novel-string batches diverge from the per-pod loop in every
            # interned-id-bearing tensor
            self._register_pod_terms(pod, rec)
            if node_row >= 0:
                self._row_pods.setdefault(node_row, set()).add(key)
                if ports:
                    for pp_ip in ports:
                        self._node_ports[node_row][pp_ip] += 1
                    touched_ports.add(node_row)
                if disk_adv:
                    for dv in disk_adv:
                        self._node_disk_vols[node_row][dv] += 1
                    touched_vols.add(node_row)
                if cnt_ids:
                    cnts = self._node_cnt_vols.get(node_row)
                    if cnts is None:
                        cnts = self._node_cnt_vols[node_row] = [
                            Counter() for _ in range(self.dims.VT)
                        ]
                    for t, ids in enumerate(cnt_ids):
                        for vid in ids:
                            cnts[t][vid] += 1
                            self._cnt_vol_rows[t].setdefault(vid, set()).add(
                                node_row
                            )
                    vol_rows.add(node_row)
        # -- pass 3: vectorized arena writes
        ms_arr = np.asarray(ms, np.intp)
        self.p_alive[ms_arr] = True
        self.p_ns[ms_arr] = np.asarray(ns_ids, np.int32)
        self.p_node[ms_arr] = np.asarray(rows, np.int32)
        for kid, (kms, vids) in label_writes.items():
            col = self._label_cols.get(kid)
            if col is None:
                col = np.full(self._cap_m, PAD, np.int32)
                self._label_cols[kid] = col
            col[np.asarray(kms, np.intp)] = np.asarray(vids, np.int32)
        rows_arr = np.asarray(rows, np.intp)
        on_node = rows_arr >= 0
        if on_node.any():
            req_stack = np.stack([r.req for r in recs])
            nz_stack = np.stack([r.nonzero for r in recs])
            np.add.at(self.a_requested, rows_arr[on_node], req_stack[on_node])
            np.add.at(self.a_nonzero, rows_arr[on_node], nz_stack[on_node])
            # tenant usage columns (ISSUE 14), same ordered-scatter shape
            t_arr = np.asarray(
                [self._ns_row(r.ns) for r in recs], np.intp
            )
            np.add.at(self.a_ns_usage, t_arr[on_node], req_stack[on_node])
        for row in vol_rows:
            cnts = self._node_cnt_vols[row]
            for t in range(self.dims.VT):
                self.a_volcnt[row, t] = len(cnts[t])
        for row in touched_ports:
            self._rebuild_node_ports(row)
        for row in touched_vols:
            self._rebuild_node_vols(row)
        for rec in recs:
            self._mark_pod_dirty(rec.node_row)
        self._gc_dirty = True
        self.generation += len(pods)

    def remove_pod(self, pod: Pod) -> None:
        key = (pod.namespace, pod.name)
        rec = self.pods.pop(key, None)
        if rec is None:
            return
        m = rec.m
        self.p_alive[m] = False
        self.p_ns[m] = PAD
        self.p_node[m] = PAD
        for col in self._label_cols.values():
            col[m] = PAD
        self._free_m.append(m)
        row = rec.node_row
        if row >= 0:
            self._row_pods.get(row, set()).discard(key)
            self.a_requested[row, : rec.req.shape[0]] -= rec.req
            self.a_nonzero[row] -= rec.nonzero
            self.a_ns_usage[
                self._ns_row(rec.ns), : rec.req.shape[0]
            ] -= rec.req
            if rec.ports:  # rebuilds are row-wide sorts: skip when untouched
                c = self._node_ports[row]
                for pp_ip in rec.ports:
                    c[pp_ip] -= 1
                    if c[pp_ip] <= 0:
                        del c[pp_ip]
                self._rebuild_node_ports(row)
            if rec.disk_vols:
                c = self._node_disk_vols[row]
                for dv in rec.disk_vols:
                    c[dv] -= 1
                    if c[dv] <= 0:
                        del c[dv]
                self._rebuild_node_vols(row)
            cnts = self._node_cnt_vols.get(row)
            if cnts is not None:
                for t, ids in enumerate(rec.cnt_vols):
                    for vid in ids:
                        cnts[t][vid] -= 1
                        if cnts[t][vid] <= 0:
                            del cnts[t][vid]
                            rows = self._cnt_vol_rows[t].get(vid)
                            if rows is not None:
                                rows.discard(row)
                                if not rows:
                                    del self._cnt_vol_rows[t][vid]
                    self.a_volcnt[row, t] = len(cnts[t])
        self._unregister_pod_terms(rec)
        self._mark_pod_dirty(row)
        self._gc_dirty = True
        self.generation += 1

    # ------------------------------------------------- affinity term grouping

    def _iter_pod_terms(self, pod: Pod):
        aff = pod.spec.affinity
        if aff is None:
            return
        if aff.pod_anti_affinity:
            for t in aff.pod_anti_affinity.required:
                yield K_ANTI_REQ, 1.0, t
            for wt in aff.pod_anti_affinity.preferred:
                yield K_ANTI_PREF, float(wt.weight), wt.term
        if aff.pod_affinity:
            for t in aff.pod_affinity.required:
                yield K_AFF_REQ, 1.0, t
            for wt in aff.pod_affinity.preferred:
                yield K_AFF_PREF, float(wt.weight), wt.term

    def _term_sig(self, kind: int, weight: float, term: PodAffinityTerm, pod_ns: str):
        namespaces = frozenset(term.namespaces or (pod_ns,))
        sel = _sel_requirements(term.label_selector)
        sel_key = tuple(sel.requirements) if sel is not None else None
        return (kind, weight, term.topology_key, namespaces, sel_key)

    def _register_pod_terms(self, pod: Pod, rec: _PodRecord) -> None:
        for kind, weight, term in self._iter_pod_terms(pod):
            if not term.topology_key:
                continue
            kid = self.register_topology_key(term.topology_key)
            sig = self._term_sig(kind, weight, term, pod.namespace)
            g = self.term_groups.get(sig)
            if g is None:
                sel = _sel_requirements(term.label_selector)
                g = _TermGroup(
                    kind=kind,
                    topo_key_id=kid,
                    namespaces=frozenset(term.namespaces or (pod.namespace,)),
                    selector=sel if sel is not None else klabels.Selector(()),
                    weight=weight,
                    pair_counts=np.zeros(self.dims.TP, np.float32),
                )
                self.term_groups[sig] = g
            g.members += 1
            if rec.node_row >= 0:
                pid = self._node_pair_id[kid][rec.node_row]
                if pid >= 0:
                    g.pair_counts[pid] += 1
            rec.group_refs.append(sig)

    def _shift_pod_pairs(self, rec: _PodRecord, add: bool) -> None:
        """Add/remove rec's term-group pair contributions for its current
        node_row (used when the pod's node assignment or the node's topology
        labels change, without touching group membership)."""
        if rec.node_row < 0:
            return
        delta = 1.0 if add else -1.0
        for sig in rec.group_refs:
            g = self.term_groups.get(sig)
            if g is None:
                continue
            pid = self._node_pair_id[g.topo_key_id][rec.node_row]
            if pid >= 0:
                g.pair_counts[pid] += delta

    def _unregister_pod_terms(self, rec: _PodRecord) -> None:
        for sig in rec.group_refs:
            g = self.term_groups.get(sig)
            if g is None:
                continue
            g.members -= 1
            if rec.node_row >= 0:
                pid = self._node_pair_id[g.topo_key_id][rec.node_row]
                if pid >= 0:
                    g.pair_counts[pid] -= 1
            if g.members <= 0:
                del self.term_groups[sig]

    # -------------------------------------------------------------- storage

    def add_pv(self, pv) -> None:
        self.pvs[pv.name] = pv
        self.generation += 1

    def remove_pv(self, name: str) -> None:
        self.pvs.pop(name, None)
        self.generation += 1

    def add_pvc(self, pvc) -> None:
        self.pvcs[(pvc.namespace, pvc.name)] = pvc
        self.generation += 1

    def remove_pvc(self, namespace: str, name: str) -> None:
        self.pvcs.pop((namespace, name), None)
        self.generation += 1

    def add_storage_class(self, sc) -> None:
        self.storage_classes[sc.name] = sc
        self.generation += 1

    def remove_storage_class(self, name: str) -> None:
        self.storage_classes.pop(name, None)
        self.generation += 1

    def _rows_matching_pv_topology(self, pv) -> List[int]:
        """Node rows compatible with a PV's nodeAffinity (exact host-side
        evaluation — ref volumebinder checking PV.spec.nodeAffinity)."""
        from kubernetes_tpu.cpuref.reference import match_node_selector_term

        rows = []
        for name, row in self.node_rows.items():
            node = self._row_node[row]
            if pv.node_affinity is not None:
                if not any(
                    match_node_selector_term(t, node)
                    for t in pv.node_affinity.terms
                ):
                    continue
            rows.append(row)
        return rows

    def _rows_matching_pv_zone(self, pv) -> Optional[List[int]]:
        """Node rows matching the PV's zone/region labels, or None if the PV
        carries no zone labels (no restriction) — ref predicates.go
        NoVolumeZoneConflict (:616-741); multi-zone PV label values use the
        "__" separator (volumehelpers.LabelZonesToSet)."""
        restricting = {}
        for key in (HOSTNAME_KEY, ZONE_KEY, REGION_KEY):
            val = pv.labels.get(key)
            if val is not None:
                restricting[key] = set(val.split("__"))
        if not restricting:
            return None
        rows = []
        for name, row in self.node_rows.items():
            node = self._row_node[row]
            if all(node.labels.get(k) in vs for k, vs in restricting.items()):
                rows.append(row)
        return rows

    def _rows_to_pairs(self, rows: List[int]) -> np.ndarray:
        pairs = np.zeros(self.dims.TP, bool)
        col = self._node_pair_id[self.hostname_key]
        for r in rows:
            pid = col[r]
            if pid >= 0:
                pairs[pid] = True
        return pairs

    def _candidate_pvs(self, pvc) -> List[object]:
        """Available PVs that could satisfy an unbound claim (class, size,
        access modes) — the volume binder's FindPodVolumes matching."""
        out = []
        for pv in self.pvs.values():
            if pv.phase not in ("Available",):
                continue
            if pv.storage_class != pvc.storage_class:
                continue
            if pvc.request is not None and pv.capacity is not None and pv.capacity < pvc.request:
                continue
            if pvc.access_modes and not set(pvc.access_modes) <= set(pv.access_modes):
                continue
            out.append(pv)
        return out

    def _pod_volume_terms(self, pod: Pod):
        """(zone_terms, bind_terms, fail_all): per-PVC topology restrictions
        as hostname-pair sets.  (Attachment-type counts are handled by
        _pod_vols, which both add_pod and encode_pods use.)"""
        zone_terms: List[np.ndarray] = []
        bind_terms: List[np.ndarray] = []
        fail_all = False
        for v in pod.spec.volumes:
            claim = v.get("persistentVolumeClaim")
            if not claim:
                continue
            pvc = self.pvcs.get((pod.namespace, claim.get("claimName", "")))
            if pvc is None:
                fail_all = True  # missing PVC: unschedulable (ErrMissingPVC)
                continue
            if pvc.volume_name:
                pv = self.pvs.get(pvc.volume_name)
                if pv is None:
                    fail_all = True
                    continue
                zrows = self._rows_matching_pv_zone(pv)
                if zrows is not None:
                    zone_terms.append(self._rows_to_pairs(zrows))
                if pv.node_affinity is not None:
                    bind_terms.append(
                        self._rows_to_pairs(self._rows_matching_pv_topology(pv))
                    )
            else:
                sc = self.storage_classes.get(pvc.storage_class)
                cands = self._candidate_pvs(pvc)
                if cands:
                    allowed = np.zeros(self.dims.TP, bool)
                    for pv in cands:
                        rows = self._rows_matching_pv_topology(pv)
                        zrows = self._rows_matching_pv_zone(pv)
                        if zrows is not None:
                            rows = [r for r in rows if r in set(zrows)]
                        allowed |= self._rows_to_pairs(rows)
                    bind_terms.append(allowed)
                elif sc is not None and sc.provisioner:
                    # dynamic provisioning: WaitForFirstConsumer defers to
                    # the chosen node; Immediate will provision anywhere
                    pass
                else:
                    fail_all = True
        return zone_terms, bind_terms, fail_all

    # ------------------------------------------------------------- spreading

    def set_service_affinity_keys(self, key_ids: Sequence[int]) -> None:
        """Configure the CheckServiceAffinity homogeneity labels (Policy
        serviceAffinity argument, predicates.go:993-1067)."""
        self.service_affinity_keys = list(key_ids)
        self._pod_row_cache.clear()

    def adopt_filter_config(self, cfg):
        """Normalize a FilterConfig against THIS encoder: intern any
        still-string service-affinity labels and register the keys so
        encode_pods emits the candidate columns.  Returns the (possibly
        replaced) config — the single entry point for runtime components
        (Scheduler, ExtenderServer)."""
        if cfg.service_affinity_labels:
            import dataclasses as _dc

            ids = tuple(
                self.interner.intern(x) if isinstance(x, str) else int(x)
                for x in cfg.service_affinity_labels
            )
            if ids != tuple(cfg.service_affinity_labels):
                cfg = _dc.replace(cfg, service_affinity_labels=ids)
            self.set_service_affinity_keys(ids)
        return cfg

    def add_spread_selector(self, namespace: str, match_labels: Dict[str, str],
                            kind: str = "Service") -> None:
        """Register a Service/RC/RS/StatefulSet selector for SelectorSpread
        (ref priorities/selector_spreading.go getSelectors).  `kind` matters
        to CheckServiceAffinity, whose backfill gate counts only Services
        (GetPodServices, predicates.go:978)."""
        self._spread.append((namespace, klabels.selector_from_match_labels(match_labels)))
        self._spread_kinds.append(kind)
        if kind == "Service":
            self._service_selectors.append((namespace, dict(match_labels)))
        if len(self._spread) > self.dims.G:
            self.dims = self.dims.bump(G=len(self._spread))
        self._gc_dirty = True
        self.generation += 1

    def _match_selector_vec(
        self, sel: klabels.Selector, ns_ids: Optional[Sequence[int]]
    ) -> np.ndarray:
        """Vectorized selector match over the existing-pod arena -> bool[M]."""
        m = self.p_alive.copy()
        if ns_ids is not None:
            m &= np.isin(self.p_ns, np.asarray(list(ns_ids), np.int32))
        for r in sel.requirements:
            kid = self.interner.lookup(r.key)
            col = self._label_cols.get(kid) if kid >= 0 else None
            if col is None:
                vals = np.full(self._cap_m, PAD, np.int32)
            else:
                vals = col
            if r.operator == klabels.IN:
                ids = [self.interner.lookup(v) for v in r.values]
                m &= np.isin(vals, np.asarray([i for i in ids if i >= 0] or [-2], np.int32))
            elif r.operator == klabels.NOT_IN:
                ids = [self.interner.lookup(v) for v in r.values]
                m &= ~np.isin(vals, np.asarray([i for i in ids if i >= 0] or [-2], np.int32))
            elif r.operator == klabels.EXISTS:
                m &= vals != PAD
            elif r.operator == klabels.DOES_NOT_EXIST:
                m &= vals == PAD
            else:  # Gt/Lt: rare — fall back to per-pod python
                keep = np.zeros(self._cap_m, bool)
                for rec in self.pods.values():
                    keep[rec.m] = r.matches(rec.labels)
                m &= keep
        return m

    # ------------------------------------------------------------- snapshot

    # ClusterTensors field -> arena attribute, split by what dirties them:
    # pod commits touch only the aggregate fields, node events touch every
    # per-row field of the affected row.
    _POD_FIELDS = (
        ("requested", "a_requested"), ("nonzero_req", "a_nonzero"),
        ("vol_counts", "a_volcnt"), ("port_pp", "a_ppp"),
        ("port_ip", "a_pip"), ("port_used", "a_pused"),
        ("disk_vol_ids", "a_dvol"),
    )
    _NODE_FIELDS = (
        ("allocatable", "a_allocatable"), ("valid", "a_valid"),
        ("unschedulable", "a_unsched"), ("not_ready", "a_notready"),
        ("mem_pressure", "a_mempress"), ("disk_pressure", "a_diskpress"),
        ("pid_pressure", "a_pidpress"), ("node_name_id", "a_name"),
        ("label_keys", "a_lkeys"), ("label_vals", "a_lvals"),
        ("label_nums", "a_lnums"), ("taint_key", "a_tkey"),
        ("taint_val", "a_tval"), ("taint_effect", "a_teff"),
        ("topo_pairs", "a_topo"), ("image_id", "a_img_id"),
        ("avoid_owner", "a_avoid"), ("vol_limits", "a_vollim"),
    )

    def _pair_topo_key_arr(self) -> np.ndarray:
        pk = np.full(self.dims.TP, PAD, np.int32)
        if self._pair_topo_key:
            pk[: len(self._pair_topo_key)] = np.asarray(self._pair_topo_key, np.int32)
        return pk

    def _image_size_arr(self) -> np.ndarray:
        # image spread scaling (image_locality.go scaledImageScore):
        # scaled = size * numNodesWithImage / totalNodes
        total = max(len(self.node_rows), 1)
        scale = np.ones_like(self.a_img_sz)
        ids = self.a_img_id
        if self._image_nodes:
            lut = np.zeros(len(self.interner), np.float32)
            for name, cnt in self._image_nodes.items():
                iid = self.interner.lookup(name)
                if iid >= 0:
                    lut[iid] = cnt / total
            scale = np.where(ids >= 0, lut[np.maximum(ids, 0)], 0.0)
        return (self.a_img_sz * scale).astype(np.float32)

    def snapshot(self, full: bool = False) -> ClusterTensors:
        """Point-in-time ClusterTensors.  Incremental by default per the
        class docstring's dirty-row contract (cow re-encode of dirty rows,
        identity-reuse of untouched fields — treat the arrays as
        immutable); `full=True` forces a from-scratch rebuild."""
        if full or self._snap is None or self._snap_dirty_all:
            snap = self._snapshot_full()
            self._snap_rows_acc = None  # consumer must full-sync
        else:
            snap = self._snapshot_incremental()
        self._snap = snap
        self._snap_dirty_all = False
        self._dirty_node_rows.clear()
        self._dirty_pod_rows.clear()
        self._gc_dirty = False
        self._snap_pairs_len = len(self._pair_topo_key)
        return snap

    def _snapshot_full(self) -> ClusterTensors:
        fields = {
            name: getattr(self, attr).copy()
            for name, attr in self._POD_FIELDS + self._NODE_FIELDS
        }
        return ClusterTensors(
            # per-group per-node matching-pod counts: the device-side source
            # for SelectorSpread when the batch is spread-lean (every pod in
            # <= 1 group); multi-group batches ship exact AND counts in
            # PodBatch.spread_counts instead
            group_counts=self._group_counts(),
            pair_topo_key=self._pair_topo_key_arr(),
            image_size=self._image_size_arr(),
            **fields,
        )

    def _snapshot_incremental(self) -> ClusterTensors:
        prev = self._snap
        node_d = self._dirty_node_rows
        pod_d = self._dirty_pod_rows | node_d
        changed: Dict[str, np.ndarray] = {}

        def cow(spec, rows_idx):
            for name, attr in spec:
                src = getattr(self, attr)
                new = getattr(prev, name).copy()
                new[rows_idx] = src[rows_idx]
                changed[name] = new

        if pod_d:
            cow(self._POD_FIELDS, np.asarray(sorted(pod_d), np.intp))
        if node_d:
            cow(self._NODE_FIELDS, np.asarray(sorted(node_d), np.intp))
            # the per-image scale divides by the node count, so any node
            # event rescales every row
            changed["image_size"] = self._image_size_arr()
        if self._gc_dirty or prev.group_counts.shape != (self._cap_n, self.dims.G):
            changed["group_counts"] = self._group_counts()
        if len(self._pair_topo_key) != self._snap_pairs_len:
            changed["pair_topo_key"] = self._pair_topo_key_arr()
        if self._snap_rows_acc is not None:
            self._snap_rows_acc |= pod_d
        if not changed:
            return prev
        return dataclasses.replace(prev, **changed)

    def row_name(self, row: int) -> str:
        """Node name for an arena row (O(1); _row_node is kept consistent by
        add/update/remove_node)."""
        node = self._row_node.get(row)
        return node.name if node is not None else ""

    def pods_snapshot(self) -> "PodsArena":
        """Per-pod device tensors for preemption what-ifs: the assigned-pod
        arena as a PodsArena view (node_row, priority, req, nonzero, valid,
        start, keys, uids).

        M is the padded pod capacity; `keys` maps arena index -> (ns, name)
        and `uids` -> metadata.uid for decoding victim picks on the host."""
        M = self._cap_m
        node = np.full(M, PAD, np.int32)
        prio = np.zeros(M, np.int32)
        req = np.zeros((M, self.dims.R), np.float32)
        nz = np.zeros((M, 2), np.float32)
        valid = np.zeros(M, bool)
        # f64: epoch-second timestamps quantize to ~128s in f32; device
        # kernels receive dense RANKS (models.preemption.dense_start_ranks)
        start = np.zeros(M, np.float64)
        keys: List = [None] * M
        uids: List = [""] * M
        for rec in self.pods.values():
            m = rec.m
            node[m] = rec.node_row
            prio[m] = rec.priority
            req[m, : rec.req.shape[0]] = rec.req
            nz[m] = rec.nonzero
            valid[m] = rec.node_row >= 0
            start[m] = rec.start_time
            keys[m] = rec.key
            uids[m] = rec.uid
        return PodsArena(node, prio, req, nz, valid, start, keys, uids)

    def preemption_arrays(self, pod: Pod, max_vols=(39.0, 16.0, 1e9, 16.0, 1e9)):
        """Extended what-if arrays for models.preemption.preempt_one.

        selectVictimsOnNode re-runs all predicates after victim removal
        (generic_scheduler.go:1054-1128); the resolvable ones with per-pod
        device state — resources, host ports, disk conflicts, volume-count
        budgets — fold into one `used - freed + req <= allocatable` check by
        appending columns to the resource axis:

          col R     : count of pods whose host ports conflict with `pod`
                      (limit 0.5, pod "requests" 0.25 -> remaining must be 0)
          col R+1   : count of pods holding one of `pod`'s exclusive disk
                      volumes (same encoding)
          col R+2.. : the five Max*VolumeCount budgets

        Returns (pod_req_ext f32[E], requested_ext f32[N, E],
        allocatable_ext f32[N, E], pods_req_ext f32[M, E])."""
        # _pod_vols can grow dims.VT (first-seen CSI driver): call it
        # BEFORE sizing the ext arrays (the encode_pods pre-registration
        # discipline)
        want_ports = self._pod_ports(pod)
        want_disk, _, new_vols, _ = self._pod_vols(pod)
        R = self.dims.R
        E = R + 2 + self.dims.VT
        M, N = self._cap_m, self._cap_n
        want_disk_set = set(want_disk)

        pods_ext = np.zeros((M, E), np.float32)
        for rec in self.pods.values():
            m = rec.m
            pods_ext[m, : rec.req.shape[0]] = rec.req
            if want_ports and rec.node_row >= 0:
                for pp, ip in rec.ports:
                    if any(
                        pp == wpp and (ip == wip or ip == WILDCARD or wip == WILDCARD)
                        for wpp, wip in want_ports
                    ):
                        pods_ext[m, R] = 1.0
                        break
            if want_disk_set and rec.node_row >= 0:
                if any(dv in want_disk_set for dv in rec.disk_vols):
                    pods_ext[m, R + 1] = 1.0
            pods_ext[m, R + 2 :] = rec.vol_counts

        requested_ext = np.zeros((N, E), np.float32)
        requested_ext[:, :R] = self.a_requested
        arena_nodes = np.array(
            [rec.node_row for rec in self.pods.values()], np.int32
        ).reshape(-1)
        arena_ms = np.array([rec.m for rec in self.pods.values()], np.int32).reshape(-1)
        if len(arena_ms):
            on_node = arena_nodes >= 0
            np.add.at(
                requested_ext[:, R], arena_nodes[on_node], pods_ext[arena_ms[on_node], R]
            )
            np.add.at(
                requested_ext[:, R + 1],
                arena_nodes[on_node],
                pods_ext[arena_ms[on_node], R + 1],
            )
        # the pending pod's volumes already attached on a node consume no
        # NEW attachment there (filterVolumes already-mounted subtraction):
        # credit them against the node's distinct-attached counts
        requested_ext[:, R + 2 :] = np.maximum(
            self.a_volcnt - self._vol_overlap([pod])[0].T, 0.0
        )

        allocatable_ext = np.zeros((N, E), np.float32)
        allocatable_ext[:, :R] = self.a_allocatable
        allocatable_ext[:, R] = 0.5
        allocatable_ext[:, R + 1] = 0.5
        defaults = np.asarray(max_vols, np.float32)
        if defaults.shape[0] < self.dims.VT:
            # per-CSI-driver columns inherit the CSI default cap
            defaults = np.concatenate([
                defaults,
                np.full(self.dims.VT - defaults.shape[0],
                        float(max_vols[VOL_CSI]), np.float32),
            ])
        allocatable_ext[:, R + 2 :] = np.minimum(defaults[None], self.a_vollim)

        pod_req_ext = np.zeros(E, np.float32)
        req = self._req_vector(pod.resource_request())
        pod_req_ext[: req.shape[0]] = req
        pod_req_ext[R] = 0.25 if want_ports else 0.0
        pod_req_ext[R + 1] = 0.25 if want_disk_set else 0.0
        pod_req_ext[R + 2 :] = new_vols
        return pod_req_ext, requested_ext, allocatable_ext, pods_ext

    def victim_volume_tables(self, slots):
        """Identity-deduped volume-credit tables for the preemption what-if
        (VERDICT r4 #4 — closes PARITY §3's linear-subtraction over-credit):
        victims sharing one volume must free ONE attachment, and a volume
        also held by a non-victim frees none.

        Per distinct (node, type, volume-id) held by a LISTED victim:
          vid_total[j]  — holders on the node among ALL assigned pods
          vid_listed[j] — holders among the listed victims
        A volume is freed iff every holder is evicted (evicted == total);
        the reprieve scan decrements evicted counts as victims return.
        Arrays carry one sentinel tail slot (total 2^30, never full) that
        out-of-range gathers hit.

        Returns (slot_vids i32[Kv, VMAX] aligned row-for-row with `slots`,
        vid_type i32[VID+1], vid_total i32[VID+1], vid_listed i32[VID+1],
        freed_vol_init f32[N, VT])."""
        N, VT = self._cap_n, self.dims.VT
        m_to_rec = {rec.m: rec for rec in self.pods.values()}
        vid_index: Dict[tuple, int] = {}
        vid_type: List[int] = []
        vid_total: List[int] = []
        vid_listed: List[int] = []
        per_slot: List[List[int]] = []
        for s in np.asarray(slots).tolist():
            vids: List[int] = []
            rec = m_to_rec.get(int(s)) if s >= 0 else None
            if rec is not None and rec.cnt_vols and rec.node_row >= 0:
                cnts = self._node_cnt_vols.get(rec.node_row)
                for t, ids in enumerate(rec.cnt_vols):
                    for vid in ids:
                        keyv = (rec.node_row, t, vid)
                        j = vid_index.get(keyv)
                        if j is None:
                            j = vid_index[keyv] = len(vid_type)
                            vid_type.append(t)
                            vid_total.append(
                                int(cnts[t][vid]) if cnts else 1)
                            vid_listed.append(0)
                        vid_listed[j] += 1
                        vids.append(j)
            per_slot.append(vids)
        vmax = 1
        while vmax < max((len(v) for v in per_slot), default=1):
            vmax *= 2
        nv = 1
        while nv < max(len(vid_type), 1):
            nv *= 2
        slot_vids = np.full((len(per_slot), vmax), -1, np.int32)
        for i, vids in enumerate(per_slot):
            slot_vids[i, : len(vids)] = vids
        t_arr = np.full(nv + 1, VT, np.int32)      # sentinel type -> dropped
        t_arr[: len(vid_type)] = vid_type
        tot = np.full(nv + 1, 1 << 30, np.int32)   # sentinel never full
        tot[: len(vid_total)] = vid_total
        lst = np.zeros(nv + 1, np.int32)
        lst[: len(vid_listed)] = vid_listed
        freed_vol_init = np.zeros((N, VT), np.float32)
        for (row, t, _vid), j in vid_index.items():
            if vid_listed[j] >= vid_total[j]:
                freed_vol_init[row, t] += 1.0
        return slot_vids, t_arr, tot, lst, freed_vol_init

    def has_required_pod_terms(self) -> bool:
        """Any live required (anti-)affinity term in the cluster — the
        condition under which the counting preemption what-if cannot be
        trusted alone and the object-level nomination verify must run."""
        return any(
            g.members > 0 and g.kind in (K_ANTI_REQ, K_AFF_REQ)
            for g in self.term_groups.values()
        )

    # ------------------------------------------------------------ pod batch

    def batch_pad(self, n: int) -> int:
        """Effective pod-batch pad width for an n-pod batch: the transient
        batch_width() override when one is active (never growing dims.B),
        else the sticky pow2 floor dims.B.  EVERY batch-shaped tensor cut
        for one encode must use this (encode_pods, _vol_overlap, and the
        models/batched.py port/affinity helpers) or shapes diverge between
        the batch leaves and the engine retraces per cycle."""
        if self._batch_width is not None:
            return _pow2(max(n, 1), self._batch_width)
        return _pow2(max(n, 1), max(self.dims.B, 1))

    @contextlib.contextmanager
    def batch_width(self, width: Optional[int]):
        """Context manager pinning the pod-batch pad width for the encode
        calls inside it (width=None is a no-op passthrough).  The express
        lane wraps its encode in batch_width(express_batch_size) so its
        small batches compile once at that shape instead of re-padding to
        the bulk lane's sticky dims.B."""
        prev = self._batch_width
        self._batch_width = width
        try:
            yield self
        finally:
            self._batch_width = prev

    def encode_pods(self, pods: Sequence[Pod]) -> PodBatch:
        """Encode pending pods into a PodBatch, precomputing the
        inter-pod-affinity pair tensors against current cluster state."""
        d = self.dims
        B = self.batch_pad(len(pods))
        if self._batch_width is None and B > d.B:
            self.dims = d = dataclasses.replace(d, B=B)
        # grow per-pod dims to fit
        need = dict(Q=1, TT=1, NS=1, S=1, E=1, V=1, PS=1, PT=1, AT=1, GP=1, C=1,
                    DV=1, VZ=1, VB=1)
        for pod in pods:
            need["Q"] = max(need["Q"], len(pod.host_ports()))
            # pod-side disk-conflict check tokens: one per gce/ebs/iscsi
            # volume, one PER MONITOR for rbd (the overlap identity) — the
            # DV axis must fit them all or conflicts silently vanish
            n_disk = 0
            for v in pod.spec.volumes:
                if "rbd" in v:
                    n_disk += len(v["rbd"].get("monitors", []) or ())
                elif ("gcePersistentDisk" in v or "awsElasticBlockStore" in v
                      or "iscsi" in v):
                    n_disk += 1
            need["DV"] = max(need["DV"], n_disk)
            n_pvc = sum(1 for v in pod.spec.volumes if "persistentVolumeClaim" in v)
            need["VZ"] = max(need["VZ"], n_pvc)
            need["VB"] = max(need["VB"], n_pvc)
            need["TT"] = max(need["TT"], len(pod.spec.tolerations))
            need["NS"] = max(need["NS"], len(pod.spec.node_selector))
            need["C"] = max(need["C"], len(pod.spec.containers))
            aff = pod.spec.affinity
            na = aff.node_affinity if aff else None
            if na and na.required:
                need["S"] = max(need["S"], len(na.required.terms))
                for t in na.required.terms:
                    need["E"] = max(need["E"], len(t.match_expressions) + len(t.match_fields))
                    for e in t.match_expressions:
                        need["V"] = max(need["V"], len(e.values))
            if na:
                need["PS"] = max(need["PS"], len(na.preferred))
                for p in na.preferred:
                    need["E"] = max(need["E"], len(p.preference.match_expressions))
                    for e in p.preference.match_expressions:
                        need["V"] = max(need["V"], len(e.values))
            if aff and aff.pod_affinity:
                need["PT"] = max(need["PT"], len(aff.pod_affinity.required))
            if aff and aff.pod_anti_affinity:
                need["AT"] = max(need["AT"], len(aff.pod_anti_affinity.required))
        bump = {k: v for k, v in need.items() if v > getattr(d, k)}
        if bump:
            self.dims = d = self.dims.bump(**bump)
        # topology keys must be registered before encoding pair tensors, and
        # extended-resource columns before the out arrays are allocated
        # (a mid-loop dims.R bump would orphan the already-allocated arrays)
        for pod in pods:
            for _, _, term in self._iter_pod_terms(pod):
                if term.topology_key:
                    self.register_topology_key(term.topology_key)
            # resource column registration needs only the NAMES — iterate
            # container dicts directly instead of summing Quantities
            # (resource_request is exact-Fraction math, ~15us/pod)
            for c in pod.spec.containers:
                for rname in c.requests:
                    self._res_col(rname)
            for c in pod.spec.init_containers:
                for rname in c.requests:
                    self._res_col(rname)
            # CSI driver columns must exist BEFORE the out arrays are cut
            # (same reason as resource columns: a mid-loop dims.VT bump
            # would orphan already-allocated batch arrays)
            for v in pod.spec.volumes:
                claim = v.get("persistentVolumeClaim")
                if not claim:
                    continue
                pvc = self.pvcs.get((pod.namespace, claim.get("claimName", "")))
                if pvc is not None and pvc.volume_name:
                    pv = self.pvs.get(pvc.volume_name)
                    if pv is not None and pv.source_kind == "csi" and pv.csi_driver:
                        self._vol_col(pv.csi_driver)
        d = self.dims
        it = self.interner
        f32, i32 = np.float32, np.int32

        def zi(*shape):
            return np.full(shape, PAD, i32)

        def zf(*shape):
            return np.zeros(shape, f32)

        def zb(*shape):
            return np.zeros(shape, bool)

        # ---- lean widths: the pair tensors are [.., TP] with TP the whole
        # topology-pair vocabulary (hostname pairs dominate: ~1 per node).
        # For a batch with no inter-pod-affinity exposure / no volumes they
        # are provably all-zero, so emit width-1 placeholders instead — the
        # kernels gate on shape (ops/predicates._is_lean) and skip the work.
        # At 5k nodes this removes ~70MB of zero upload per 512-pod batch,
        # the dominant cost through a remote-device tunnel.
        aff_lean = not self.term_groups and not any(
            p.spec.affinity is not None
            and (
                p.spec.affinity.pod_affinity is not None
                or p.spec.affinity.pod_anti_affinity is not None
            )
            for p in pods
        )
        vol_lean = not any(p.spec.volumes for p in pods)
        TPA = 1 if aff_lean else d.TP
        TPV = 1 if vol_lean else d.TP
        SA = max(len(self.service_affinity_keys), 1)
        # node-affinity lean widths: a batch where NO pod carries required /
        # preferred nodeAffinity emits zero-width term tensors, and the
        # selector/affinity kernels skip statically on shape — the expr
        # evaluation is [B, S, E, N, L] work, the single hottest kernel on
        # the CPU fallback for affinity-free workloads
        def _na(p):
            return p.spec.affinity.node_affinity if p.spec.affinity else None

        SL = 0 if not any(
            _na(p) and _na(p).required for p in pods
        ) else d.S
        PSL = 0 if not any(
            _na(p) and _na(p).preferred for p in pods
        ) else d.PS

        out = dict(
            valid=zb(B),
            req=zf(B, d.R),
            nonzero_req=zf(B, 2),
            limits2=zf(B, 2),
            priority=np.zeros(B, i32),
            best_effort=zb(B),
            ns_id=zi(B),
            owner_uid=zi(B),
            node_name_req=zi(B),
            port_pp=zi(B, d.Q),
            port_ip=zi(B, d.Q),
            port_valid=zb(B, d.Q),
            tol_key=zi(B, d.TT),
            tol_op=np.zeros((B, d.TT), i32),
            tol_val=zi(B, d.TT),
            tol_effect=zi(B, d.TT),
            tol_valid=zb(B, d.TT),
            ns_keys=zi(B, d.NS),
            ns_vals=zi(B, d.NS),
            ns_valid=zb(B, d.NS),
            has_req_affinity=zb(B),
            term_valid=zb(B, SL),
            expr_key=zi(B, SL, d.E),
            expr_op=np.zeros((B, SL, d.E), i32),
            expr_vals=zi(B, SL, d.E, d.V),
            expr_nval=np.zeros((B, SL, d.E), i32),
            expr_num=np.full((B, SL, d.E), np.nan, f32),
            expr_valid=zb(B, SL, d.E),
            pref_weight=zf(B, PSL),
            pref_term_valid=zb(B, PSL),
            pref_expr_key=zi(B, PSL, d.E),
            pref_expr_op=np.zeros((B, PSL, d.E), i32),
            pref_expr_vals=zi(B, PSL, d.E, d.V),
            pref_expr_nval=np.zeros((B, PSL, d.E), i32),
            pref_expr_num=np.full((B, PSL, d.E), np.nan, f32),
            pref_expr_valid=zb(B, PSL, d.E),
            forbidden_pairs=zb(B, TPA),
            aff_term_pairs=zb(B, d.PT, TPA),
            aff_term_valid=zb(B, d.PT),
            aff_term_self=zb(B, d.PT),
            aff_term_topo_key=zi(B, d.PT),
            anti_term_pairs=zb(B, d.AT, TPA),
            anti_term_valid=zb(B, d.AT),
            anti_term_topo_key=zi(B, d.AT),
            anti_term_self=zb(B, d.AT),
            pref_pair_weights=zf(B, TPA),
            group_ids=zi(B, d.GP),
            group_valid=zb(B, d.GP),
            svc_aff_fixed=zi(B, SA),
            image_ids=zi(B, d.C),
            image_bytes=zf(B, d.C),
            new_vol_counts=zf(B, d.VT),
            disk_vol_ids=zi(B, d.DV),
            vol_zone_pairs=zb(B, d.VZ, TPV),
            vol_zone_valid=zb(B, d.VZ),
            vol_bind_pairs=zb(B, d.VB, TPV),
            vol_bind_valid=zb(B, d.VB),
            vol_fail_all=zb(B),
        )

        # interner ids are append-only (stable), so only pad-dim or
        # spread-registry changes invalidate cached rows
        # NOTE: SL/PSL in the token means a lean<->full flip flushes the
        # whole row cache; accepted — scheduler batches are formed per
        # cycle from queue order, so affinity presence rarely oscillates,
        # and a flush costs one re-encode, not correctness
        token = (self.dims, len(self._spread), aff_lean, vol_lean, SL, PSL,
                 tuple(self.service_affinity_keys))
        cnt_ids_by_b: dict = {}
        if token != self._pod_cache_token:
            self._pod_row_cache.clear()
            self._pod_cache_token = token

        # cache-hit pods grouped by row key: one broadcast assignment per
        # DISTINCT row per field instead of a per-pod python loop —
        # controller-stamped workloads have ~20 distinct rows across
        # thousands of pods, so this is ~100x fewer numpy calls
        hit_groups: Dict[Tuple, List[int]] = {}
        # CALL-LOCAL row sharing for the pods the cross-call cache must
        # refuse (affinity / live term_groups, where rows depend on cluster
        # state): within one encode_pods call the state is frozen (callers
        # hold the cache lock), so same-content pods share a row.  Keyed by
        # the static key EXTENDED with the affinity content signature;
        # pods with volumes stay per-pod (PVC rows also carry per-call
        # binder assumptions).
        local_first: Dict[Tuple, int] = {}
        local_hits: Dict[int, List[int]] = {}
        for b, pod in enumerate(pods):
            ck = self._pod_static_key(pod)
            cached = self._pod_row_cache.get(ck) if ck is not None else None
            if cached is not None:
                hit_groups.setdefault(ck, []).append(b)
                continue
            lk = self._pod_local_key(pod) if ck is None else None
            if lk is not None:
                first = local_first.get(lk)
                if first is not None:
                    local_hits.setdefault(first, []).append(b)
                    continue
                local_first[lk] = b
            out["valid"][b] = True
            req = self._req_vector(pod.resource_request())
            out["req"][b, : req.shape[0]] = req
            out["nonzero_req"][b] = self._nonzero(pod)
            # summed container limits (ResourceLimitsPriority,
            # priorities/resource_limits.go getResourceLimits)
            lim_cpu = lim_mem = 0.0
            for c in pod.spec.containers:
                if RESOURCE_CPU in c.limits:
                    lim_cpu += c.limits[RESOURCE_CPU].milli
                if RESOURCE_MEMORY in c.limits:
                    lim_mem += float(c.limits[RESOURCE_MEMORY])
            out["limits2"][b] = (lim_cpu, lim_mem)
            out["priority"][b] = pod.spec.priority
            out["best_effort"][b] = all(
                not c.requests and not c.limits for c in pod.spec.containers
            )
            out["ns_id"][b] = it.intern(pod.namespace)
            # NodePreferAvoidPods only applies to RC/RS-owned pods
            # (ref priorities/node_prefer_avoid_pods.go:41-55)
            if pod.metadata.owner_uid and pod.metadata.owner_kind in (
                "ReplicationController",
                "ReplicaSet",
            ):
                out["owner_uid"][b] = it.intern(pod.metadata.owner_uid)
            if pod.spec.node_name:
                out["node_name_req"][b] = it.intern(pod.spec.node_name)
            for j, (pp, ip) in enumerate(self._pod_ports(pod)[: d.Q]):
                out["port_pp"][b, j] = pp
                out["port_ip"][b, j] = ip
                out["port_valid"][b, j] = True
            for j, t in enumerate(pod.spec.tolerations[: d.TT]):
                out["tol_key"][b, j] = it.intern(t.key) if t.key else 0
                out["tol_op"][b, j] = TOL_OP_CODES.get(t.operator, 0)
                out["tol_val"][b, j] = it.intern(t.value)
                out["tol_effect"][b, j] = EFFECT_CODES.get(t.effect, PAD) if t.effect else PAD
                out["tol_valid"][b, j] = True
            for j, (k, v) in enumerate(sorted(pod.spec.node_selector.items())[: d.NS]):
                out["ns_keys"][b, j] = it.intern(k)
                out["ns_vals"][b, j] = it.lookup(v) if it.lookup(v) >= 0 else it.intern(v)
                out["ns_valid"][b, j] = True
            aff = pod.spec.affinity
            na = aff.node_affinity if aff else None
            if na and na.required is not None:
                out["has_req_affinity"][b] = True
                for s, term in enumerate(na.required.terms[: d.S]):
                    out["term_valid"][b, s] = True
                    e = 0
                    for expr in term.match_expressions:
                        if e >= d.E:
                            break
                        self._encode_expr(out, "expr", b, s, e, expr.key, expr.operator, expr.values)
                        e += 1
                    for expr in term.match_fields:
                        if e >= d.E:
                            break
                        # matchFields only supports metadata.name (ref
                        # apis/core/validation: NodeFieldSelectorKeys)
                        self._encode_expr(
                            out, "expr", b, s, e, FIELD_NODE_NAME,
                            expr.operator, expr.values, is_field=True,
                        )
                        e += 1
            if na:
                for s, pterm in enumerate(na.preferred[: d.PS]):
                    out["pref_term_valid"][b, s] = True
                    out["pref_weight"][b, s] = float(pterm.weight)
                    for e, expr in enumerate(pterm.preference.match_expressions[: d.E]):
                        self._encode_expr(
                            out, "pref_expr", b, s, e, expr.key, expr.operator, expr.values
                        )
            self._encode_pod_affinity(out, b, pod)
            for j, kid in enumerate(self.service_affinity_keys):
                v = pod.spec.node_selector.get(it.string(kid))
                if v is not None:
                    out["svc_aff_fixed"][b, j] = it.intern(v)
            gi = 0
            for g, (ns, sel) in enumerate(self._spread):
                if gi >= d.GP:
                    break
                if ns == pod.namespace and sel.matches(pod.labels):
                    out["group_ids"][b, gi] = g
                    out["group_valid"][b, gi] = True
                    gi += 1
            for j, c in enumerate(pod.spec.containers[: d.C]):
                if c.image:
                    out["image_ids"][b, j] = it.lookup(
                        normalized_image(c.image)
                    )
            disk, _, vcounts, cnt_ids = self._pod_vols(pod)
            cnt_ids_by_b[b] = cnt_ids
            out["new_vol_counts"][b] = vcounts
            for j, dv in enumerate(disk[: d.DV]):
                out["disk_vol_ids"][b, j] = dv
            zone_terms, bind_terms, fail_all = self._pod_volume_terms(pod)
            out["vol_fail_all"][b] = fail_all
            for j, pairs in enumerate(zone_terms[: d.VZ]):
                out["vol_zone_pairs"][b, j] = pairs[: d.TP]
                out["vol_zone_valid"][b, j] = True
            for j, pairs in enumerate(bind_terms[: d.VB]):
                out["vol_bind_pairs"][b, j] = pairs[: d.TP]
                out["vol_bind_valid"][b, j] = True
            if ck is not None:
                self._pod_row_cache[ck] = {
                    k: np.copy(v[b]) for k, v in out.items()
                }

        for first, idxs in local_hits.items():
            ia = np.asarray(idxs, np.intp)
            for k, v in out.items():
                v[ia] = v[first]
            if first in cnt_ids_by_b:
                for b2 in idxs:
                    cnt_ids_by_b[b2] = cnt_ids_by_b[first]

        for ck, idxs in hit_groups.items():
            cached = self._pod_row_cache[ck]
            ia = np.asarray(idxs, np.intp)
            for k, v in cached.items():
                out[k][ia] = v

        # state-dependent, so computed fresh every call (outside the row
        # cache): per-node counts of existing pods matching ALL of each pod's
        # spread selectors — countMatchingPods AND semantics
        # (selector_spreading.go:165-187), not one count per selector.
        # Lean form: when every pod belongs to <= 1 spread group, the AND
        # degenerates to that group's column of cluster.group_counts — the
        # device derives counts from the snapshot (selector_spread gates on
        # shape) and the [B, N] host tensor is skipped entirely.
        if not (out["group_valid"].sum(axis=1) > 1).any():
            spread = np.zeros((out["group_ids"].shape[0], 1), np.float32)
        else:
            spread = self._spread_and_counts(out)
        d0, d1 = self._service_affinity_candidates(pods, out)
        return PodBatch(
            **out, spread_counts=spread, svc_aff_d0=d0, svc_aff_d1=d1,
            vol_overlap=self._vol_overlap(pods, cnt_ids_by_b),
        )

    def _vol_overlap(self, pods, cnt_ids_by_b=None) -> np.ndarray:
        """f32[B, VT, N] count of the pod's attachable volumes
        ALREADY mounted on each node (filterVolumes' already-mounted
        subtraction: they add no new attachment); [B, VT, 1] lean
        placeholder when no pod carries volumes.  `cnt_ids_by_b` reuses the
        id sets the encode loop already computed."""
        B = self.batch_pad(len(pods))
        if not any(getattr(p.spec, "volumes", None) for p in pods):
            return np.zeros((B, self.dims.VT, 1), np.float32)
        out = np.zeros((B, self.dims.VT, self._cap_n), np.float32)
        for b, pod in enumerate(pods):
            if not pod.spec.volumes:
                continue
            cnt_ids = (cnt_ids_by_b or {}).get(b)
            if cnt_ids is None:
                _, _, _, cnt_ids = self._pod_vols(pod)
            for t, ids in enumerate(cnt_ids):
                for vid in ids:
                    for row in self._cnt_vol_rows[t].get(vid, ()):
                        out[b, t, row] += 1.0
        return out

    def _service_affinity_candidates(self, pods, out):
        """(d0, d1) i32[B]: first same-namespace arena pod whose labels
        superset-match the pod's own labels (CreateSelectorFromLabels of
        pod.Labels, predicates.go serviceAffinityMetadataProducer), and the
        first such pod on a DIFFERENT node — together they resolve
        FilterOutPods(evaluated node) per node on device.  Gated on some
        service selecting the pod (GetPodServices non-empty)."""
        B = out["group_ids"].shape[0]
        d0 = np.full(B, -1, np.int32)
        d1 = np.full(B, -1, np.int32)
        if not self.service_affinity_keys:
            return d0, d1
        for b, pod in enumerate(pods):
            # gate: some SERVICE selects the pod (GetPodServices; RC/RS/SS
            # spread selectors don't count, predicates.go:978)
            if not any(
                kind == "Service" and ns == pod.namespace
                and sel.matches(pod.labels)
                for (ns, sel), kind in zip(self._spread, self._spread_kinds)
            ):
                continue
            nsid = self.interner.lookup(pod.namespace)
            if nsid < 0:
                continue
            sel = klabels.selector_from_match_labels(pod.labels)
            m = self._match_selector_vec(sel, [nsid])
            nodes = self.p_node[m & (self.p_node >= 0)]
            if nodes.size:
                d0[b] = nodes[0]
                other = nodes[nodes != nodes[0]]
                if other.size:
                    d1[b] = other[0]
        return d0, d1

    def _group_counts(self) -> np.ndarray:
        counts = np.zeros((self._cap_n, self.dims.G), np.float32)
        for gi, (ns, sel) in enumerate(self._spread):
            nsid = self.interner.lookup(ns)
            if nsid < 0:
                continue
            matched = self._match_selector_vec(sel, [nsid])
            nodes = self.p_node[matched]
            nodes = nodes[nodes >= 0]
            if nodes.size:
                counts[:, gi] = np.bincount(
                    nodes, minlength=self._cap_n
                )[: self._cap_n].astype(np.float32)
        return counts

    def _spread_and_counts(self, out) -> np.ndarray:
        """f32[B, N] from the batch's group_ids/group_valid rows: existing
        alive pods per node matching every one of the pod's spread groups
        (a pod with no groups contributes all-zero counts, which the reduce
        maps to the uniform MAX_PRIORITY — the len(selectors)==0 score-0
        path of CalculateSpreadPriorityMap)."""
        B = out["group_ids"].shape[0]
        counts = np.zeros((B, self._cap_n), np.float32)
        mask_cache: Dict[int, np.ndarray] = {}
        for b in range(B):
            gs = out["group_ids"][b][out["group_valid"][b]]
            if gs.size == 0:
                continue
            m = None
            for g in gs:
                g = int(g)
                mg = mask_cache.get(g)
                if mg is None:
                    ns, sel = self._spread[g]
                    nsid = self.interner.lookup(ns)
                    mg = (
                        self._match_selector_vec(sel, [nsid])
                        if nsid >= 0
                        else np.zeros(self._cap_m, bool)
                    )
                    mask_cache[g] = mg
                m = mg if m is None else (m & mg)
            nodes = self.p_node[m]
            nodes = nodes[nodes >= 0]
            if nodes.size:
                counts[b] = np.bincount(
                    nodes, minlength=self._cap_n
                )[: self._cap_n].astype(np.float32)
        return counts

    def _pod_key_base(self, pod: Pod):
        """The shared content-key body both caching keys build on: every
        non-affinity pod attribute an encoded row depends on.  Raises
        TypeError for unhashable content (callers translate to None)."""
        return (
            pod.namespace,
            tuple(sorted(pod.labels.items())),
            tuple(sorted(pod.spec.node_selector.items())),
            # the *resolved* image id goes into the key: a lookup miss
            # (image not yet on any node) must not freeze ImageLocality
            # at 0 once the image appears and gets interned
            # Quantity is a frozen dataclass over Fraction: hashable and
            # ordered, so the exact objects key the row directly (str()
            # round-trips cost Fraction formatting, ~10us/pod)
            tuple(
                (self.interner.lookup(normalized_image(c.image)),
                 tuple(sorted(c.requests.items())),
                 # limits participate in the row (limits2, best_effort):
                 # two pods differing only in limits must not share a row
                 tuple(sorted(c.limits.items())),
                 tuple(c.ports))
                for c in pod.spec.containers
            ),
            tuple(
                (c.image,
                 tuple(sorted(c.requests.items())),
                 tuple(sorted(c.limits.items())))
                for c in pod.spec.init_containers
            ),
            pod.spec.tolerations,
            pod.spec.node_name,
            pod.spec.priority,
            pod.metadata.owner_uid,
            pod.metadata.owner_kind,
        )

    def _pod_local_key(self, pod: Pod):
        """Key for CALL-LOCAL row sharing (encode_pods): the cross-call
        gate fields (affinity content) join the shared key base, since
        within one call the cluster state every row depends on is frozen.
        Pods with volumes return None — their rows also carry per-call
        binder assumptions keyed by pod identity (CheckVolumeBinding
        assume bookkeeping), so sharing could alias distinct claims."""
        if pod.spec.volumes:
            return None

        def _ts(t):
            # canonical selector form — the same _sel_requirements
            # canonicalization _term_sig uses, so semantically identical
            # terms (matchLabels vs equivalent matchExpressions) share
            sel = _sel_requirements(t.label_selector)
            sel_key = tuple(sel.requirements) if sel is not None else None
            return (sel_key, t.topology_key, frozenset(t.namespaces))

        aff = pod.spec.affinity
        try:
            if aff is None:
                aff_sig = None
            else:
                pa, paa = aff.pod_affinity, aff.pod_anti_affinity
                aff_sig = (
                    aff.node_affinity,  # frozen dataclasses: hashable
                    None if pa is None else (
                        tuple(_ts(t) for t in pa.required),
                        tuple((w.weight, _ts(w.term)) for w in pa.preferred),
                    ),
                    None if paa is None else (
                        tuple(_ts(t) for t in paa.required),
                        tuple((w.weight, _ts(w.term)) for w in paa.preferred),
                    ),
                )
            return (aff_sig,) + self._pod_key_base(pod)
        except TypeError:
            return None

    def _pod_static_key(self, pod: Pod):
        """Cache key for state-independent pods; None disables caching.

        A pod with no affinity of its own is still state-dependent when ANY
        existing pod carries (anti-)affinity terms: its forbidden_pairs /
        pref_pair_weights rows come from matching those terms, whose pair
        counts move with every placement."""
        if pod.spec.affinity is not None or pod.spec.volumes or self.term_groups:
            return None
        try:
            return self._pod_key_base(pod)
        except TypeError:
            return None

    def _encode_expr(self, out, prefix, b, s, e, key, op, values,
                     is_field: bool = False) -> None:
        it = self.interner
        out[f"{prefix}_key"][b, s, e] = it.intern(key)
        out[f"{prefix}_op"][b, s, e] = SEL_OP_CODES[op]
        out[f"{prefix}_valid"][b, s, e] = True
        if not is_field and klabels.requirement_is_unbuildable(key, op, values):
            # the requirement cannot be built (NodeSelectorRequirements
            # AsSelector errors), so the TERM never matches — encode as
            # In-with-no-values (matches nothing); matchFields exempt
            out[f"{prefix}_op"][b, s, e] = SEL_OP_CODES[klabels.IN]
            out[f"{prefix}_nval"][b, s, e] = 0
            return
        if op in (klabels.GT, klabels.LT):
            try:
                out[f"{prefix}_num"][b, s, e] = float(int(values[0]))
            except (ValueError, IndexError):
                out[f"{prefix}_num"][b, s, e] = np.nan
        else:
            nv = 0
            for v in values[: out[f"{prefix}_vals"].shape[-1]]:
                vid = it.lookup(v)
                out[f"{prefix}_vals"][b, s, e, nv] = vid if vid >= 0 else it.intern(v)
                nv += 1
            out[f"{prefix}_nval"][b, s, e] = nv

    def _matches_one(self, sel: klabels.Selector, namespaces: frozenset, pod: Pod) -> bool:
        return pod.namespace in namespaces and sel.matches(pod.labels)

    def _term_pairs(self, term: PodAffinityTerm, pod_ns: str) -> Tuple[np.ndarray, int]:
        """f32[TP] count of existing pods matching `term` per topology pair
        (counts matter: the priority adds weight once per matching pod,
        ref priorities/interpod_affinity.go processExistingPod)."""
        kid = self.interner.lookup(term.topology_key)
        pairs = np.zeros(self.dims.TP, np.float32)
        sel = _sel_requirements(term.label_selector)
        if sel is None or kid < 0:
            return pairs, kid
        ns_ids = [
            self.interner.lookup(n)
            for n in (term.namespaces or (pod_ns,))
            if self.interner.lookup(n) >= 0
        ]
        if not ns_ids:
            return pairs, kid
        matched = self._match_selector_vec(sel, ns_ids)
        nodes = self.p_node[matched]
        nodes = nodes[nodes >= 0]
        if nodes.size:
            pids = self._node_pair_id[kid][nodes]
            pids = pids[pids >= 0]
            if pids.size:
                pairs += np.bincount(pids, minlength=self.dims.TP).astype(np.float32)
        return pairs, kid

    def _encode_pod_affinity(self, out, b: int, pod: Pod) -> None:
        """Fill forbidden/affinity pair tensors for one incoming pod.

        forbidden_pairs: existing pods' required anti-affinity terms that match
        this pod forbid their topology pairs (ref predicates.go
        satisfiesExistingPodsAntiAffinity via metadata
        topologyPairsAntiAffinityPodsMap).
        pref_pair_weights: soft scoring weight per pair — combines the incoming
        pod's preferred terms and existing pods' preferred (anti-)affinity and
        hard-affinity symmetry (ref priorities/interpod_affinity.go).
        """
        d = self.dims
        hard_w = self.hard_pod_affinity_weight
        for sig, g in self.term_groups.items():
            if g.members <= 0:
                continue
            if not self._matches_one(g.selector, g.namespaces, pod):
                continue
            if g.kind == K_ANTI_REQ:
                out["forbidden_pairs"][b] |= g.pair_counts[: d.TP] > 0
            elif g.kind == K_ANTI_PREF:
                out["pref_pair_weights"][b] -= g.weight * g.pair_counts[: d.TP]
            elif g.kind == K_AFF_PREF:
                out["pref_pair_weights"][b] += g.weight * g.pair_counts[: d.TP]
            elif g.kind == K_AFF_REQ and hard_w:
                out["pref_pair_weights"][b] += hard_w * g.pair_counts[: d.TP]
        aff = pod.spec.affinity
        if aff is None:
            return
        if aff.pod_affinity:
            for j, term in enumerate(aff.pod_affinity.required[: d.PT]):
                pairs, kid = self._term_pairs(term, pod.namespace)
                out["aff_term_pairs"][b, j] = pairs > 0
                out["aff_term_valid"][b, j] = True
                out["aff_term_topo_key"][b, j] = kid
                sel = _sel_requirements(term.label_selector)
                out["aff_term_self"][b, j] = bool(
                    sel is not None
                    and pod.namespace in (term.namespaces or (pod.namespace,))
                    and sel.matches(pod.labels)
                )
            for wt in aff.pod_affinity.preferred:
                pairs, _ = self._term_pairs(wt.term, pod.namespace)
                out["pref_pair_weights"][b] += float(wt.weight) * pairs
        if aff.pod_anti_affinity:
            for j, term in enumerate(aff.pod_anti_affinity.required[: d.AT]):
                pairs, kid = self._term_pairs(term, pod.namespace)
                out["anti_term_pairs"][b, j] = pairs > 0
                out["anti_term_valid"][b, j] = True
                out["anti_term_topo_key"][b, j] = kid
                sel = _sel_requirements(term.label_selector)
                out["anti_term_self"][b, j] = bool(
                    sel is not None
                    and pod.namespace in (term.namespaces or (pod.namespace,))
                    and sel.matches(pod.labels)
                )
            for wt in aff.pod_anti_affinity.preferred:
                pairs, _ = self._term_pairs(wt.term, pod.namespace)
                out["pref_pair_weights"][b] -= float(wt.weight) * pairs
