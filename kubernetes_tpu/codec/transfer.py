"""Packed host->device transfer for remote-attached accelerators.

A pytree of small numpy leaves (PodBatch has ~60) costs one host->device
round trip PER LEAF when passed straight into a jitted call — on a
tunnel-attached TPU that is ~8ms x 60 = ~0.5s per scheduling batch, far more
than the compute itself.  pack_tree collapses the tree into at most three
flat buffers (one per dtype kind: float, int, bool) so the device pays one
RTT each; unpack_tree rebuilds the original tree *inside* the jitted
program with static slices (free: XLA folds them into the consumers).

Two further wire rules learned on real hardware (r05):
- BYTES matter as much as round trips: jit-argument transfers cross the
  tunnel on a slow synchronous path (~25-55MB/s measured vs ~1.4GB/s for
  explicit jax.device_put), so callers device_put the packed buffers; and
  the [B, ...] pair/mask tensors of controller-stamped batches repeat a
  handful of distinct rows, so pack_tree ships unique rows + an index and
  unpack_tree gathers the dense leaf back on device (~190MB -> ~2MB for a
  2048-pod anti-affinity batch).

The reference has no analog (its scheduler state never leaves host RAM);
this is TPU-plumbing the same way protobuf wire-batching is etcd-plumbing.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.codec import faults
from kubernetes_tpu.codec.schema import _pow2

# ------------------------------------------------------ transfer accounting
#
# Every wire seam notes the bytes it moved (ISSUE 11): direction h2d/d2h
# plus the seam name, computed from HOST array nbytes — never a device
# sync, so the accounting is safe to leave always-on (the perf_smoke
# budget pins it inside the <2% observatory envelope).  Totals feed the
# ktpu_transfer_* counter families and the per-cycle deltas the
# scheduler annotates onto each cycle span / hands to the performance
# observatory (runtime/perfobs.py).

_XFER_LOCK = threading.Lock()
# (direction, seam) -> [bytes, calls]; plain ints under a lock — the
# fetch worker and the scheduling thread both note here
_XFER_TOTALS: "dict[Tuple[str, str], list]" = {}


def note_transfer(direction: str, seam: str, nbytes: int) -> None:
    """Account one transfer at a wire seam.  Zero-byte calls still count
    a call (an empty dirty set that reached the wire is signal)."""
    from kubernetes_tpu.utils import metrics as m

    nbytes = int(nbytes)
    with _XFER_LOCK:
        cell = _XFER_TOTALS.get((direction, seam))
        if cell is None:
            cell = _XFER_TOTALS[(direction, seam)] = [0, 0]
        cell[0] += nbytes
        cell[1] += 1
    m.TRANSFER_BYTES.inc(nbytes, direction=direction, seam=seam)
    m.TRANSFER_CALLS.inc(direction=direction, seam=seam)


def tree_nbytes(tree) -> int:
    """Sum of nbytes over the numpy/jax leaves of a pytree (None leaves
    and scalars without nbytes are free)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nb = getattr(leaf, "nbytes", None)
        if nb:
            total += int(nb)
    return total


def note_transfer_tree(direction: str, seam: str, tree) -> None:
    note_transfer(direction, seam, tree_nbytes(tree))


def transfer_totals() -> "dict[str, dict]":
    """Snapshot of cumulative transfer accounting:
    {"h2d/snapshot_upload": {"bytes": B, "calls": C}, ...}.  Cheap (a
    handful of entries) — the scheduler snapshots it per cycle to
    compute the cycle's transfer delta."""
    with _XFER_LOCK:
        return {
            f"{d}/{s}": {"bytes": v[0], "calls": v[1]}
            for (d, s), v in _XFER_TOTALS.items()
        }


def transfer_delta(prev: "dict[str, dict]") -> "dict[str, dict]":
    """Non-zero per-seam deltas of transfer_totals() since `prev` (a
    previous transfer_totals() snapshot)."""
    out: dict = {}
    for key, cur in transfer_totals().items():
        p = prev.get(key, {"bytes": 0, "calls": 0})
        db, dc = cur["bytes"] - p["bytes"], cur["calls"] - p["calls"]
        if db or dc:
            out[key] = {"bytes": db, "calls": dc}
    return out


def device_annotation(name: str):
    """Optional jax.profiler annotation around a device-path section:
    when a real accelerator backend is active (and a jax profiler trace
    is being captured) the named range shows up in the device timeline,
    composing with the host-side spans (utils/trace.py).  On the CPU
    backend — the tier-1 path — this is a zero-cost no-op, so callers
    can wrap hot sections unconditionally."""
    import contextlib

    if jax.default_backend() == "cpu":
        return contextlib.nullcontext()
    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception:  # profiler unavailable on this backend build
        return contextlib.nullcontext()

# ---------------------------------------------------------------- D2H fences
#
# Every device->host materialization the RUNTIME performs goes through the
# helpers below, which report each sync that actually BLOCKS the calling
# thread to the registered listeners.  Tests hook this (on_blocking_sync) to
# pin the per-cycle blocking-sync budget — the regression guard that keeps
# per-pod fetches from silently coming back (tests/test_host_sync_guard.py).
# Engine-INTERNAL syncs (the speculative CPU host-rounds loop) are a
# documented design choice and are not routed through here.

_SYNC_LISTENERS: List[Callable[[str], None]] = []


def on_blocking_sync(fn: Callable[[str], None]) -> Callable[[], None]:
    """Register a listener called with a tag on every blocking device sync
    performed through this module's fetch helpers.  Returns a remover."""
    _SYNC_LISTENERS.append(fn)

    def remove() -> None:
        try:
            _SYNC_LISTENERS.remove(fn)
        except ValueError:
            pass

    return remove


def _note_sync(tag: str) -> None:
    for fn in _SYNC_LISTENERS:
        fn(tag)


def _involved_device_ids(x):
    """frozenset of jax device ids a device array's sharding spans, or
    None when unknowable (host arrays, duck-typed handles, or — the
    common case — no fault injector installed).  The fault-injection
    seams report these so a shard-targeted arm (faults.FaultInjector
    device_index) faults exactly the computations that touch the dead
    device.  Computed ONLY while an injector is live: production runs
    keep faults.py's no-op contract (one module-global load per site)."""
    if faults.current_injector() is None:
        return None
    sh = getattr(x, "sharding", None)
    ds = getattr(sh, "device_set", None)
    if not ds:
        return None
    try:
        return frozenset(int(getattr(d, "id", -1)) for d in ds)
    except TypeError:
        return None


def host_fetch(x, tag: str = "fetch") -> np.ndarray:
    """The canonical BLOCKING device->host sync point: np.asarray with the
    fence listeners notified first.  Runtime code must fetch through this
    (or AsyncFetch) rather than raw np.asarray so sync counts stay
    observable."""
    _note_sync(tag)
    faults.check(faults.SITE_FETCH, devices=_involved_device_ids(x))
    with device_annotation(f"ktpu.{tag}"):
        out = faults.corrupt(faults.SITE_FETCH, np.asarray(x))
    note_transfer("d2h", tag, out.nbytes)
    return out


def upload_async(tree):
    """Async H2D: jax.device_put returns immediately (the copy overlaps
    host work); pair with ready_fence() when completion must be ordered
    before a dependent host step.  Exists mostly as the named seam — the
    point is that NO fence is needed on the hot path, because jit consumers
    order themselves on the transfer."""
    note_transfer_tree("h2d", "upload", tree)
    return jax.device_put(tree)


def ready_fence(tree, tag: str = "fence"):
    """Explicit blocking fence: waits until every leaf of `tree` is
    computed/transferred.  Counts as a blocking sync."""
    _note_sync(tag)
    faults.check(faults.SITE_FENCE)
    jax.block_until_ready(tree)
    return tree


class _FetchWorker:
    """One persistent daemon thread draining AsyncFetch jobs — per-cycle
    thread create/teardown was measurable under trickle arrival (hundreds
    of cycles/s), and a DAEMON thread (unlike a ThreadPoolExecutor's
    joined workers) cannot let a wedged-tunnel fetch block interpreter
    exit."""

    def __init__(self) -> None:
        import queue as _q

        self._jobs: Any = _q.SimpleQueue()
        self.thread = threading.Thread(
            target=self._drain, daemon=True, name="ktpu-async-fetch"
        )
        self.thread.start()

    def submit(self, fn: Callable[[], None]) -> None:
        self._jobs.put(fn)

    def _drain(self) -> None:
        while True:
            job = self._jobs.get()
            try:
                job()
            except BaseException:  # noqa: BLE001
                # A raising job must never kill the shared worker: every
                # fetch queued BEHIND it would hang forever at result().
                # AsyncFetch._run routes its own errors into the owning
                # handle; this guard covers jobs that fail before that
                # plumbing (or foreign submit() callers) — logged, since
                # such a caller has no other way to see the failure.
                import traceback

                traceback.print_exc()


_FETCH_WORKER: "_FetchWorker | None" = None
_FETCH_WORKER_LOCK = threading.Lock()


def _fetch_worker() -> _FetchWorker:
    global _FETCH_WORKER
    w = _FETCH_WORKER
    if w is None or not w.thread.is_alive():  # first use, or post-fork
        with _FETCH_WORKER_LOCK:
            w = _FETCH_WORKER
            if w is None or not w.thread.is_alive():
                w = _FETCH_WORKER = _FetchWorker()
    return w


class AsyncFetch:
    """Fetch-in-flight handle for a device result (the D2H half of the
    double-buffered commit pipeline).

    Starts the wire copy immediately — copy_to_host_async() enqueues the
    D2H DMA to fire the moment the producing computation finishes — and
    completes the materialization on the shared fetch worker, so the
    blocking device sync overlaps whatever the scheduling thread does
    next (dispatching batch k+1, running batch k-1's side-effect tail).

    result() is the ready-fence: it returns the host array, blocking only
    if the copy hasn't landed yet (and only that case is reported to the
    sync listeners); a device error re-raises HERE, so callers own the
    batch's recovery at the fence.  `seconds` is the device-side window
    from dispatch to copy-complete — the honest "fetch" phase cost, which
    may overlap other host phases (bench.py's overlap-efficiency figure
    divides wall clock by the sum of such phases)."""

    def __init__(self, dev, tag: str = "fetch") -> None:
        self._dev = dev
        self._tag = tag
        # device ids this result's sharding spans (a mesh-replicated
        # winners buffer spans every mesh device): the fault seams below
        # report them so a lost shard faults this fetch attributably
        self._devices = _involved_device_ids(dev)
        if hasattr(dev, "copy_to_host_async"):
            dev.copy_to_host_async()
        self._done = threading.Event()
        self._out: Any = None
        self._err: Any = None
        self.seconds = 0.0
        # the host/device attribution split (ISSUE 11), stamped by the
        # ready fences in _run: execute = dispatch -> computation ready
        # (the honest device-execute window), materialize = the residual
        # D2H landing after the result was ready (with the async copy
        # prefetch this is usually ~0).  execute + materialize <= seconds
        # (the worker also pays queueing before the fence).
        self.execute_seconds = 0.0
        self.materialize_seconds = 0.0
        self._t0 = time.monotonic()
        _fetch_worker().submit(self._run)

    def _run(self) -> None:
        try:
            faults.check(faults.SITE_FETCH, devices=self._devices)
            with device_annotation(f"ktpu.{self._tag}"):
                t_wait0 = time.monotonic()
                wait = getattr(self._dev, "block_until_ready", None)
                if wait is not None:
                    # ready fence BEFORE the materialize: splits "device
                    # still computing" from "host copying" (a failed
                    # computation raises here exactly as np.asarray would)
                    wait()
                self.execute_seconds = time.monotonic() - t_wait0
                self._out = faults.corrupt(
                    faults.SITE_FETCH, np.asarray(self._dev)
                )
                self.materialize_seconds = (
                    time.monotonic() - t_wait0 - self.execute_seconds
                )
            note_transfer("d2h", self._tag, self._out.nbytes)
        except BaseException as e:  # noqa: BLE001 — re-raised in result()
            self._err = e
        finally:
            self.seconds = time.monotonic() - self._t0
            self._done.set()

    def ready(self) -> bool:
        """Non-blocking fence probe: has the host copy landed?"""
        return self._done.is_set()

    def result(self) -> np.ndarray:
        """The ready-fence: host array, blocking (and reporting a blocking
        sync) only when the copy is still in flight.  Fence-site faults
        inject HERE — synchronously on the calling thread, where the
        scheduler's classified-retry wrapper owns recovery."""
        faults.check(faults.SITE_FENCE, devices=self._devices)
        if not self._done.is_set():
            _note_sync(self._tag)
            self._done.wait()
        if self._err is not None:
            raise self._err
        return self._out

_GROUPS = ("f", "i", "b")
_HOST_DTYPE = {"f": np.float32, "i": np.int32, "b": np.bool_}
_DEV_DTYPE = {"f": jnp.float32, "i": jnp.int32, "b": jnp.bool_}


def _group(dtype) -> str:
    k = np.dtype(dtype).kind
    if k == "f":
        return "f"
    if k in ("i", "u"):
        return "i"
    if k == "b":
        return "b"
    raise TypeError(f"unsupported leaf dtype {dtype!r}")


# Leaves at least this big get row-deduplicated before packing: workload
# batches are controller-stamped, so the [B, ...] pair/mask tensors repeat
# a handful of distinct rows and the wire cost collapses ~B/G x.  Content
# (bytes) keyed — no semantic assumption can go stale.
_FACTOR_MIN_BYTES = 1 << 20
# Factoring wins only while the unique-row bucket stays <= B/8: real
# workloads are either controller-stamped (U ~ #deployments, tiny) or
# essentially unique-rowed (U ~ B).  The coarse pow2 bucket with a floor
# of 32 keeps meta — and therefore the jit cache key — stable across the
# batches of one workload; a factored<->dense flip needs a 64x change in
# row cardinality, which is workload drift, not batch noise.
_FACTOR_MAX_FRAC = 8


def pack_tree(tree, factor: "bool | None" = None) -> Tuple[Tuple[np.ndarray, ...], Any]:
    """tree (numpy/scalar leaves) -> (buffers, meta).

    buffers: up to 3 flat numpy arrays (f32 / i32 / bool).  meta is hashable
    (treedef + per-leaf placement + factoring pattern) and is the jit-cache
    key for the matching unpack — batches of one workload (same shapes,
    same factoring bucket) share one compiled program.
    64-bit leaves are narrowed to 32-bit (the device schema is 32-bit).

    Large [B, ...] leaves (>= _FACTOR_MIN_BYTES) are shipped FACTORED:
    unique rows (pow2-padded, floor 32) plus an i32[B] row index;
    unpack_tree gathers the dense leaf back ON DEVICE.  A remote-attached
    accelerator bills per byte moved (~25-55 MB/s through the tunnel), and
    a 2048-pod anti-affinity batch carries ~150MB of dense pair tensors
    with ~20 distinct rows.  factor=None auto-disables on the CPU backend
    (no transfer to save); tests pass factor=True to force the path.
    """
    if factor is None:
        factor = jax.default_backend() != "cpu"
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    chunks = {g: [] for g in _GROUPS}
    offs = {g: 0 for g in _GROUPS}
    metas = []

    def _append(a, g, factored, shape):
        flat = np.ravel(a).astype(_HOST_DTYPE[g], copy=False)
        metas.append((g, offs[g], shape, factored))
        offs[g] += flat.size
        chunks[g].append(flat)

    for leaf in leaves:
        a = np.asarray(leaf)
        g = _group(a.dtype)
        if factor and a.nbytes >= _FACTOR_MIN_BYTES and a.ndim >= 1 \
                and a.shape[0] > 1:
            B = a.shape[0]
            max_u = max(32, B // _FACTOR_MAX_FRAC)
            rows = a.reshape(B, -1)
            seen: dict = {}
            idx = np.empty(B, np.int32)
            uniq_rows = []
            for r in range(B):
                key = rows[r].tobytes()
                u = seen.get(key)
                if u is None:
                    if len(uniq_rows) >= max_u:
                        uniq_rows = None  # early bail: can never win now
                        break
                    u = seen[key] = len(uniq_rows)
                    uniq_rows.append(rows[r])
                idx[r] = u
            if uniq_rows is not None:
                U = max(32, _pow2(len(uniq_rows)))
                uniq = np.zeros((U, rows.shape[1]), a.dtype)
                uniq[: len(uniq_rows)] = uniq_rows
                # factored leaf = two packed entries: uniq then idx
                _append(uniq, g, "uniq", (U,) + a.shape[1:])
                _append(idx, "i", "idx", (B,))
                continue
        _append(a, g, None, a.shape)
    bufs = tuple(
        np.concatenate(chunks[g]) if chunks[g] else np.zeros(0, _HOST_DTYPE[g])
        for g in _GROUPS
    )
    return bufs, (treedef, tuple(metas))


def unpack_tree(bufs, meta):
    """Rebuild the packed tree from device buffers (call inside jit).
    Factored leaves are re-densified with an on-device gather."""
    treedef, metas = meta
    by_group = dict(zip(_GROUPS, bufs))
    leaves = []
    pending_uniq = None  # (device uniq rows, dense row shape tail)
    for g, off, shape, factored in metas:
        size = int(np.prod(shape)) if shape else 1
        piece = by_group[g][off:off + size]
        arr = jnp.reshape(piece, shape).astype(_DEV_DTYPE[g])
        if factored == "uniq":
            pending_uniq = arr
            continue
        if factored == "idx":
            leaves.append(jnp.take(pending_uniq, arr, axis=0))
            pending_uniq = None
            continue
        leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _scatter_impl(dev, rows, vals):
    return dev.at[rows].set(vals)


_scatter_copy = jax.jit(_scatter_impl)
# donated variant: the resident buffer (arg 0) is consumed and its memory
# reused for the output — the per-cycle dirty-row refresh updates the
# snapshot IN PLACE instead of allocating + copying a whole tensor per
# scattered field (requested/nonzero move every cycle; at 50k nodes that
# is MBs per field per cycle of pure copy).  Sound because the sole
# caller (DeviceSnapshotCache.update) immediately replaces its _dev entry
# with the result, and PJRT sequences the donation behind any in-flight
# reader of the old buffer.
_scatter_donate = jax.jit(_scatter_impl, donate_argnums=(0,))


def _scatter_rows(dev, rows, vals):
    """Row scatter into a resident device buffer (duplicate indices carry
    identical values, so pad-by-repeat is safe).  XLA:CPU has no buffer
    donation — the copying variant keeps warning noise out of cpu runs."""
    faults.check(faults.SITE_SCATTER, devices=_involved_device_ids(dev))
    if jax.default_backend() == "cpu":
        return _scatter_copy(dev, rows, vals)
    return _scatter_donate(dev, rows, vals)


# one jitted scatter per (resident sharding, donation) pair — bounded by
# the handful of distinct field ranks a mesh-backed snapshot carries
_SCATTER_SHARDED: dict = {}


def _scatter_rows_sharded(dev, rows, vals, sharding):
    """Row scatter into a MESH-SHARDED resident buffer: out_shardings pins
    the output to the same NamedSharding the resident buffer carries, so
    XLA's SPMD partitioner routes each row update to the shard that owns
    the row (a shard drops updates outside its row block locally — the
    refreshed buffer never gathers to one chip and incremental upload
    stays O(dirty)).  Donation keeps the `_scatter_rows` semantics
    per shard on accelerator backends: each device recycles its own
    block's HBM for the output; XLA:CPU (the virtual test mesh) has no
    donation, so the copying variant serves it.

    Instrumented as the `scatter` fault seam: a fault here is raised
    inside the scheduler's classified launch wrapper, and — because the
    scatter lands on the shard that owns the rows — carries the device
    ids the delta touches, so the elastic ladder can attribute it to the
    failing shard instead of demoting the whole mesh.  The id set is
    only computed while an injector is live (the hot path pays one
    module-global load, faults.py's contract)."""
    if faults.current_injector() is not None:
        from kubernetes_tpu.parallel.mesh import mesh_device_ids

        faults.check(
            faults.SITE_SCATTER, devices=mesh_device_ids(sharding.mesh)
        )
    donate = jax.default_backend() != "cpu"
    key = (sharding, donate)
    fn = _SCATTER_SHARDED.get(key)
    if fn is None:
        fn = _SCATTER_SHARDED[key] = jax.jit(
            _scatter_impl,
            out_shardings=sharding,
            donate_argnums=(0,) if donate else (),
        )
    return fn(dev, rows, vals)


# fields whose leading axis is NOT the node-row axis, or which the encoder
# recomputes wholesale so their diffs are NOT confined to dirty rows
# (image_size rescales every row when the node count moves; group_counts
# can shift many rows when a spread selector registers) — never scattered
_NON_ROW_FIELDS = frozenset({"pair_topo_key", "image_size", "group_counts"})
# scatter only pays while the dirty set stays a small fraction of N
_SCATTER_MAX_FRAC = 4


class DeviceSnapshotCache:
    """Incremental cluster-snapshot upload (SURVEY's "device-resident state
    with delta scatter, not re-upload" requirement; the host-side analog is
    the generation-numbered incremental NodeInfo snapshot,
    internal/cache/cache.go:210-222).

    The scheduler takes a fresh host snapshot every cycle, but between
    cycles most cluster tensor fields are byte-identical — label/taint/
    topology tensors only move on node events, while requested/nonzero move
    on every commit.  update() skips any field whose host array is the
    SAME OBJECT as last time (the encoder's incremental snapshot reuses
    unchanged leaves by identity, making unchanged-field detection O(1));
    non-identical fields fall back to content comparison (memcmp) before
    re-uploading.  When the caller passes `dirty_rows` (the encoder's
    take_dirty_rows()), a changed row-indexed field uploads only those
    rows and scatters them into the resident device buffer instead of
    re-shipping the whole tensor — the dirty set is exactly the rows the
    incremental snapshot rewrote, so host arrays cannot differ elsewhere.

    Multi-chip sharding (mesh != None): every node-axis field uploads
    sharded over the mesh's `spec_axis` (parallel/mesh.py shard_cluster's
    classification — leading dim == the snapshot's node count; the
    cluster-wide pair_topo_key vector replicates), so NO single device
    ever holds the full node tensor, and the dirty-row scatter routes
    each row delta to the shard that owns the row
    (_scatter_rows_sharded).  mesh=None is today's single-chip behavior
    bit-for-bit.
    """

    def __init__(self, mesh=None, spec_axis=None) -> None:
        self._host: dict = {}   # field -> last-uploaded host array
        self._dev: dict = {}    # field -> resident device array
        self._mesh = mesh
        if mesh is not None and spec_axis is None:
            names = tuple(mesh.axis_names)
            spec_axis = names if len(names) > 1 else names[0]
        self._spec_axis = spec_axis

    @property
    def mesh(self):
        return self._mesh

    def _sharding_for(self, name: str, arr: np.ndarray, n_rows: int):
        """NamedSharding for one snapshot field (None = unsharded cache),
        classified by the ONE shared rule (parallel.mesh.node_axis_spec):
        node-axis fields split over spec_axis, everything else (and the
        cluster-wide pair_topo_key, whatever its length) replicates."""
        if self._mesh is None:
            return None
        from jax.sharding import NamedSharding

        from kubernetes_tpu.parallel.mesh import node_axis_spec

        return NamedSharding(
            self._mesh, node_axis_spec(name, arr, n_rows, self._spec_axis)
        )

    def resident(self, names: "tuple[str, ...]"):
        """Device-resident buffers for the named snapshot fields, or None
        when any is absent (before the first update(), or after a fault
        invalidate()).  The telemetry analytics side-launch
        (ops/analytics.py) reads the snapshot THROUGH this accessor so it
        reduces the buffers already on device — zero extra H2D traffic —
        and degrades to its host fallback exactly when the device state
        cannot be trusted."""
        out = []
        for n in names:
            dev = self._dev.get(n)
            if dev is None:
                return None
            out.append(dev)
        return tuple(out)

    def invalidate(self) -> None:
        """Drop every resident buffer: the next update() re-uploads the
        whole snapshot.  Called after a device fault — the wire state is
        unknown (an upload may have half-landed) and the encoder's
        dirty-row stream may have been consumed by the failed cycle, so
        the incremental invariant (_host == device contents) cannot be
        trusted until rebuilt from scratch."""
        self._host.clear()
        self._dev.clear()

    def update(self, cluster, dirty_rows=None):
        """Host ClusterTensors (or any flat dataclass of numpy arrays) ->
        same type with device-resident leaves, uploading only changes.
        dirty_rows: optional i32[] of node rows touched since the previous
        update (from SnapshotEncoder.take_dirty_rows(); None = unknown,
        full content comparison).

        Fault discipline: _host must only record arrays whose device copy
        actually landed — a raising upload leaves the already-committed
        fields coherent (host+dev move together) and the failed/remaining
        fields untouched, so a retry after a transient fault re-uploads
        exactly what is missing.  The whole-tensor path therefore stages
        its _host commits until after the batched device_put."""
        faults.check(faults.SITE_SNAPSHOT_UPDATE)
        changed = []
        staged: dict = {}
        rows_arr = None
        if dirty_rows is not None and len(dirty_rows) > 0:
            rows_arr = np.asarray(dirty_rows, np.int32)
        n_rows = getattr(cluster, "n_nodes", None)
        if n_rows is None:
            first = dataclasses.fields(cluster)[0]
            n_rows = np.asarray(getattr(cluster, first.name)).shape[0]
        if self._mesh is not None and n_rows % self._mesh.size:
            raise ValueError(
                f"snapshot node axis ({n_rows}) does not divide over the "
                f"{self._mesh.size}-device mesh (node arenas grow pow2 to "
                "2048 rows then in 512-multiples — use a pow2 mesh of at "
                "most 512 devices and no larger than the node axis)"
            )
        for f in dataclasses.fields(cluster):
            host = np.asarray(getattr(cluster, f.name))
            prev = self._host.get(f.name)
            if prev is host:
                continue  # identity: unchanged leaf reused by the encoder
            if (
                prev is not None
                and rows_arr is not None
                and f.name not in _NON_ROW_FIELDS
                and f.name in self._dev
                and prev.shape == host.shape
                and prev.dtype == host.dtype
                and host.ndim >= 1
                and len(rows_arr) <= host.shape[0] // _SCATTER_MAX_FRAC
            ):
                sub = host[rows_arr]
                if not np.array_equal(prev[rows_arr], sub):
                    # pad rows to a pow2 bucket (repeat the first row) so
                    # the scatter kernel compiles once per shape bucket
                    k = _pow2(len(rows_arr))
                    if k > len(rows_arr):
                        pad = k - len(rows_arr)
                        rows_p = np.concatenate(
                            [rows_arr, np.repeat(rows_arr[:1], pad)]
                        )
                        sub_p = np.concatenate(
                            [sub, np.repeat(sub[:1], pad, axis=0)]
                        )
                    else:
                        rows_p, sub_p = rows_arr, sub
                    # the delta that actually crosses the wire: the
                    # padded row-index vector + the padded row values
                    note_transfer(
                        "h2d", "dirty_scatter",
                        rows_p.nbytes + sub_p.nbytes,
                    )
                    if self._mesh is not None:
                        # rows/vals ship uncommitted (the compiler
                        # replicates the tiny delta); the scatter routes
                        # each row to its owning shard
                        self._dev[f.name] = _scatter_rows_sharded(
                            self._dev[f.name], rows_p, sub_p,
                            self._sharding_for(f.name, host, n_rows),
                        )
                    else:
                        dev_rows, dev_vals = jax.device_put((rows_p, sub_p))
                        self._dev[f.name] = _scatter_rows(
                            self._dev[f.name], dev_rows, dev_vals
                        )
                self._host[f.name] = host
                continue
            if (
                prev is None
                or prev.shape != host.shape
                or prev.dtype != host.dtype
                or not np.array_equal(prev, host)
            ):
                changed.append(f.name)
                staged[f.name] = host
            else:
                self._host[f.name] = host  # content-equal: no upload needed
        if changed:
            note_transfer(
                "h2d", "snapshot_upload",
                sum(staged[n].nbytes for n in changed),
            )
            with device_annotation("ktpu.snapshot_upload"):
                if self._mesh is not None:
                    uploaded = jax.device_put(
                        [staged[n] for n in changed],
                        [self._sharding_for(n, staged[n], n_rows)
                         for n in changed],
                    )
                else:
                    uploaded = jax.device_put([staged[n] for n in changed])
            self._dev.update(zip(changed, uploaded))
            self._host.update(staged)
        return type(cluster)(**self._dev)


# ------------------------------------------------------- snapshot deltas
# Host-side snapshot delta serialization for the decision ledger
# (runtime/ledger.py): the on-disk twin of DeviceSnapshotCache's
# incremental upload.  A recorded cycle stores only the rows of each
# field that moved since the previously RECORDED snapshot (the encoder's
# cow snapshot makes unchanged fields identity-equal, so most fields
# cost one pointer compare); replay folds the deltas back into a full
# ClusterTensors, bit-identical to what the cycle dispatched.


def _row_changed(prev: np.ndarray, cur: np.ndarray) -> np.ndarray:
    """intp[] rows (axis 0) where prev and cur differ, NaN-safe (NaN is
    a live value in label_nums — two NaNs count as equal)."""
    neq = prev != cur
    if prev.dtype.kind == "f":
        neq &= ~(np.isnan(prev) & np.isnan(cur))
    if neq.ndim > 1:
        neq = neq.reshape(neq.shape[0], -1).any(axis=1)
    return np.flatnonzero(neq)


def snapshot_delta(prev, cur) -> dict:
    """ClusterTensors pair -> {field: ("full", arr) | ("rows", idx, vals)}.
    prev=None (or a shape/dtype change, or a diff touching most rows)
    records the field whole; identity-equal fields are omitted entirely.
    Pure numpy — safe to run on the ledger's writer thread because the
    encoder's snapshot arrays are immutable by the dirty-row contract."""
    out: dict = {}
    for f in dataclasses.fields(cur):
        cur_a = np.asarray(getattr(cur, f.name))
        prev_a = (
            np.asarray(getattr(prev, f.name)) if prev is not None else None
        )
        if prev_a is cur_a:
            continue
        if (
            prev_a is None
            or prev_a.shape != cur_a.shape
            or prev_a.dtype != cur_a.dtype
            or cur_a.ndim == 0
        ):
            out[f.name] = ("full", cur_a)
            continue
        rows = _row_changed(prev_a, cur_a)
        if len(rows) == 0:
            continue
        if len(rows) > cur_a.shape[0] // 2:
            out[f.name] = ("full", cur_a)
        else:
            out[f.name] = ("rows", rows.astype(np.int64), cur_a[rows])
    return out


def apply_snapshot_delta(prev, delta: dict, cls=None):
    """Fold a snapshot_delta back onto `prev` (None for the first,
    necessarily-full record) -> a reconstructed snapshot of type `cls`
    (defaults to type(prev)).  Row patches copy-on-write, so the caller
    may keep every reconstructed snapshot alive (the replay harness
    does)."""
    if prev is None:
        missing = [
            f.name for f in dataclasses.fields(cls)
            if f.name not in delta
        ]
        if missing:
            raise ValueError(
                f"first ledger record is not a full snapshot: {missing}"
            )
        fields = {k: v[1] for k, v in delta.items()}
        return cls(**fields)
    cls = cls or type(prev)
    fields = {}
    for f in dataclasses.fields(prev):
        cur = np.asarray(getattr(prev, f.name))
        d = delta.get(f.name)
        if d is not None:
            if d[0] == "full":
                cur = d[1]
            else:
                cur = cur.copy()
                cur[d[1]] = d[2]
        fields[f.name] = cur
    return cls(**fields)
