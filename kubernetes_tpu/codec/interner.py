"""String interning: the host-side bridge from label/taint/name strings to
device int32 ids.

The reference does string compares in the hot loop (label map lookups in every
predicate, e.g. predicates.go PodMatchNodeSelector); on TPU strings cannot
exist, so every string the kernels consume is interned once at snapshot-encode
time.  Id 0 is reserved as the wildcard/empty id (used e.g. for host-port IP
"" / "0.0.0.0" which conflicts with every address, predicates host_ports
semantics); -1 is the universal padding value.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Sequence


class Interner:
    WILDCARD = 0

    def __init__(self) -> None:
        self._ids: Dict[str, int] = {"": self.WILDCARD}
        self._strs: List[str] = [""]

    def intern(self, s: str) -> int:
        i = self._ids.get(s)
        if i is None:
            i = len(self._strs)
            self._ids[s] = i
            self._strs.append(s)
        return i

    def lookup(self, s: str) -> int:
        """Return the id for s, or -1 if never interned (matches nothing)."""
        return self._ids.get(s, -1)

    def string(self, i: int) -> str:
        return self._strs[i]

    def __len__(self) -> int:
        return len(self._strs)

    def intern_many(self, strs: Sequence[str]) -> List[int]:
        """Batch intern: id assignment order is exactly intern() called per
        string in sequence order (novel strings get consecutive ids).  The
        common shape — most strings already interned — is one C-speed dict
        lookup comprehension; only the misses walk the python patch loop.
        The bulk node ingest path stacks ~10 strings per node through
        this, and per-string method resolution dominated at 5k-node
        re-sync scale."""
        get = self._ids.get
        out = [get(s) for s in strs]
        if None in out:
            ids = self._ids
            lst = self._strs
            for idx, i in enumerate(out):
                if i is None:
                    s = strs[idx]
                    i = ids.get(s)  # a dup earlier in the batch may have won
                    if i is None:
                        i = ids[s] = len(lst)
                        lst.append(s)
                    out[idx] = i
        return out

    def intern_all(self, strs: Iterable[str]) -> List[int]:
        return self.intern_many(
            strs if isinstance(strs, (list, tuple)) else list(strs)
        )
