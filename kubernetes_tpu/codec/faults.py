"""Device-fault model: classified errors + a deterministic injection seam.

The reference treats fault tolerance as a first-class harness (the
chaosmonkey in test/e2e/chaosmonkey + the disruptive e2e suites), but its
faults all live at the CLUSTER layer — pods die, nodes go dark, leaders
crash.  A TPU control plane has a second failure domain the reference never
had: the accelerator itself.  A tunnel-attached device can time out, come
back garbled, slow to a crawl, or vanish ("device lost"), and each of those
deserves a different response from the scheduling loop:

  transient   retry the SAME in-flight batch with jittered backoff — the
              XLA runtime error family that clears on its own
              (RESOURCE_EXHAUSTED, DEADLINE_EXCEEDED, UNAVAILABLE, ...)
  persistent  stop using the device NOW (trip the breaker) and serve
              cycles from the CPU reference engine — "device lost",
              DATA_LOSS, INTERNAL
  corrupt     a fetch that *returned* but fails structural validation
              (winner rows out of range); treated as transient — re-run
  slow        not an error: injected latency, exercises the overlap math

This module owns (a) the classified exception types, (b) the mapping from
real JAX/XLA runtime errors to a fault class, and (c) `FaultInjector` — a
seeded, deterministic injector the chaos harness (runtime/chaos.py
Disruptions) arms per SITE:

  dispatch         engine launch in Scheduler._encode_and_dispatch
  fence            the ready-fence (AsyncFetch.result / ready_fence)
  fetch            D2H materialization (host_fetch / the fetch worker)
  snapshot_update  DeviceSnapshotCache.update (H2D delta upload)

Injection is OFF unless an injector is installed (`install_injector`); the
instrumented code calls `check(site)` / `corrupt(site, arr)` which are
no-ops otherwise, so the hot path pays one module-global load per site.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

# fault classes (the breaker's retry-policy vocabulary + metrics label)
FAULT_TRANSIENT = "transient"
FAULT_PERSISTENT = "persistent"
FAULT_CORRUPT = "corrupt"
FAULT_SLOW = "slow"

# injection sites (the seams instrumented in codec/transfer.py and
# runtime/scheduler.py)
SITE_DISPATCH = "dispatch"
SITE_FENCE = "fence"
SITE_FETCH = "fetch"
SITE_SNAPSHOT_UPDATE = "snapshot_update"
SITES = (SITE_DISPATCH, SITE_FENCE, SITE_FETCH, SITE_SNAPSHOT_UPDATE)


class DeviceFault(RuntimeError):
    """Base for classified device-path failures (injected or mapped from
    real runtime errors).  `fault_class` drives the retry/breaker policy."""

    fault_class = FAULT_TRANSIENT


class TransientDeviceError(DeviceFault):
    """Clears on its own: retry the same batch with backoff."""

    fault_class = FAULT_TRANSIENT


class PersistentDeviceError(DeviceFault):
    """Device lost: trip the breaker, degrade to the CPU engine."""

    fault_class = FAULT_PERSISTENT


class CorruptedFetchError(DeviceFault):
    """A fetch returned structurally-invalid data (winner rows out of
    range).  Retried like a transient fault — the wire, not the program."""

    fault_class = FAULT_TRANSIENT


# XLA status substrings -> fault class.  jaxlib surfaces device errors as
# XlaRuntimeError (a RuntimeError subclass) whose message leads with the
# absl status code; the split below mirrors how large control planes
# (PAPERS.md Borg/Omega lineage) bucket infra errors: codes that clear on
# retry vs codes that mean the backend is gone.
_PERSISTENT_MARKERS = (
    "device lost",
    "DATA_LOSS",
    "INTERNAL:",
    "FAILED_PRECONDITION",
    "device halted",
)
_TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "DEADLINE_EXCEEDED",
    "UNAVAILABLE",
    "ABORTED",
    "CANCELLED",
)


def classify_device_error(err: BaseException) -> Optional[str]:
    """Map an exception raised on the device path to a fault class, or None
    when it is NOT a device fault (a programming error must propagate, not
    be retried into oblivion)."""
    if isinstance(err, DeviceFault):
        return err.fault_class
    # real XLA runtime errors: XlaRuntimeError subclasses RuntimeError; the
    # name check keeps this import-free (jaxlib's module path moves between
    # releases)
    if isinstance(err, RuntimeError):
        msg = str(err)
        for marker in _PERSISTENT_MARKERS:
            if marker in msg:
                return FAULT_PERSISTENT
        for marker in _TRANSIENT_MARKERS:
            if marker in msg:
                return FAULT_TRANSIENT
        if type(err).__name__ == "XlaRuntimeError":
            # unknown runtime status from the device: worth one retry round
            return FAULT_TRANSIENT
    return None


@dataclass
class _Arm:
    kind: str
    p: float
    count: Optional[int]        # max fires; None = unlimited
    latency_s: float
    fired: int = 0


class FaultInjector:
    """Seeded, deterministic per-site fault injection.

    arm(site, kind, ...) arms one site with one fault kind; `count` bounds
    how many times it fires (the deterministic lever the fault-matrix
    tests use: count=1 == "exactly the first call faults"), `p` makes it
    probabilistic from the injector's own seeded rng.  `log` records every
    fire as (site, kind) for assertions."""

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)
        self._arms: dict = {}
        self.log: list = []

    def arm(
        self,
        site: str,
        kind: str = FAULT_TRANSIENT,
        p: float = 1.0,
        count: Optional[int] = None,
        latency_s: float = 0.01,
    ) -> "FaultInjector":
        if site not in SITES:
            raise ValueError(f"unknown fault site {site!r} (sites: {SITES})")
        if kind not in (FAULT_TRANSIENT, FAULT_PERSISTENT, FAULT_CORRUPT,
                        FAULT_SLOW):
            raise ValueError(f"unknown fault kind {kind!r}")
        self._arms[site] = _Arm(kind=kind, p=p, count=count,
                                latency_s=latency_s)
        return self

    def disarm(self, site: Optional[str] = None) -> None:
        if site is None:
            self._arms.clear()
        else:
            self._arms.pop(site, None)

    def _should_fire(self, a: _Arm) -> bool:
        if a.count is not None and a.fired >= a.count:
            return False
        if a.p < 1.0 and self._rng.random() >= a.p:
            return False
        return True

    def fire(self, site: str) -> None:
        """Raise/sleep per the site's armed fault; corrupt-kind arms are
        handled by maybe_corrupt (they alter data, not control flow)."""
        a = self._arms.get(site)
        if a is None or a.kind == FAULT_CORRUPT or not self._should_fire(a):
            return
        a.fired += 1
        self.log.append((site, a.kind))
        if a.kind == FAULT_SLOW:
            time.sleep(a.latency_s)
            return
        if a.kind == FAULT_PERSISTENT:
            raise PersistentDeviceError(
                f"injected device-lost at {site} (fire #{a.fired})"
            )
        raise TransientDeviceError(
            f"injected transient XLA error at {site} (fire #{a.fired}): "
            "UNAVAILABLE: fabric tunnel reset"
        )

    def maybe_corrupt(self, site: str, arr):
        """Scramble a fetched array when the site is armed with a corrupt
        fault: winner rows are pushed far out of range so structural
        validation (scheduler._validate_hosts) catches it — the seam has no
        checksum, so in-range corruption is out of scope by design."""
        a = self._arms.get(site)
        if a is None or a.kind != FAULT_CORRUPT or not self._should_fire(a):
            return arr
        a.fired += 1
        self.log.append((site, FAULT_CORRUPT))
        out = np.array(arr)
        if out.dtype.kind in ("i", "u"):
            out = out + (1 << 20)
        else:
            out = out + np.float32(3.0e38)
        return out


# ------------------------------------------------------- the global seam

_INJECTOR: Optional[FaultInjector] = None


def install_injector(inj: FaultInjector) -> Callable[[], None]:
    """Install `inj` as the process-wide injector; returns a remover that
    restores whatever was installed before (tests stack cleanly)."""
    global _INJECTOR
    prev = _INJECTOR
    _INJECTOR = inj

    def remove() -> None:
        global _INJECTOR
        _INJECTOR = prev

    return remove


def current_injector() -> Optional[FaultInjector]:
    return _INJECTOR


def check(site: str) -> None:
    """Instrumentation hook: fire the armed fault for `site`, if any."""
    inj = _INJECTOR
    if inj is not None:
        inj.fire(site)


def corrupt(site: str, arr):
    """Instrumentation hook: corrupt fetched data for `site`, if armed."""
    inj = _INJECTOR
    if inj is not None:
        return inj.maybe_corrupt(site, arr)
    return arr
