"""Device-fault model: classified errors + a deterministic injection seam.

The reference treats fault tolerance as a first-class harness (the
chaosmonkey in test/e2e/chaosmonkey + the disruptive e2e suites), but its
faults all live at the CLUSTER layer — pods die, nodes go dark, leaders
crash.  A TPU control plane has a second failure domain the reference never
had: the accelerator itself.  A tunnel-attached device can time out, come
back garbled, slow to a crawl, or vanish ("device lost"), and each of those
deserves a different response from the scheduling loop:

  transient   retry the SAME in-flight batch with jittered backoff — the
              XLA runtime error family that clears on its own
              (RESOURCE_EXHAUSTED, DEADLINE_EXCEEDED, UNAVAILABLE, ...)
  persistent  stop using the device NOW (trip the breaker) and serve
              cycles from the CPU reference engine — "device lost",
              DATA_LOSS, INTERNAL
  corrupt     a fetch that *returned* but fails structural validation
              (winner rows out of range); treated as transient — re-run
  slow        not an error: injected latency, exercises the overlap math

This module owns (a) the classified exception types, (b) the mapping from
real JAX/XLA runtime errors to a fault class, and (c) `FaultInjector` — a
seeded, deterministic injector the chaos harness (runtime/chaos.py
Disruptions) arms per SITE:

  dispatch         engine launch in Scheduler._encode_and_dispatch
  fence            the ready-fence (AsyncFetch.result / ready_fence)
  fetch            D2H materialization (host_fetch / the fetch worker)
  snapshot_update  DeviceSnapshotCache.update (H2D delta upload)
  scatter          the dirty-row scatter into a resident buffer
                   (_scatter_rows / _scatter_rows_sharded — per-shard on
                   a mesh, so this is the shard-attributable H2D seam)

Injection is OFF unless an injector is installed (`install_injector`); the
instrumented code calls `check(site)` / `corrupt(site, arr)` which are
no-ops otherwise, so the hot path pays one module-global load per site.
"""

from __future__ import annotations

import random
import re
import time
from dataclasses import dataclass
from typing import Callable, Iterable, Optional

import numpy as np

# fault classes (the breaker's retry-policy vocabulary + metrics label)
FAULT_TRANSIENT = "transient"
FAULT_PERSISTENT = "persistent"
FAULT_CORRUPT = "corrupt"
FAULT_SLOW = "slow"

# injection sites (the seams instrumented in codec/transfer.py and
# runtime/scheduler.py)
SITE_DISPATCH = "dispatch"
SITE_FENCE = "fence"
SITE_FETCH = "fetch"
SITE_SNAPSHOT_UPDATE = "snapshot_update"
# the dirty-row scatter into a resident device buffer (H2D delta path,
# _scatter_rows / _scatter_rows_sharded): on a mesh each scatter lands on
# the shard that owns the rows, so a scatter-side fault is exactly the
# per-shard failure the elastic ladder attributes
SITE_SCATTER = "scatter"
SITES = (SITE_DISPATCH, SITE_FENCE, SITE_FETCH, SITE_SNAPSHOT_UPDATE,
         SITE_SCATTER)


class DeviceFault(RuntimeError):
    """Base for classified device-path failures (injected or mapped from
    real runtime errors).  `fault_class` drives the retry/breaker policy;
    `device_index` (when known) attributes the fault to ONE device of the
    mesh — jax device .id — so the scheduler can lose that shard instead
    of the whole mesh (runtime/scheduler.py elastic degradation ladder)."""

    fault_class = FAULT_TRANSIENT
    device_index: Optional[int] = None


class TransientDeviceError(DeviceFault):
    """Clears on its own: retry the same batch with backoff."""

    fault_class = FAULT_TRANSIENT


class PersistentDeviceError(DeviceFault):
    """Device lost: trip the breaker, degrade to the CPU engine."""

    fault_class = FAULT_PERSISTENT


class CorruptedFetchError(DeviceFault):
    """A fetch returned structurally-invalid data (winner rows out of
    range).  Retried like a transient fault — the wire, not the program."""

    fault_class = FAULT_TRANSIENT


# XLA status substrings -> fault class.  jaxlib surfaces device errors as
# XlaRuntimeError (a RuntimeError subclass) whose message leads with the
# absl status code; the split below mirrors how large control planes
# (PAPERS.md Borg/Omega lineage) bucket infra errors: codes that clear on
# retry vs codes that mean the backend is gone.
_PERSISTENT_MARKERS = (
    "device lost",
    "DATA_LOSS",
    "INTERNAL:",
    "FAILED_PRECONDITION",
    "device halted",
)
_TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "DEADLINE_EXCEEDED",
    "UNAVAILABLE",
    "ABORTED",
    "CANCELLED",
)


def classify_device_error(err: BaseException) -> Optional[str]:
    """Map an exception raised on the device path to a fault class, or None
    when it is NOT a device fault (a programming error must propagate, not
    be retried into oblivion)."""
    if isinstance(err, DeviceFault):
        return err.fault_class
    # real XLA runtime errors: XlaRuntimeError subclasses RuntimeError; the
    # name check keeps this import-free (jaxlib's module path moves between
    # releases)
    if isinstance(err, RuntimeError):
        msg = str(err)
        for marker in _PERSISTENT_MARKERS:
            if marker in msg:
                return FAULT_PERSISTENT
        for marker in _TRANSIENT_MARKERS:
            if marker in msg:
                return FAULT_TRANSIENT
        if type(err).__name__ == "XlaRuntimeError":
            # unknown runtime status from the device: worth one retry round
            return FAULT_TRANSIENT
    return None


# "device 3", "device: 3", "device #3", "TPU_2" — the message shapes real
# runtimes use when they can name the failing chip.  Deliberately narrow:
# a miss means "unattributed" (whole-mesh policy), never a wrong shard.
_DEVICE_ID_RE = re.compile(r"\bdevice[ :#]+(\d+)\b|\bTPU_(\d+)\b")


def fault_device_index(err: BaseException) -> Optional[int]:
    """Which device (jax .id) a classified device fault blames, or None
    when the error names no single device.  Injected faults carry the
    index as an attribute; real XLA runtime errors are matched against
    the narrow message patterns above."""
    idx = getattr(err, "device_index", None)
    if idx is not None:
        return int(idx)
    if isinstance(err, RuntimeError):
        mt = _DEVICE_ID_RE.search(str(err))
        if mt is not None:
            return int(mt.group(1) or mt.group(2))
    return None


@dataclass
class _Arm:
    kind: str
    p: float
    count: Optional[int]        # max fires; None = unlimited
    latency_s: float
    # shard-targeted arm: fire only when the instrumented call reports
    # one of these devices (jax .id) among the devices it touches — the
    # "mesh device(s) are dead" chaos primitive.  None = untargeted (the
    # PR 3 behavior: every call at the site faults).
    device_index: Optional[frozenset] = None
    fired: int = 0


class FaultInjector:
    """Seeded, deterministic per-site fault injection.

    arm(site, kind, ...) arms one site with one fault kind; `count` bounds
    how many times it fires (the deterministic lever the fault-matrix
    tests use: count=1 == "exactly the first call faults"), `p` makes it
    probabilistic from the injector's own seeded rng.  `log` records every
    fire as (site, kind) for assertions."""

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)
        self._arms: dict = {}
        self.log: list = []

    def arm(
        self,
        site: str,
        kind: str = FAULT_TRANSIENT,
        p: float = 1.0,
        count: Optional[int] = None,
        latency_s: float = 0.01,
        device_index: Optional[int] = None,
    ) -> "FaultInjector":
        if site not in SITES:
            raise ValueError(f"unknown fault site {site!r} (sites: {SITES})")
        if kind not in (FAULT_TRANSIENT, FAULT_PERSISTENT, FAULT_CORRUPT,
                        FAULT_SLOW):
            raise ValueError(f"unknown fault kind {kind!r}")
        if device_index is not None and not isinstance(
            device_index, (set, frozenset, list, tuple)
        ):
            device_index = (device_index,)
        self._arms[site] = _Arm(
            kind=kind, p=p, count=count, latency_s=latency_s,
            device_index=(
                frozenset(int(d) for d in device_index)
                if device_index is not None else None
            ),
        )
        return self

    def arm_devices(
        self,
        site: str,
        devices: Iterable[int],
        kind: str = FAULT_PERSISTENT,
        count: Optional[int] = None,
    ) -> "FaultInjector":
        """Merge device targets into the site's arm (creating it when
        absent).  Unlike re-arming, an existing same-kind targeted arm
        keeps its consumed fire budget (`fired`) — the accumulate
        primitive Disruptions.shard_lost builds on (losing a second
        device must not refresh the first one's count= budget)."""
        targets = frozenset(int(d) for d in devices)
        arm = self._arms.get(site)
        if arm is not None and arm.device_index and arm.kind == kind:
            arm.device_index = arm.device_index | targets
            if count is not None:
                arm.count = count
            return self
        return self.arm(site, kind=kind, count=count, device_index=targets)

    def clear_devices(
        self, site: str, devices: Optional[Iterable[int]] = None
    ) -> None:
        """Remove device targets from the site's TARGETED arm (None =
        all of them), disarming the site when none remain; the arm's
        remaining budget is preserved.  Untargeted arms are never
        touched — they belong to other primitives."""
        arm = self._arms.get(site)
        if arm is None or arm.device_index is None:
            return
        remaining = (
            arm.device_index - frozenset(int(d) for d in devices)
            if devices is not None else frozenset()
        )
        if remaining:
            arm.device_index = remaining
        else:
            del self._arms[site]

    def is_armed(self, site: str) -> bool:
        return site in self._arms

    def disarm(self, site: Optional[str] = None) -> None:
        if site is None:
            self._arms.clear()
        else:
            self._arms.pop(site, None)

    def _should_fire(self, a: _Arm) -> bool:
        if a.count is not None and a.fired >= a.count:
            return False
        if a.p < 1.0 and self._rng.random() >= a.p:
            return False
        return True

    def fire(self, site: str,
             devices: Optional[Iterable[int]] = None) -> None:
        """Raise/sleep per the site's armed fault; corrupt-kind arms are
        handled by maybe_corrupt (they alter data, not control flow).

        `devices` reports which device ids the instrumented call touches
        (the mesh's device set at dispatch/scatter, the fetched buffer's
        sharding at fetch/fence; None = unknown).  A shard-targeted arm
        (device_index set) fires only when its device is among them — a
        dead shard faults every computation that involves it, lets
        everything else through, and the half-open probe of exactly that
        device (devices=(d,)) keeps failing until the arm clears."""
        a = self._arms.get(site)
        if a is None or a.kind == FAULT_CORRUPT:
            return
        hit: Optional[int] = None
        if a.device_index is not None:
            if devices is None:
                return
            common = a.device_index.intersection(
                int(d) for d in devices
            )
            if not common:
                return
            # the error blames ONE device (the attribution contract);
            # min() keeps repeated fires deterministic
            hit = min(common)
        if not self._should_fire(a):
            return
        a.fired += 1
        self.log.append((site, a.kind))
        if a.kind == FAULT_SLOW:
            time.sleep(a.latency_s)
            return
        if a.kind == FAULT_PERSISTENT:
            err: DeviceFault = PersistentDeviceError(
                f"injected device-lost at {site} (fire #{a.fired})"
            )
        else:
            err = TransientDeviceError(
                f"injected transient XLA error at {site} (fire #{a.fired}): "
                "UNAVAILABLE: fabric tunnel reset"
            )
        err.device_index = hit
        raise err

    def maybe_corrupt(self, site: str, arr):
        """Scramble a fetched array when the site is armed with a corrupt
        fault: winner rows are pushed far out of range so structural
        validation (scheduler._validate_hosts) catches it — the seam has no
        checksum, so in-range corruption is out of scope by design."""
        a = self._arms.get(site)
        if a is None or a.kind != FAULT_CORRUPT or not self._should_fire(a):
            return arr
        a.fired += 1
        self.log.append((site, FAULT_CORRUPT))
        out = np.array(arr)
        if out.dtype.kind in ("i", "u"):
            out = out + (1 << 20)
        else:
            out = out + np.float32(3.0e38)
        return out


# ------------------------------------------------------- the global seam

_INJECTOR: Optional[FaultInjector] = None


def install_injector(inj: FaultInjector) -> Callable[[], None]:
    """Install `inj` as the process-wide injector; returns a remover that
    restores whatever was installed before (tests stack cleanly)."""
    global _INJECTOR
    prev = _INJECTOR
    _INJECTOR = inj

    def remove() -> None:
        global _INJECTOR
        _INJECTOR = prev

    return remove


def current_injector() -> Optional[FaultInjector]:
    return _INJECTOR


def check(site: str, devices: Optional[Iterable[int]] = None) -> None:
    """Instrumentation hook: fire the armed fault for `site`, if any.
    `devices` (optional) names the device ids the call touches so
    shard-targeted arms can fire selectively (see FaultInjector.fire)."""
    inj = _INJECTOR
    if inj is not None:
        inj.fire(site, devices=devices)


def corrupt(site: str, arr):
    """Instrumentation hook: corrupt fetched data for `site`, if armed."""
    inj = _INJECTOR
    if inj is not None:
        return inj.maybe_corrupt(site, arr)
    return arr
