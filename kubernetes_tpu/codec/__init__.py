"""Tensor schema + snapshot codec: the device mirror of the scheduler cache.

This is the TPU-native redesign of `NodeInfo` / `NodeInfoSnapshot`
(ref pkg/scheduler/nodeinfo/node_info.go:47-148,
pkg/scheduler/internal/cache/interface.go:125-128): instead of a map of
per-node structs, cluster state is a struct-of-arrays over the node axis, with
all strings interned to int32 ids on the host so every predicate/priority
becomes pure integer/float tensor math on device.
"""

from kubernetes_tpu.codec.interner import Interner
from kubernetes_tpu.codec.schema import (
    ClusterTensors,
    PodBatch,
    PadDims,
    EFFECT_CODES,
    TOL_OP_CODES,
    SEL_OP_CODES,
    FIELD_NODE_NAME,
    PAD,
    WILDCARD,
)
from kubernetes_tpu.codec.encoder import SnapshotEncoder
