"""Object model: the minimal, scheduler-relevant slice of the Kubernetes API.

Reference: staging/src/k8s.io/api/core/v1/types.go and pkg/apis/core/types.go.
Only the fields the scheduling pipeline reads are modeled; everything is a
plain frozen-ish dataclass with a `from_dict` codec accepting the familiar
Kubernetes JSON/YAML shapes.
"""

from kubernetes_tpu.api.resource import Quantity, parse_quantity
from kubernetes_tpu.api.labels import (
    Requirement,
    Selector,
    selector_from_label_selector,
    selector_from_match_labels,
)
from kubernetes_tpu.api.storage import (
    PersistentVolume,
    PersistentVolumeClaim,
    StorageClass,
    IMMEDIATE,
    WAIT_FOR_FIRST_CONSUMER,
)
from kubernetes_tpu.api.types import (
    Affinity,
    Container,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    ObjectMeta,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodSpec,
    PodStatus,
    NodeStatus,
    NodeSpec,
    ContainerImage,
    ContainerPort,
    Taint,
    Toleration,
    WeightedPodAffinityTerm,
    PreferredSchedulingTerm,
)
