"""Storage object model: PV / PVC / StorageClass (scheduler-relevant slice).

Reference: staging/src/k8s.io/api/core/v1/types.go (PersistentVolume,
PersistentVolumeClaim) and storage/v1 StorageClass.  The scheduler consumes:
  * PVC -> bound PV (spec.volumeName) or its storageClassName for binding;
  * PV zone/region labels (NoVolumeZoneConflict, predicates.go:616-741);
  * PV spec.nodeAffinity.required (CheckVolumeBinding via the volume binder);
  * the PV's source type (MaxVolumeCount filters, csi for MaxCSIVolumeCount);
  * StorageClass.volumeBindingMode: Immediate vs WaitForFirstConsumer
    (delayed binding — the scheduler picks the node first).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from kubernetes_tpu.api.resource import Quantity, parse_quantity
from kubernetes_tpu.api.types import NodeSelector, ObjectMeta

IMMEDIATE = "Immediate"
WAIT_FOR_FIRST_CONSUMER = "WaitForFirstConsumer"

# volume source kinds the filters care about
SRC_EBS = "awsElasticBlockStore"
SRC_GCE = "gcePersistentDisk"
SRC_AZURE = "azureDisk"
SRC_CINDER = "cinder"
SRC_CSI = "csi"

# which spec field carries each source kind's volume identity
_SRC_ID_FIELD = {
    SRC_EBS: "volumeID", SRC_GCE: "pdName", SRC_AZURE: "diskName",
    SRC_CINDER: "volumeID", SRC_CSI: "volumeHandle",
}


def _storage_meta(meta: "ObjectMeta", namespaced: bool) -> dict:
    out = {"name": meta.name, "labels": dict(meta.labels)}
    if namespaced:
        out["namespace"] = meta.namespace
    if meta.deletion_timestamp is not None:
        out["deletionTimestamp"] = meta.deletion_timestamp
    if meta.finalizers:
        out["finalizers"] = list(meta.finalizers)
    return out


@dataclass
class PersistentVolume:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    capacity: Optional[Quantity] = None
    access_modes: Tuple[str, ...] = ()
    storage_class: str = ""
    node_affinity: Optional[NodeSelector] = None  # spec.nodeAffinity.required
    source_kind: str = ""                          # SRC_* ("" unknown)
    csi_driver: str = ""
    # the underlying volume identity (EBS volumeID / GCE pdName / Azure
    # diskName / Cinder volumeID / CSI volumeHandle): attach-count dedup
    # keys by THIS, so a PV and a direct volume over the same disk (or two
    # PVs over one disk) count once (filterVolumes FilterPersistentVolume)
    source_id: str = ""
    phase: str = "Available"                       # Available | Bound | ...
    claim_ref: str = ""                            # "ns/name" of bound PVC
    # persistentVolumeReclaimPolicy: Retain | Delete (Recycle deprecated);
    # manual PVs default Retain, dynamically provisioned ones Delete
    reclaim_policy: str = "Retain"

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return ""  # cluster-scoped

    @property
    def labels(self) -> Dict[str, str]:
        return self.metadata.labels

    def to_dict(self) -> dict:
        src: Dict[str, dict] = {}
        if self.source_kind:
            src[self.source_kind] = {
                _SRC_ID_FIELD[self.source_kind]: self.source_id}
            if self.source_kind == SRC_CSI and self.csi_driver:
                src[self.source_kind]["driver"] = self.csi_driver
        spec = {
            "capacity": ({"storage": str(self.capacity)}
                         if self.capacity is not None else {}),
            "accessModes": list(self.access_modes),
            "storageClassName": self.storage_class,
            "persistentVolumeReclaimPolicy": self.reclaim_policy,
            **src,
        }
        if self.node_affinity is not None:
            spec["nodeAffinity"] = {"required": self.node_affinity.to_dict()}
        if self.claim_ref:
            ns, _, nm = self.claim_ref.partition("/")
            spec["claimRef"] = {"namespace": ns, "name": nm}
        return {
            "kind": "PersistentVolume", "apiVersion": "v1",
            "metadata": _storage_meta(self.metadata, namespaced=False),
            "spec": spec,
            "status": {"phase": self.phase},
        }

    @staticmethod
    def from_dict(d: dict) -> "PersistentVolume":
        spec = d.get("spec") or {}
        source_kind = ""
        csi_driver = ""
        source_id = ""
        for k in (SRC_EBS, SRC_GCE, SRC_AZURE, SRC_CINDER, SRC_CSI):
            if k in spec:
                source_kind = k
                source_id = spec[k].get(_SRC_ID_FIELD[k], "")
                if k == SRC_CSI:
                    csi_driver = spec[k].get("driver", "")
                break
        na = None
        aff = (spec.get("nodeAffinity") or {}).get("required")
        if aff:
            na = NodeSelector.from_dict(aff)
        cap = (spec.get("capacity") or {}).get("storage")
        cr = spec.get("claimRef") or {}
        return PersistentVolume(
            metadata=ObjectMeta.from_dict(d.get("metadata")),
            capacity=parse_quantity(cap) if cap is not None else None,
            access_modes=tuple(spec.get("accessModes") or ()),
            storage_class=spec.get("storageClassName", ""),
            node_affinity=na,
            source_kind=source_kind,
            csi_driver=csi_driver,
            source_id=source_id,
            phase=(d.get("status") or {}).get("phase", "Available"),
            claim_ref=f"{cr.get('namespace', '')}/{cr.get('name', '')}" if cr else "",
            reclaim_policy=spec.get("persistentVolumeReclaimPolicy", "Retain"),
        )


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    storage_class: str = ""
    volume_name: str = ""         # bound PV
    request: Optional[Quantity] = None
    access_modes: Tuple[str, ...] = ()
    phase: str = "Pending"

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    @staticmethod
    def from_dict(d: dict) -> "PersistentVolumeClaim":
        spec = d.get("spec") or {}
        req = ((spec.get("resources") or {}).get("requests") or {}).get("storage")
        return PersistentVolumeClaim(
            metadata=ObjectMeta.from_dict(d.get("metadata")),
            storage_class=spec.get("storageClassName", ""),
            volume_name=spec.get("volumeName", ""),
            request=parse_quantity(req) if req is not None else None,
            access_modes=tuple(spec.get("accessModes") or ()),
            phase=(d.get("status") or {}).get("phase", "Pending"),
        )

    def to_dict(self) -> dict:
        return {
            "kind": "PersistentVolumeClaim", "apiVersion": "v1",
            "metadata": _storage_meta(self.metadata, namespaced=True),
            "spec": {
                "storageClassName": self.storage_class,
                "volumeName": self.volume_name,
                "accessModes": list(self.access_modes),
                "resources": {"requests": (
                    {"storage": str(self.request)}
                    if self.request is not None else {}
                )},
            },
            "status": {"phase": self.phase},
        }


@dataclass
class StorageClass:
    name: str = ""
    provisioner: str = ""
    binding_mode: str = IMMEDIATE

    @property
    def namespace(self) -> str:
        return ""  # cluster-scoped

    @staticmethod
    def from_dict(d: dict) -> "StorageClass":
        return StorageClass(
            name=(d.get("metadata") or {}).get("name", ""),
            provisioner=d.get("provisioner", ""),
            binding_mode=d.get("volumeBindingMode", IMMEDIATE),
        )

    def to_dict(self) -> dict:
        return {
            "kind": "StorageClass", "apiVersion": "storage.k8s.io/v1",
            "metadata": {"name": self.name},
            "provisioner": self.provisioner,
            "volumeBindingMode": self.binding_mode,
        }
