"""Wire serializers: API objects -> Kubernetes JSON dicts.

The inverse of the from_dict codecs in api/types.py, shaped like the v1 wire
format (staging/src/k8s.io/api/core/v1/types.go JSON tags) so
`Pod.from_dict(pod_to_dict(p))` round-trips every field the model carries.
Used by the REST apiserver layer and the kubectl analog.
"""

from __future__ import annotations

from typing import Optional

from kubernetes_tpu.api.types import (
    Affinity,
    Container,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorTerm,
    PodAffinity,
    Pod,
)


def _drop_empty(d: dict) -> dict:
    return {k: v for k, v in d.items() if v not in (None, "", {}, [], ())}


def meta_to_dict(m) -> dict:
    out = {
        "name": m.name,
        "namespace": m.namespace,
        "labels": dict(m.labels),
        "annotations": dict(m.annotations),
        "uid": m.uid,
    }
    if m.owner_uid:
        out["ownerReferences"] = [
            {"kind": m.owner_kind, "uid": m.owner_uid, "controller": True}
        ]
    if m.deletion_timestamp is not None:
        out["deletionTimestamp"] = m.deletion_timestamp
    if m.finalizers:
        out["finalizers"] = list(m.finalizers)
    return _drop_empty(out)


def _container_to_dict(c: Container) -> dict:
    return _drop_empty({
        "name": c.name,
        "image": c.image,
        "resources": _drop_empty({
            "requests": {k: str(q) for k, q in c.requests.items()},
            "limits": {k: str(q) for k, q in c.limits.items()},
        }),
        "ports": [
            _drop_empty({
                "hostPort": p.host_port,
                "containerPort": p.container_port,
                "protocol": p.protocol,
                "hostIP": p.host_ip,
            })
            for p in c.ports
        ],
    })


def _nst_to_dict(t: NodeSelectorTerm) -> dict:
    return _drop_empty({
        "matchExpressions": [
            _drop_empty({"key": e.key, "operator": e.operator,
                         "values": list(e.values)})
            for e in t.match_expressions
        ],
        "matchFields": [
            _drop_empty({"key": e.key, "operator": e.operator,
                         "values": list(e.values)})
            for e in t.match_fields
        ],
    })


def _node_affinity_to_dict(na: NodeAffinity) -> dict:
    out = {}
    if na.required is not None:
        out["requiredDuringSchedulingIgnoredDuringExecution"] = {
            "nodeSelectorTerms": [_nst_to_dict(t) for t in na.required.terms]
        }
    if na.preferred:
        out["preferredDuringSchedulingIgnoredDuringExecution"] = [
            {"weight": p.weight, "preference": _nst_to_dict(p.preference)}
            for p in na.preferred
        ]
    return out


def _pod_affinity_to_dict(pa: PodAffinity) -> dict:
    def term(t):
        return _drop_empty({
            "labelSelector": t.label_selector,
            "topologyKey": t.topology_key,
            "namespaces": list(t.namespaces),
        })

    out = {}
    if pa.required:
        out["requiredDuringSchedulingIgnoredDuringExecution"] = [
            term(t) for t in pa.required
        ]
    if pa.preferred:
        out["preferredDuringSchedulingIgnoredDuringExecution"] = [
            {"weight": w.weight, "podAffinityTerm": term(w.term)}
            for w in pa.preferred
        ]
    return out


def _affinity_to_dict(a: Optional[Affinity]) -> Optional[dict]:
    if a is None:
        return None
    out = {}
    if a.node_affinity is not None:
        out["nodeAffinity"] = _node_affinity_to_dict(a.node_affinity)
    if a.pod_affinity is not None:
        out["podAffinity"] = _pod_affinity_to_dict(a.pod_affinity)
    if a.pod_anti_affinity is not None:
        out["podAntiAffinity"] = _pod_affinity_to_dict(a.pod_anti_affinity)
    return out or None


def pod_to_dict(pod: Pod) -> dict:
    spec = _drop_empty({
        "nodeName": pod.spec.node_name,
        "nodeSelector": dict(pod.spec.node_selector),
        "affinity": _affinity_to_dict(pod.spec.affinity),
        "tolerations": [
            _drop_empty({
                "key": t.key, "operator": t.operator,
                "value": t.value, "effect": t.effect,
            })
            for t in pod.spec.tolerations
        ],
        "containers": [_container_to_dict(c) for c in pod.spec.containers],
        "initContainers": [
            _container_to_dict(c) for c in pod.spec.init_containers
        ],
        "priority": pod.spec.priority,
        "volumes": [dict(v) for v in pod.spec.volumes],
        "serviceAccountName": pod.spec.service_account_name,
    })
    spec["schedulerName"] = pod.spec.scheduler_name
    return {
        "kind": "Pod",
        "apiVersion": "v1",
        "metadata": meta_to_dict(pod.metadata),
        "spec": spec,
        "status": _drop_empty({
            "phase": pod.status.phase,
            "reason": pod.status.reason or None,
            "message": pod.status.message or None,
            "startTime": pod.status.start_time or None,
            "nominatedNodeName": pod.status.nominated_node_name,
            "conditions": (
                [{"type": "Ready", "status": "False"}]
                if not pod.status.ready else None
            ),
            "containerStatuses": (
                [{"restartCount": pod.status.restart_count}]
                if pod.status.restart_count else None
            ),
        }),
    }


def node_to_dict(node: Node) -> dict:
    return {
        "kind": "Node",
        "apiVersion": "v1",
        "metadata": meta_to_dict(node.metadata),
        "spec": _drop_empty({
            "unschedulable": node.spec.unschedulable or None,
            "podCIDR": node.spec.pod_cidr,
            "taints": [
                _drop_empty({"key": t.key, "value": t.value,
                             "effect": t.effect})
                for t in node.spec.taints
            ],
        }),
        "status": _drop_empty({
            "allocatable": {
                k: str(q) for k, q in node.status.allocatable.items()
            },
            "capacity": {k: str(q) for k, q in node.status.capacity.items()},
            "images": [
                {"names": list(i.names), "sizeBytes": i.size_bytes}
                for i in node.status.images
            ],
            "conditions": [
                {"type": k, "status": v}
                for k, v in sorted(node.status.conditions.items())
            ],
            "volumesAttached": [
                {"name": n, "devicePath": ""}
                for n in node.status.volumes_attached
            ],
        }),
    }


def object_to_dict(kind: str, obj) -> dict:
    if kind == "pods":
        return pod_to_dict(obj)
    if kind == "nodes":
        return node_to_dict(obj)
    if kind in ("persistentvolumes", "persistentvolumeclaims",
                "storageclasses"):
        return obj.to_dict()
    if isinstance(obj, dict):
        return obj  # services / leases / raw objects
    if kind == "deployments":
        dep_meta = {"name": obj.name, "namespace": obj.namespace,
                    "uid": obj.uid}
        if getattr(obj, "labels", None):
            dep_meta["labels"] = dict(obj.labels)
        if getattr(obj, "annotations", None):
            dep_meta["annotations"] = dict(obj.annotations)
        return {
            "kind": "Deployment",
            "apiVersion": "apps/v1",
            "metadata": dep_meta,
            "spec": {
                "replicas": obj.replicas,
                "selector": {"matchLabels": dict(obj.selector)},
                "template": obj.template,
                "strategy": {
                    "type": obj.strategy,
                    "rollingUpdate": {"maxSurge": obj.max_surge,
                                      "maxUnavailable": obj.max_unavailable},
                },
            },
        }
    if kind == "poddisruptionbudgets":
        return {
            "kind": "PodDisruptionBudget",
            "apiVersion": "policy/v1beta1",
            "metadata": meta_to_dict(obj.metadata),
            "spec": _drop_empty({
                "selector": obj.selector,
                "minAvailable": obj.min_available,
                "maxUnavailable": obj.max_unavailable,
            }),
            "status": {"disruptionsAllowed": obj.disruptions_allowed},
        }
    if kind == "jobs":
        job_meta = {"name": obj.name, "namespace": obj.namespace,
                    "uid": obj.uid}
        if getattr(obj, "owner_uid", ""):
            job_meta["ownerReferences"] = [{"kind": "CronJob",
                                            "uid": obj.owner_uid,
                                            "controller": True}]
        return {
            "kind": "Job",
            "apiVersion": "batch/v1",
            "metadata": job_meta,
            "spec": _drop_empty({"completions": obj.completions,
                     "parallelism": obj.parallelism,
                     "backoffLimit": obj.backoff_limit,
                     "ttlSecondsAfterFinished":
                         obj.ttl_seconds_after_finished,
                     "template": obj.template}),
            "status": _drop_empty({
                "succeeded": obj.succeeded,
                "failed": obj.failed,
                "completionTime": obj.finished_at or None,
                "conditions": (
                    [{"type": "Complete", "status": "True"}]
                    if obj.complete else
                    ([{"type": "Failed", "status": "True"}]
                     if getattr(obj, "failed_state", False) else [])
                ),
            }),
        }
    if kind == "daemonsets":
        return {
            "kind": "DaemonSet",
            "apiVersion": "apps/v1",
            "metadata": {"name": obj.name, "namespace": obj.namespace,
                         "uid": obj.uid},
            "spec": {
                "selector": {"matchLabels": dict(obj.selector)},
                "template": obj.template,
            },
        }
    if kind == "statefulsets":
        st_spec = {
            "replicas": obj.replicas,
            "selector": {"matchLabels": dict(obj.selector)},
            "template": obj.template,
        }
        if getattr(obj, "volume_claim_templates", ()):
            st_spec["volumeClaimTemplates"] = [
                dict(t) for t in obj.volume_claim_templates]
        return {
            "kind": "StatefulSet",
            "apiVersion": "apps/v1",
            "metadata": {"name": obj.name, "namespace": obj.namespace,
                         "uid": obj.uid},
            "spec": st_spec,
        }
    if kind == "cronjobs":
        return {
            "kind": "CronJob",
            "apiVersion": "batch/v1beta1",
            "metadata": {"name": obj.name, "namespace": obj.namespace,
                         "uid": obj.uid},
            "spec": _drop_empty({
                "schedule": obj.schedule,
                "jobTemplate": obj.job_template,
                "concurrencyPolicy": obj.concurrency_policy,
                "suspend": obj.suspend,
            }),
            # status.lastScheduleTime round-trips the dedup state: a
            # read-modify-write must not allow the same minute to fire twice
            "status": _drop_empty({
                "lastScheduleTime": (
                    obj.last_schedule_minute * 60
                    if obj.last_schedule_minute >= 0 else None
                ),
            }),
        }
    if kind == "horizontalpodautoscalers":
        return {
            "kind": "HorizontalPodAutoscaler",
            "apiVersion": "autoscaling/v1",
            "metadata": {"name": obj.name, "namespace": obj.namespace,
                         "uid": obj.uid},
            "spec": {
                "scaleTargetRef": {"kind": obj.target_kind,
                                   "name": obj.target_name},
                "minReplicas": obj.min_replicas,
                "maxReplicas": obj.max_replicas,
                "targetCPUUtilizationPercentage": obj.target_cpu_utilization,
            },
            "status": {"currentReplicas": obj.current_replicas,
                       "desiredReplicas": obj.desired_replicas},
        }
    if kind == "replicationcontrollers":
        return {
            "kind": "ReplicationController",
            "apiVersion": "v1",
            "metadata": {"name": obj.name, "namespace": obj.namespace,
                         "uid": obj.uid},
            "spec": {
                "replicas": obj.replicas,
                "selector": dict(obj.selector),   # plain map (core/v1)
                "template": obj.template,
            },
        }
    if kind == "replicasets":
        meta = {"name": obj.name, "namespace": obj.namespace,
                "uid": obj.uid}
        if getattr(obj, "annotations", None):
            meta["annotations"] = dict(obj.annotations)
        if obj.owner_uid:
            # the Deployment->RS controller link must survive the wire or a
            # remote controller-manager orphans every managed ReplicaSet
            meta["ownerReferences"] = [{"kind": "Deployment",
                                        "uid": obj.owner_uid,
                                        "controller": True}]
        return {
            "kind": "ReplicaSet",
            "apiVersion": "apps/v1",
            "metadata": meta,
            "spec": {
                "replicas": obj.replicas,
                "selector": {"matchLabels": dict(obj.selector)},
                "template": obj.template,
            },
        }
    raise ValueError(f"unknown kind {kind!r}")
