"""Resource quantities.

Reference: staging/src/k8s.io/apimachinery/pkg/api/resource (the `Quantity`
type).  The reference implements infinite-precision decimal arithmetic with
canonical serialization; the scheduler only ever uses quantities through
`MilliValue()` (CPU) and `Value()` (memory/storage/counts) — see
pkg/scheduler/nodeinfo/node_info.go:139-148 (`Resource{MilliCPU, Memory, ...}`).

We therefore parse to exact integers where possible and hold a float fallback,
which is lossless for every practically-occurring quantity ("100m", "2Gi",
"1.5G", "250M", plain integers).
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass
from fractions import Fraction

_BIN_SUFFIX = {
    "Ki": 2**10,
    "Mi": 2**20,
    "Gi": 2**30,
    "Ti": 2**40,
    "Pi": 2**50,
    "Ei": 2**60,
}
_DEC_SUFFIX = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 10**3),
    "": Fraction(1),
    "k": Fraction(10**3),
    "M": Fraction(10**6),
    "G": Fraction(10**9),
    "T": Fraction(10**12),
    "P": Fraction(10**15),
    "E": Fraction(10**18),
}

_QTY_RE = re.compile(
    r"^\s*(?P<sign>[+-]?)(?P<num>\d+(?:\.\d*)?|\.\d+)"
    r"(?:[eE](?P<exp>[+-]?\d+))?"
    r"(?P<suffix>Ki|Mi|Gi|Ti|Pi|Ei|n|u|m|k|M|G|T|P|E)?\s*$"
)


@dataclass(frozen=True)
class Quantity:
    """An exact rational quantity; arithmetic stays exact."""

    value: Fraction

    def __hash__(self) -> int:
        # Fraction.__hash__ is modular-inverse arithmetic; quantities are
        # hashed on every (req, nonzero) memo lookup in the cache-commit
        # path, so memoize it on the (frozen) instance.
        h = self.__dict__.get("_hash")
        if h is None:
            h = hash(self.value)
            object.__setattr__(self, "_hash", h)
        return h

    @property
    def milli(self) -> int:
        """MilliValue(): value * 1000 rounded up (ref resource.Quantity.MilliValue)."""
        return math.ceil(self.value * 1000)

    @property
    def scalar(self) -> int:
        """Value(): rounded up to the nearest integer."""
        return math.ceil(self.value)

    def __float__(self) -> float:
        return float(self.value)

    def __add__(self, other: "Quantity") -> "Quantity":
        return Quantity(self.value + other.value)

    def __sub__(self, other: "Quantity") -> "Quantity":
        return Quantity(self.value - other.value)

    def __lt__(self, other: "Quantity") -> bool:
        return self.value < other.value

    def __le__(self, other: "Quantity") -> bool:
        return self.value <= other.value

    def __str__(self) -> str:
        if self.value.denominator == 1:
            return str(self.value.numerator)
        return str(float(self.value))


_PARSE_MEMO: dict = {}


def parse_quantity(s: "str | int | float | Quantity") -> Quantity:
    """Parse a Kubernetes quantity string ("100m", "2Gi", "1e3", 4) exactly.

    String parses are memoized to a canonical instance: workloads stamp
    thousands of pods with identical request strings, and sharing the
    instance lets downstream dict/tuple comparisons take the identity
    fast path (Quantity is immutable, so sharing is safe)."""
    if isinstance(s, Quantity):
        return s
    if isinstance(s, str):
        q = _PARSE_MEMO.get(s)
        if q is None:
            if len(_PARSE_MEMO) > 65536:
                _PARSE_MEMO.clear()
            q = _PARSE_MEMO[s] = _parse_quantity_str(s)
        return q
    if isinstance(s, int):
        # ints memoize like strings (pods: 110 across a 5k-node fleet):
        # sharing the canonical instance lets downstream memo keys take the
        # identity fast path; bool is an int subtype, fine to share too
        q = _PARSE_MEMO.get(s)
        if q is None:
            if len(_PARSE_MEMO) > 65536:
                _PARSE_MEMO.clear()
            q = _PARSE_MEMO[s] = Quantity(Fraction(s))
        return q
    if isinstance(s, float):
        return Quantity(Fraction(s).limit_denominator(10**9))
    raise ValueError(f"invalid quantity {s!r}")


def _parse_quantity_str(s: str) -> Quantity:
    m = _QTY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity {s!r}")
    num_str = m.group("num")
    if num_str.startswith("."):
        num_str = "0" + num_str
    if num_str.endswith("."):
        num_str += "0"
    num = Fraction(num_str)
    if m.group("sign") == "-":
        num = -num
    exp = m.group("exp")
    if exp is not None:
        num *= Fraction(10) ** int(exp)
    suffix = m.group("suffix")
    if suffix in _BIN_SUFFIX:
        num *= _BIN_SUFFIX[suffix]
    elif suffix:
        num *= _DEC_SUFFIX[suffix]
    return Quantity(num)
