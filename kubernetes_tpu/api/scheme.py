"""Scheme: the GroupVersionKind registry + codec dispatch.

Reference: staging/src/k8s.io/apimachinery/pkg/runtime (runtime.Scheme,
`schema.GroupVersionKind`) — one registry answering "what wire identity
does this storage kind carry, and how do its objects encode/decode".
Every serialization seam (REST layer, WAL/snapshot persistence, the
reflector) dispatches through here instead of growing private tables.

  gvk_for("deployments")      -> GroupVersionKind("apps", "v1", "Deployment")
  rest_path("jobs", "ns")     -> "/apis/batch/v1/namespaces/ns/jobs"
  decode("pods", wire_dict)   -> Pod
  encode("pods", pod)         -> wire dict

Dynamic (CRD-established) kinds — the "<plural>.<group>" convention —
resolve to their group with version v1* and encode/decode as wire dicts,
the unstructured.Unstructured analog.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional


@dataclass(frozen=True)
class GroupVersionKind:
    group: str          # "" = core
    version: str
    kind: str           # wire Kind ("Pod")

    @property
    def api_version(self) -> str:
        return self.version if not self.group else f"{self.group}/{self.version}"


# storage kind -> (GVK, cluster_scoped)
_REGISTRY: Dict[str, tuple] = {
    "pods": (GroupVersionKind("", "v1", "Pod"), False),
    "nodes": (GroupVersionKind("", "v1", "Node"), True),
    "services": (GroupVersionKind("", "v1", "Service"), False),
    "endpoints": (GroupVersionKind("", "v1", "Endpoints"), False),
    "namespaces": (GroupVersionKind("", "v1", "Namespace"), True),
    "limitranges": (GroupVersionKind("", "v1", "LimitRange"), False),
    "resourcequotas": (GroupVersionKind("", "v1", "ResourceQuota"), False),
    "leases": (
        GroupVersionKind("coordination.k8s.io", "v1", "Lease"), False),
    "priorityclasses": (
        GroupVersionKind("scheduling.k8s.io", "v1beta1", "PriorityClass"),
        True),
    "replicasets": (GroupVersionKind("apps", "v1", "ReplicaSet"), False),
    "deployments": (GroupVersionKind("apps", "v1", "Deployment"), False),
    "daemonsets": (GroupVersionKind("apps", "v1", "DaemonSet"), False),
    "statefulsets": (GroupVersionKind("apps", "v1", "StatefulSet"), False),
    "jobs": (GroupVersionKind("batch", "v1", "Job"), False),
    "cronjobs": (GroupVersionKind("batch", "v1beta1", "CronJob"), False),
    "horizontalpodautoscalers": (
        GroupVersionKind("autoscaling", "v1", "HorizontalPodAutoscaler"),
        False),
    "poddisruptionbudgets": (
        GroupVersionKind("policy", "v1beta1", "PodDisruptionBudget"), False),
    "customresourcedefinitions": (
        GroupVersionKind("apiextensions.k8s.io", "v1beta1",
                         "CustomResourceDefinition"), True),
    "apiservices": (
        GroupVersionKind("apiregistration.k8s.io", "v1", "APIService"), True),
    "secrets": (GroupVersionKind("", "v1", "Secret"), False),
    "serviceaccounts": (GroupVersionKind("", "v1", "ServiceAccount"), False),
    "roles": (
        GroupVersionKind("rbac.authorization.k8s.io", "v1", "Role"), False),
    "rolebindings": (
        GroupVersionKind("rbac.authorization.k8s.io", "v1", "RoleBinding"),
        False),
    "clusterroles": (
        GroupVersionKind("rbac.authorization.k8s.io", "v1", "ClusterRole"),
        True),
    "clusterrolebindings": (
        GroupVersionKind("rbac.authorization.k8s.io", "v1",
                         "ClusterRoleBinding"), True),
    "persistentvolumes": (
        GroupVersionKind("", "v1", "PersistentVolume"), True),
    "persistentvolumeclaims": (
        GroupVersionKind("", "v1", "PersistentVolumeClaim"), False),
    "storageclasses": (
        GroupVersionKind("storage.k8s.io", "v1", "StorageClass"), True),
    "replicationcontrollers": (
        GroupVersionKind("", "v1", "ReplicationController"), False),
    "certificatesigningrequests": (
        GroupVersionKind("certificates.k8s.io", "v1beta1",
                         "CertificateSigningRequest"), True),
    "configmaps": (GroupVersionKind("", "v1", "ConfigMap"), False),
    "mutatingwebhookconfigurations": (
        GroupVersionKind("admissionregistration.k8s.io", "v1",
                         "MutatingWebhookConfiguration"), True),
    "validatingwebhookconfigurations": (
        GroupVersionKind("admissionregistration.k8s.io", "v1",
                         "ValidatingWebhookConfiguration"), True),
}


def kinds() -> tuple:
    return tuple(_REGISTRY)


def gvk_for(kind: str) -> GroupVersionKind:
    """Storage kind -> wire identity; dynamic '<plural>.<group>' kinds map
    to their CRD group (unstructured)."""
    if kind in _REGISTRY:
        return _REGISTRY[kind][0]
    if "." in kind:
        # the true wire Kind lives in the CRD's spec.names.kind, which the
        # scheme cannot see — carry the plural verbatim (capitalized) the
        # way unstructured objects carry whatever the wire said; do NOT
        # guess singulars ("policies" -> "Policy" needs the CRD)
        plural, _, group = kind.partition(".")
        return GroupVersionKind(group, "v1", plural[:1].upper() + plural[1:])
    raise KeyError(f"unknown kind {kind!r}")


def is_cluster_scoped(kind: str) -> bool:
    if kind in _REGISTRY:
        return _REGISTRY[kind][1]
    return False  # custom resources default Namespaced (CRD spec.scope)


def kind_for_wire(wire_kind: str) -> Optional[str]:
    """Wire Kind ("Deployment") -> storage kind ("deployments")."""
    for k, (gvk, _) in _REGISTRY.items():
        if gvk.kind == wire_kind:
            return k
    return None


# kinds the server routes under their API group; everything else (core +
# cluster-scoped extension kinds) is served flat under /api/v1
_GROUP_ROUTED = (
    "replicasets", "deployments", "daemonsets", "statefulsets",
    "jobs", "cronjobs", "poddisruptionbudgets",
    "horizontalpodautoscalers",
)


def rest_path(kind: str, namespace: str = "default", name: str = "") -> str:
    """The REST collection/object path the API server actually serves for a
    kind (the RESTMapper half of the scheme)."""
    gvk = gvk_for(kind)
    if "." in kind:
        # custom resources serve under their CRD's group route
        plural, _, group = kind.partition(".")
        base = (f"/apis/{group}/{gvk.version}"
                f"/namespaces/{namespace}/{plural}")
    elif kind in _GROUP_ROUTED:
        base = (f"/apis/{gvk.group}/{gvk.version}"
                f"/namespaces/{namespace}/{kind}")
    elif is_cluster_scoped(kind):
        base = f"/api/v1/{kind}"
    else:
        base = f"/api/v1/namespaces/{namespace}/{kind}"
    return f"{base}/{name}" if name else base


def decode(kind: str, d: dict):
    """Wire dict -> stored object (the codec's Decode half)."""
    from kubernetes_tpu.apiserver.server import _decode

    return _decode(kind, d)


def encode(kind: str, obj) -> dict:
    """Stored object -> wire dict (the codec's Encode half)."""
    from kubernetes_tpu.api.serialize import object_to_dict

    return object_to_dict(kind, obj)
