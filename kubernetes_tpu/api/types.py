"""The scheduler-relevant slice of the Kubernetes object model.

Reference: staging/src/k8s.io/api/core/v1/types.go (Pod, Node, Affinity,
Taint/Toleration, ContainerPort, ...).  Modeled as plain dataclasses with
`from_dict` codecs that accept the familiar JSON/YAML wire shapes, so test
fixtures read like the reference's table-driven tests.

Only fields the scheduling pipeline consumes are present; adding more is a
matter of widening these dataclasses (no generated deepcopy machinery needed —
Python values are immutable-by-convention here).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Tuple

from kubernetes_tpu.api.resource import Quantity, parse_quantity

# Taint effects (ref core/v1/types.go TaintEffect)
TAINT_NO_SCHEDULE = "NoSchedule"
TAINT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
TAINT_NO_EXECUTE = "NoExecute"

# Toleration operators (ref core/v1/types.go TolerationOperator)
TOLERATION_OP_EQUAL = "Equal"
TOLERATION_OP_EXISTS = "Exists"

# Resource names the scheduler cares about (ref core/v1/types.go ResourceName,
# scheduler nodeinfo.Resource pkg/scheduler/nodeinfo/node_info.go:139-148)
RESOURCE_CPU = "cpu"
RESOURCE_MEMORY = "memory"
RESOURCE_EPHEMERAL_STORAGE = "ephemeral-storage"
RESOURCE_PODS = "pods"

# Non-zero defaults used by scoring when a pod declares no request
# (ref pkg/scheduler/util/non_zero.go:28-32)
DEFAULT_MILLI_CPU_REQUEST = 100
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024


def is_best_effort(pod: "Pod") -> bool:
    """QoS BestEffort: no container requests or limits (qos.GetPodQOS
    slice — the class CheckNodeMemoryPressure repels and the kubelet
    eviction manager ranks first)."""
    return all(not c.requests and not c.limits for c in pod.spec.containers)


def qos_class(pod: "Pod") -> str:
    """GetPodQOS (pkg/apis/core/v1/helper/qos/qos.go:37-95): Guaranteed =
    every container has limits == requests for cpu+memory; BestEffort = no
    requests/limits anywhere; Burstable = the rest."""
    if is_best_effort(pod):
        return "BestEffort"
    # only the supported compute resources participate (qos.go
    # supportedQoSComputeResources = {cpu, memory}): an extended-resource
    # request must not demote a pod out of Guaranteed
    for c in pod.spec.containers:
        for res in ("cpu", "memory"):
            if res not in c.limits:
                return "Burstable"
            if res in c.requests and c.requests[res] != c.limits[res]:
                return "Burstable"
    return "Guaranteed"


def parse_time(v) -> Optional[float]:
    """Timestamp codec: the Kubernetes wire format serializes times as
    RFC3339 strings (metav1.Time); tests and internal callers may pass epoch
    seconds directly.  Returns epoch seconds or None."""
    if v is None or v == "":
        return None
    if isinstance(v, (int, float)):
        return float(v)
    from datetime import datetime, timezone

    s = str(v)
    if s.endswith("Z"):
        s = s[:-1] + "+00:00"
    try:
        dt = datetime.fromisoformat(s)
    except ValueError:
        return None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt.timestamp()


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    uid: str = ""
    owner_uid: str = ""   # flattened controller ownerReference UID
    owner_kind: str = ""  # its kind (ReplicationController / ReplicaSet / ...)
    # epoch seconds when a graceful delete began, None if not deleting
    # (ref metav1.ObjectMeta.DeletionTimestamp; consulted by
    # podEligibleToPreemptOthers, generic_scheduler.go:1159-1180)
    deletion_timestamp: Optional[float] = None
    # deletion is deferred until every finalizer is removed
    # (ref metav1.ObjectMeta.Finalizers; store semantics in
    # runtime/cluster.py delete/update)
    finalizers: Tuple[str, ...] = ()

    @staticmethod
    def from_dict(d: Optional[dict]) -> "ObjectMeta":
        d = d or {}
        owner_uid = ""
        owner_kind = ""
        for ref in d.get("ownerReferences") or []:
            if ref.get("controller"):
                owner_uid = ref.get("uid", "")
                owner_kind = ref.get("kind", "")
        return ObjectMeta(
            name=d.get("name", ""),
            namespace=d.get("namespace", "default"),
            labels=dict(d.get("labels") or {}),
            annotations=dict(d.get("annotations") or {}),
            uid=d.get("uid", ""),
            owner_uid=owner_uid,
            owner_kind=owner_kind,
            deletion_timestamp=parse_time(d.get("deletionTimestamp")),
            finalizers=tuple(d.get("finalizers") or ()),
        )


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = TAINT_NO_SCHEDULE

    @staticmethod
    def from_dict(d: dict) -> "Taint":
        return Taint(d["key"], d.get("value", ""), d.get("effect", TAINT_NO_SCHEDULE))


@dataclass(frozen=True)
class Toleration:
    key: str = ""
    operator: str = TOLERATION_OP_EQUAL
    value: str = ""
    effect: str = ""  # empty matches all effects

    def tolerates(self, taint: Taint) -> bool:
        """ref staging/src/k8s.io/api/core/v1/toleration.go ToleratesTaint."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator == TOLERATION_OP_EXISTS:
            return True
        # Equal (or empty ≡ Equal)
        return self.value == taint.value

    @staticmethod
    def from_dict(d: dict) -> "Toleration":
        return Toleration(
            key=d.get("key", ""),
            operator=d.get("operator", TOLERATION_OP_EQUAL),
            value=d.get("value", ""),
            effect=d.get("effect", ""),
        )


@dataclass(frozen=True)
class NodeSelectorRequirement:
    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: Tuple[str, ...] = ()

    @staticmethod
    def from_dict(d: dict) -> "NodeSelectorRequirement":
        return NodeSelectorRequirement(
            d["key"], d["operator"], tuple(d.get("values") or ())
        )

    def to_dict(self) -> dict:
        out = {"key": self.key, "operator": self.operator}
        if self.values:
            out["values"] = list(self.values)
        return out


@dataclass(frozen=True)
class NodeSelectorTerm:
    match_expressions: Tuple[NodeSelectorRequirement, ...] = ()
    match_fields: Tuple[NodeSelectorRequirement, ...] = ()  # metadata.name only

    @staticmethod
    def from_dict(d: dict) -> "NodeSelectorTerm":
        return NodeSelectorTerm(
            tuple(
                NodeSelectorRequirement.from_dict(e)
                for e in d.get("matchExpressions") or ()
            ),
            tuple(
                NodeSelectorRequirement.from_dict(e)
                for e in d.get("matchFields") or ()
            ),
        )

    def to_dict(self) -> dict:
        out: dict = {}
        if self.match_expressions:
            out["matchExpressions"] = [
                e.to_dict() for e in self.match_expressions
            ]
        if self.match_fields:
            out["matchFields"] = [e.to_dict() for e in self.match_fields]
        return out


@dataclass(frozen=True)
class NodeSelector:
    """OR of terms; each term is an AND of expressions
    (ref core/v1/types.go NodeSelector)."""

    terms: Tuple[NodeSelectorTerm, ...] = ()

    @staticmethod
    def from_dict(d: dict) -> "NodeSelector":
        return NodeSelector(
            tuple(NodeSelectorTerm.from_dict(t) for t in d.get("nodeSelectorTerms") or ())
        )

    def to_dict(self) -> dict:
        return {"nodeSelectorTerms": [t.to_dict() for t in self.terms]}


@dataclass(frozen=True)
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm

    @staticmethod
    def from_dict(d: dict) -> "PreferredSchedulingTerm":
        return PreferredSchedulingTerm(
            int(d["weight"]), NodeSelectorTerm.from_dict(d["preference"])
        )


@dataclass(frozen=True)
class NodeAffinity:
    required: Optional[NodeSelector] = None
    preferred: Tuple[PreferredSchedulingTerm, ...] = ()

    @staticmethod
    def from_dict(d: dict) -> "NodeAffinity":
        req = d.get("requiredDuringSchedulingIgnoredDuringExecution")
        return NodeAffinity(
            required=NodeSelector.from_dict(req) if req is not None else None,
            preferred=tuple(
                PreferredSchedulingTerm.from_dict(t)
                for t in d.get("preferredDuringSchedulingIgnoredDuringExecution") or ()
            ),
        )


@dataclass(frozen=True)
class PodAffinityTerm:
    label_selector: Optional[dict]  # raw metav1.LabelSelector dict
    topology_key: str
    namespaces: Tuple[str, ...] = ()  # empty => the pod's own namespace

    @staticmethod
    def from_dict(d: dict) -> "PodAffinityTerm":
        return PodAffinityTerm(
            label_selector=d.get("labelSelector"),
            topology_key=d.get("topologyKey", ""),
            namespaces=tuple(d.get("namespaces") or ()),
        )


@dataclass(frozen=True)
class WeightedPodAffinityTerm:
    weight: int
    term: PodAffinityTerm

    @staticmethod
    def from_dict(d: dict) -> "WeightedPodAffinityTerm":
        return WeightedPodAffinityTerm(
            int(d["weight"]), PodAffinityTerm.from_dict(d["podAffinityTerm"])
        )


@dataclass(frozen=True)
class PodAffinity:
    required: Tuple[PodAffinityTerm, ...] = ()
    preferred: Tuple[WeightedPodAffinityTerm, ...] = ()

    @staticmethod
    def from_dict(d: dict) -> "PodAffinity":
        return PodAffinity(
            required=tuple(
                PodAffinityTerm.from_dict(t)
                for t in d.get("requiredDuringSchedulingIgnoredDuringExecution") or ()
            ),
            preferred=tuple(
                WeightedPodAffinityTerm.from_dict(t)
                for t in d.get("preferredDuringSchedulingIgnoredDuringExecution") or ()
            ),
        )


PodAntiAffinity = PodAffinity  # same shape


@dataclass(frozen=True)
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAffinity] = None

    @staticmethod
    def from_dict(d: Optional[dict]) -> Optional["Affinity"]:
        if not d:
            return None
        return Affinity(
            node_affinity=NodeAffinity.from_dict(d["nodeAffinity"])
            if d.get("nodeAffinity")
            else None,
            pod_affinity=PodAffinity.from_dict(d["podAffinity"])
            if d.get("podAffinity")
            else None,
            pod_anti_affinity=PodAffinity.from_dict(d["podAntiAffinity"])
            if d.get("podAntiAffinity")
            else None,
        )


@dataclass(frozen=True)
class ContainerPort:
    host_port: int = 0
    container_port: int = 0
    protocol: str = "TCP"
    host_ip: str = ""

    @staticmethod
    def from_dict(d: dict) -> "ContainerPort":
        return ContainerPort(
            host_port=int(d.get("hostPort", 0)),
            container_port=int(d.get("containerPort", 0)),
            protocol=d.get("protocol", "TCP"),
            host_ip=d.get("hostIP", ""),
        )


@dataclass
class Container:
    name: str = ""
    image: str = ""
    requests: Dict[str, Quantity] = field(default_factory=dict)
    limits: Dict[str, Quantity] = field(default_factory=dict)
    ports: Tuple[ContainerPort, ...] = ()

    @staticmethod
    def from_dict(d: dict) -> "Container":
        res = d.get("resources") or {}
        return Container(
            name=d.get("name", ""),
            image=d.get("image", ""),
            requests={
                k: parse_quantity(v) for k, v in (res.get("requests") or {}).items()
            },
            limits={
                k: parse_quantity(v) for k, v in (res.get("limits") or {}).items()
            },
            ports=tuple(ContainerPort.from_dict(p) for p in d.get("ports") or ()),
        )


@dataclass
class PodSpec:
    node_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: Tuple[Toleration, ...] = ()
    containers: Tuple[Container, ...] = ()
    init_containers: Tuple[Container, ...] = ()
    priority: int = 0
    scheduler_name: str = "default-scheduler"
    volumes: Tuple[dict, ...] = ()  # raw volume dicts (gcePersistentDisk, ...)
    service_account_name: str = ""  # injected by ServiceAccount admission

    @staticmethod
    def from_dict(d: Optional[dict]) -> "PodSpec":
        d = d or {}
        return PodSpec(
            node_name=d.get("nodeName", ""),
            node_selector=dict(d.get("nodeSelector") or {}),
            affinity=Affinity.from_dict(d.get("affinity")),
            tolerations=tuple(
                Toleration.from_dict(t) for t in d.get("tolerations") or ()
            ),
            containers=tuple(Container.from_dict(c) for c in d.get("containers") or ()),
            init_containers=tuple(
                Container.from_dict(c) for c in d.get("initContainers") or ()
            ),
            priority=int(d.get("priority") or 0),
            scheduler_name=d.get("schedulerName", "default-scheduler"),
            volumes=tuple(d.get("volumes") or ()),
            service_account_name=d.get("serviceAccountName", ""),
        )


@dataclass
class PodStatus:
    phase: str = "Pending"
    # epoch seconds the pod started running; 0 = unknown (ref v1.PodStatus
    # .StartTime, consumed by pickOneNodeForPreemption criterion 5 via
    # util.GetEarliestPodStartTime)
    start_time: float = 0.0
    # node name this pod preempted victims on and expects to land on
    # (ref v1.PodStatus.NominatedNodeName, scheduler.go:310-312)
    nominated_node_name: str = ""
    # aggregate readiness (the Ready condition; endpoints only route to
    # ready pods — pkg/controller/endpoint includes a pod iff
    # podutil.IsPodReady)
    ready: bool = True
    # total container restarts (statusManager; incremented by the kubelet
    # when a liveness probe fails and the container is recreated)
    restart_count: int = 0
    # terminal-phase attribution (ref v1.PodStatus.Reason/Message, e.g.
    # UnexpectedAdmissionError when kubelet admission rejects the pod)
    reason: str = ""
    message: str = ""


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    @property
    def labels(self) -> Dict[str, str]:
        return self.metadata.labels

    def resource_request(self) -> Dict[str, Quantity]:
        """Effective request: max(sum(containers), max(initContainers)) per
        resource — ref pkg/scheduler/nodeinfo/util.go / predicates
        GetResourceRequest (predicates.go:744-762)."""
        total: Dict[str, Quantity] = {}
        for c in self.spec.containers:
            for k, q in c.requests.items():
                total[k] = total.get(k, Quantity(0)) + q  # type: ignore[arg-type]
        for c in self.spec.init_containers:
            for k, q in c.requests.items():
                if k not in total or total[k] < q:
                    total[k] = q
        return total

    def host_ports(self) -> List[ContainerPort]:
        return [
            p for c in self.spec.containers for p in c.ports if p.host_port > 0
        ]

    @staticmethod
    def from_dict(d: dict) -> "Pod":
        st = d.get("status") or {}
        return Pod(
            metadata=ObjectMeta.from_dict(d.get("metadata")),
            spec=PodSpec.from_dict(d.get("spec")),
            status=PodStatus(
                phase=st.get("phase", "Pending"),
                start_time=parse_time(st.get("startTime")) or 0.0,
                nominated_node_name=st.get("nominatedNodeName", ""),
                ready=not any(
                    c.get("type") == "Ready" and c.get("status") == "False"
                    for c in st.get("conditions") or []
                ),
                restart_count=sum(
                    int(cs.get("restartCount", 0))
                    for cs in st.get("containerStatuses") or []
                ),
                reason=st.get("reason", ""),
                message=st.get("message", ""),
            ),
        )


@dataclass
class PodDisruptionBudget:
    """The preemption-relevant slice of policy/v1beta1 PodDisruptionBudget
    (ref staging/src/k8s.io/api/policy/v1beta1/types.go): a label selector
    over pods plus the controller-maintained disruptions-allowed count.
    Preemption groups victims by whether evicting them would violate a PDB
    (generic_scheduler.go filterPodsWithPDBViolation: a pod is violating if
    ANY matching PDB has PodDisruptionsAllowed <= 0)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Optional[dict] = None  # raw metav1.LabelSelector dict
    disruptions_allowed: int = 0     # status.disruptionsAllowed
    # spec.minAvailable / spec.maxUnavailable: int or percent string
    # ("50%"); at most one set (validation).  The disruption controller
    # derives disruptions_allowed from these.
    min_available: object = None
    max_unavailable: object = None

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def matches(self, pod: "Pod") -> bool:
        if pod.namespace != self.metadata.namespace or self.selector is None:
            return False
        for k, v in (self.selector.get("matchLabels") or {}).items():
            if pod.labels.get(k) != v:
                return False
        for e in self.selector.get("matchExpressions") or ():
            op, key, vals = e.get("operator"), e.get("key"), e.get("values") or ()
            has = key in pod.labels
            if op == "In" and not (has and pod.labels[key] in vals):
                return False
            if op == "NotIn" and has and pod.labels[key] in vals:
                return False
            if op == "Exists" and not has:
                return False
            if op == "DoesNotExist" and has:
                return False
        return True

    @staticmethod
    def from_dict(d: dict) -> "PodDisruptionBudget":
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        return PodDisruptionBudget(
            metadata=ObjectMeta.from_dict(d.get("metadata")),
            selector=spec.get("selector"),
            disruptions_allowed=int(
                status.get("disruptionsAllowed", status.get("PodDisruptionsAllowed", 0))
            ),
            min_available=spec.get("minAvailable"),
            max_unavailable=spec.get("maxUnavailable"),
        )


@dataclass(frozen=True)
class ContainerImage:
    names: Tuple[str, ...] = ()
    size_bytes: int = 0

    @staticmethod
    def from_dict(d: dict) -> "ContainerImage":
        return ContainerImage(tuple(d.get("names") or ()), int(d.get("sizeBytes", 0)))


@dataclass
class NodeSpec:
    unschedulable: bool = False
    taints: Tuple[Taint, ...] = ()
    pod_cidr: str = ""   # assigned by the nodeipam controller

    @staticmethod
    def from_dict(d: Optional[dict]) -> "NodeSpec":
        d = d or {}
        return NodeSpec(
            unschedulable=bool(d.get("unschedulable", False)),
            taints=tuple(Taint.from_dict(t) for t in d.get("taints") or ()),
            pod_cidr=d.get("podCIDR", ""),
        )


@dataclass
class NodeStatus:
    allocatable: Dict[str, Quantity] = field(default_factory=dict)
    capacity: Dict[str, Quantity] = field(default_factory=dict)
    images: Tuple[ContainerImage, ...] = ()
    # condition type -> status ("True"/"False"/"Unknown"), e.g. {"Ready": "True"}
    conditions: Dict[str, str] = field(default_factory=dict)
    # PV names attached to this node (status.volumesAttached[].name,
    # maintained by the attach-detach controller)
    volumes_attached: Tuple[str, ...] = ()

    @staticmethod
    def from_dict(d: Optional[dict]) -> "NodeStatus":
        d = d or {}
        # allocatable defaults to capacity when absent (the kubelet computes
        # allocatable = capacity - reserved; a registration that reports
        # only capacity means "nothing reserved" — v1.NodeStatus semantics)
        alloc = d.get("allocatable") or d.get("capacity") or {}
        return NodeStatus(
            allocatable={k: parse_quantity(v) for k, v in alloc.items()},
            capacity={
                k: parse_quantity(v) for k, v in (d.get("capacity") or {}).items()
            },
            images=tuple(ContainerImage.from_dict(i) for i in d.get("images") or ()),
            conditions={
                c["type"]: c["status"] for c in d.get("conditions") or []
            },
            volumes_attached=tuple(
                v.get("name", "") for v in d.get("volumesAttached") or ()
            ),
        )


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def labels(self) -> Dict[str, str]:
        return self.metadata.labels

    @staticmethod
    def from_dict(d: dict) -> "Node":
        return Node(
            metadata=ObjectMeta.from_dict(d.get("metadata")),
            spec=NodeSpec.from_dict(d.get("spec")),
            status=NodeStatus.from_dict(d.get("status")),
        )
