"""Typed API surface for the non-scheduling kinds.

The scheduling-critical kinds (Pod, Node, PV/PVC, workloads) have full
dataclasses in api/types.py, api/storage.py and runtime/controllers.py;
the remaining core kinds were schema-less dicts (VERDICT r3 layer-1
partial).  This module gives each a typed view with from_dict/to_dict
round-trip — the staging/src/k8s.io/api/core/v1 (+ rbac/v1,
coordination/v1, certificates/v1beta1) surface distilled to the fields
this framework's components actually read — plus ``validate(kind,
body)``, the registry-strategy field validation the apiserver runs on
writes (apimachinery validation.go analogs: type errors are 400s, not
silent coercions).

Storage keeps the wire dicts (the controllers/proxies read dicts, like
the reference's unstructured clients can); the typed view is the
contract layer: ``Service.from_dict(raw)`` for typed access,
``validate`` to reject malformed writes at the door.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


class ValidationError(Exception):
    """Malformed object body (HTTP 400 / 422 semantics)."""


def _meta_of(d: dict) -> dict:
    return d.get("metadata") or d


def _name_ns(d: dict) -> Tuple[str, str]:
    m = _meta_of(d)
    return (m.get("name") or d.get("name", ""),
            m.get("namespace") or d.get("namespace", ""))


@dataclass(frozen=True)
class ServicePort:
    name: str = ""
    port: int = 0
    target_port: object = None     # int or named port string
    node_port: int = 0
    protocol: str = "TCP"

    @staticmethod
    def from_dict(d: dict) -> "ServicePort":
        return ServicePort(
            name=d.get("name", ""), port=int(d.get("port", 0)),
            target_port=d.get("targetPort"),
            node_port=int(d.get("nodePort", 0) or 0),
            protocol=d.get("protocol", "TCP"),
        )


@dataclass(frozen=True)
class Service:
    """core/v1 Service (the proxy/endpoints-relevant slice)."""

    name: str
    namespace: str = "default"
    selector: Dict[str, str] = field(default_factory=dict)
    ports: Tuple[ServicePort, ...] = ()
    cluster_ip: str = ""
    type: str = "ClusterIP"

    @staticmethod
    def from_dict(d: dict) -> "Service":
        name, ns = _name_ns(d)
        spec = d.get("spec") or d
        return Service(
            name=name, namespace=ns or "default",
            selector=dict(spec.get("selector") or {}),
            ports=tuple(ServicePort.from_dict(p)
                        for p in spec.get("ports") or ()),
            cluster_ip=spec.get("clusterIP", ""),
            type=spec.get("type", "ClusterIP"),
        )

    def to_dict(self) -> dict:
        return {
            "kind": "Service", "apiVersion": "v1",
            "metadata": {"name": self.name, "namespace": self.namespace},
            "spec": {
                "selector": dict(self.selector),
                "ports": [
                    {"name": p.name, "port": p.port,
                     "targetPort": p.target_port,
                     "nodePort": p.node_port, "protocol": p.protocol}
                    for p in self.ports
                ],
                "clusterIP": self.cluster_ip,
                "type": self.type,
            },
        }


@dataclass(frozen=True)
class EndpointAddress:
    ip: str = ""
    node_name: str = ""
    target_pod: str = ""           # targetRef name when kind == Pod


@dataclass(frozen=True)
class Endpoints:
    """core/v1 Endpoints (subsets flattened: ready addresses x ports)."""

    name: str
    namespace: str = "default"
    addresses: Tuple[EndpointAddress, ...] = ()
    ports: Tuple[ServicePort, ...] = ()

    @staticmethod
    def from_dict(d: dict) -> "Endpoints":
        name, ns = _name_ns(d)
        addrs: List[EndpointAddress] = []
        ports: List[ServicePort] = []
        for sub in d.get("subsets") or ():
            for a in sub.get("addresses") or ():
                ref = a.get("targetRef") or {}
                addrs.append(EndpointAddress(
                    ip=a.get("ip", ""), node_name=a.get("nodeName", ""),
                    target_pod=(ref.get("name", "")
                                if ref.get("kind") == "Pod" else ""),
                ))
            ports += [ServicePort.from_dict(p)
                      for p in sub.get("ports") or ()]
        return Endpoints(name=name, namespace=ns or "default",
                         addresses=tuple(addrs), ports=tuple(ports))


@dataclass(frozen=True)
class Secret:
    name: str
    namespace: str = "default"
    type: str = "Opaque"
    data: Dict[str, object] = field(default_factory=dict)

    @staticmethod
    def from_dict(d: dict) -> "Secret":
        name, ns = _name_ns(d)
        data = {**(d.get("data") or {}), **(d.get("stringData") or {})}
        return Secret(name=name, namespace=ns or "default",
                      type=d.get("type", "Opaque"), data=data)


@dataclass(frozen=True)
class ConfigMap:
    name: str
    namespace: str = "default"
    data: Dict[str, str] = field(default_factory=dict)

    @staticmethod
    def from_dict(d: dict) -> "ConfigMap":
        name, ns = _name_ns(d)
        return ConfigMap(name=name, namespace=ns or "default",
                         data=dict(d.get("data") or {}))


@dataclass(frozen=True)
class Namespace:
    name: str
    phase: str = "Active"
    labels: Dict[str, str] = field(default_factory=dict)

    @staticmethod
    def from_dict(d: dict) -> "Namespace":
        name, _ = _name_ns(d)
        return Namespace(
            name=name,
            phase=(d.get("status") or {}).get("phase", "Active"),
            labels=dict(d.get("labels")
                        or _meta_of(d).get("labels") or {}),
        )


@dataclass(frozen=True)
class ServiceAccount:
    name: str
    namespace: str = "default"
    secrets: Tuple[str, ...] = ()

    @staticmethod
    def from_dict(d: dict) -> "ServiceAccount":
        name, ns = _name_ns(d)
        return ServiceAccount(
            name=name, namespace=ns or "default",
            secrets=tuple(s.get("name", "") if isinstance(s, dict) else s
                          for s in d.get("secrets") or ()),
        )


@dataclass(frozen=True)
class PolicyRule:
    verbs: Tuple[str, ...] = ()
    resources: Tuple[str, ...] = ()
    resource_names: Tuple[str, ...] = ()

    @staticmethod
    def from_dict(d: dict) -> "PolicyRule":
        return PolicyRule(
            verbs=tuple(d.get("verbs") or ()),
            resources=tuple(d.get("resources") or ()),
            resource_names=tuple(d.get("resourceNames") or ()),
        )


@dataclass(frozen=True)
class Role:
    """rbac/v1 Role / ClusterRole (namespace empty = cluster-scoped)."""

    name: str
    namespace: str = ""
    rules: Tuple[PolicyRule, ...] = ()

    @staticmethod
    def from_dict(d: dict) -> "Role":
        name, ns = _name_ns(d)
        return Role(name=name, namespace=ns,
                    rules=tuple(PolicyRule.from_dict(r)
                                for r in d.get("rules") or ()))


@dataclass(frozen=True)
class Subject:
    kind: str = ""
    name: str = ""
    namespace: str = ""


@dataclass(frozen=True)
class RoleBinding:
    """rbac/v1 RoleBinding / ClusterRoleBinding."""

    name: str
    namespace: str = ""
    role_kind: str = ""
    role_name: str = ""
    subjects: Tuple[Subject, ...] = ()

    @staticmethod
    def from_dict(d: dict) -> "RoleBinding":
        name, ns = _name_ns(d)
        ref = d.get("roleRef") or {}
        return RoleBinding(
            name=name, namespace=ns,
            role_kind=ref.get("kind", ""), role_name=ref.get("name", ""),
            subjects=tuple(
                Subject(s.get("kind", ""), s.get("name", ""),
                        s.get("namespace", ""))
                for s in d.get("subjects") or ()
            ),
        )


@dataclass(frozen=True)
class Lease:
    """coordination/v1 Lease (node heartbeats + leader election)."""

    name: str
    namespace: str = ""
    holder: str = ""
    renew_time: Optional[float] = None
    lease_duration_seconds: int = 0

    @staticmethod
    def from_dict(d: dict) -> "Lease":
        name, ns = _name_ns(d)
        spec = d.get("spec") or d
        return Lease(
            name=name, namespace=ns,
            holder=spec.get("holderIdentity", ""),
            renew_time=spec.get("renewTime"),
            lease_duration_seconds=int(
                spec.get("leaseDurationSeconds", 0) or 0),
        )


@dataclass(frozen=True)
class CertificateSigningRequest:
    """certificates.k8s.io/v1beta1 CSR."""

    name: str
    username: str = ""
    signer_name: str = ""
    request: str = ""              # PEM CSR (PKI mode)
    requestor: str = ""
    conditions: Tuple[str, ...] = ()
    certificate: str = ""

    @staticmethod
    def from_dict(d: dict) -> "CertificateSigningRequest":
        name, _ = _name_ns(d)
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        return CertificateSigningRequest(
            name=name,
            username=spec.get("username", ""),
            signer_name=spec.get("signerName", ""),
            request=spec.get("request", ""),
            requestor=spec.get("requestorUsername", ""),
            conditions=tuple(c.get("type", "")
                             for c in status.get("conditions") or ()),
            certificate=status.get("certificate", ""),
        )


# --------------------------------------------------------- validation

# kind -> ((path, type, required), ...); paths are dotted, lists use [].
# The checks mirror the per-kind strategy Validate steps the reference
# runs before storage (apimachinery + pkg/apis/*/validation) for the
# fields this framework consumes — present-but-mistyped is a 400.
_FIELD_SPECS: Dict[str, tuple] = {
    "services": (
        ("spec.selector", dict, False),
        ("spec.ports", list, False),
        ("spec.type", str, False),
    ),
    "endpoints": (("subsets", list, False),),
    "secrets": (("type", str, False), ("data", dict, False),
                ("stringData", dict, False)),
    "configmaps": (("data", dict, False),),
    "serviceaccounts": (("secrets", list, False),),
    "namespaces": (("status.phase", str, False),),
    "roles": (("rules", list, False),),
    "clusterroles": (("rules", list, False),
                     ("aggregationRule", dict, False)),
    "rolebindings": (("subjects", list, False), ("roleRef", dict, False)),
    "clusterrolebindings": (("subjects", list, False),
                            ("roleRef", dict, False)),
    "leases": (("spec.holderIdentity", str, False),
               ("spec.leaseDurationSeconds", (int, float), False)),
    "certificatesigningrequests": (
        ("spec.username", str, False),
        ("spec.signerName", str, False),
        ("spec.request", str, False),
    ),
    "resourcequotas": (("spec.hard", dict, False),),
    "limitranges": (("spec.limits", list, False),),
    "priorityclasses": (("value", (int, float), False),),
    "mutatingwebhookconfigurations": (("webhooks", list, False),),
    "validatingwebhookconfigurations": (("webhooks", list, False),),
}

TYPED_VIEWS = {
    "services": Service,
    "endpoints": Endpoints,
    "secrets": Secret,
    "configmaps": ConfigMap,
    "namespaces": Namespace,
    "serviceaccounts": ServiceAccount,
    "roles": Role,
    "clusterroles": Role,
    "rolebindings": RoleBinding,
    "clusterrolebindings": RoleBinding,
    "leases": Lease,
    "certificatesigningrequests": CertificateSigningRequest,
}


def _walk(d: dict, path: str):
    cur: object = d
    for part in path.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


def validate(kind: str, body: dict) -> None:
    """Reject present-but-mistyped fields for the typed kinds; unknown
    kinds and absent fields pass (the permissive half of strategy
    validation — required-ness stays with each consumer)."""
    spec = _FIELD_SPECS.get(kind)
    if spec is None or not isinstance(body, dict):
        return
    for path, typ, required in spec:
        val = _walk(body, path)
        if val is None:
            if required:
                raise ValidationError(f"{kind}: missing {path}")
            continue
        if not isinstance(val, typ):
            want = (typ.__name__ if isinstance(typ, type)
                    else "/".join(t.__name__ for t in typ))
            raise ValidationError(
                f"{kind}: {path} must be {want}, "
                f"got {type(val).__name__}")


def typed(kind: str, body: dict):
    """The typed view of a stored wire dict, or the dict itself for
    kinds without one."""
    cls = TYPED_VIEWS.get(kind)
    return cls.from_dict(body) if cls is not None else body
