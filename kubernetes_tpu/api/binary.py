"""Binary wire serializer + content negotiation types.

Reference: staging/src/k8s.io/apimachinery/pkg/runtime/serializer/
protobuf/protobuf.go (455 LoC) — the apiserver negotiates
``application/vnd.kubernetes.protobuf`` for high-QPS clients; every
protobuf payload is wrapped in an envelope starting with the 4-byte
magic ``k8s\\x00`` (protobuf.go:42-46) followed by the serialized
object, and LIST/WATCH on the hot paths move ~3-5x fewer bytes than
JSON.

This framework's objects serialize through schema-shaped wire dicts
(api/serialize.py), so its binary format is a compact self-describing
encoding of those dicts rather than generated proto classes:

  * the same ``k8s\\x00`` envelope magic;
  * LEB128 varints for lengths/ints (zigzag for signed);
  * one type tag per value (null/bool/int/float/str/bytes/list/dict);
  * a per-message string table: the FIRST occurrence of any string is
    emitted inline and appended to the table, every repeat is a varint
    back-reference — which is where the wire savings come from, since
    LIST payloads repeat keys ("metadata", "resources", "cpu") and
    values (image names, label keys) hundreds of times.

The negotiation contract (server.py): requests opt in via
``Accept: application/vnd.kubernetes.binary`` for responses and
``Content-Type: application/vnd.kubernetes.binary`` for bodies; the
watch stream switches to length-prefixed binary frames (4-byte
big-endian length, zero = heartbeat).
"""

from __future__ import annotations

import struct
from typing import List, Tuple

MAGIC = b"k8s\x00"  # protobuf.go:42 — the same envelope prefix
BINARY_MEDIA_TYPE = "application/vnd.kubernetes.binary"

_T_NULL = 0
_T_TRUE = 1
_T_FALSE = 2
_T_INT = 3      # zigzag varint
_T_FLOAT = 4    # IEEE754 double, 8 bytes big-endian
_T_STR = 5      # varint byte-length + utf8, appended to the string table
_T_REF = 6      # varint index into the string table
_T_LIST = 7     # varint count + values
_T_DICT = 8     # varint count + (key value)*  (keys are _T_STR/_T_REF)
_T_BYTES = 9    # varint byte-length + raw


def _write_varint(out: bytearray, v: int) -> None:
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    shift = 0
    out = 0
    while True:
        b = data[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _zigzag(v: int) -> int:
    return (v << 1) if v >= 0 else ((-v) << 1) - 1


def _unzigzag(v: int) -> int:
    return (v >> 1) if not v & 1 else -((v + 1) >> 1)


def _write_str(out: bytearray, s: str, table: dict) -> None:
    idx = table.get(s)
    if idx is not None:
        out.append(_T_REF)
        _write_varint(out, idx)
        return
    table[s] = len(table)
    raw = s.encode("utf-8")
    out.append(_T_STR)
    _write_varint(out, len(raw))
    out += raw


def _write_value(out: bytearray, v, table: dict) -> None:
    if v is None:
        out.append(_T_NULL)
    elif v is True:
        out.append(_T_TRUE)
    elif v is False:
        out.append(_T_FALSE)
    elif isinstance(v, int):
        out.append(_T_INT)
        _write_varint(out, _zigzag(v))
    elif isinstance(v, float):
        out.append(_T_FLOAT)
        out += struct.pack(">d", v)
    elif isinstance(v, str):
        _write_str(out, v, table)
    elif isinstance(v, bytes):
        out.append(_T_BYTES)
        _write_varint(out, len(v))
        out += v
    elif isinstance(v, dict):
        out.append(_T_DICT)
        _write_varint(out, len(v))
        for k, val in v.items():
            _write_str(out, str(k), table)
            _write_value(out, val, table)
    elif isinstance(v, (list, tuple)):
        out.append(_T_LIST)
        _write_varint(out, len(v))
        for item in v:
            _write_value(out, item, table)
    else:
        # quantities and other stringifiable scalars ride as strings,
        # matching what the JSON path emits for them
        _write_str(out, str(v), table)


def _read_value(data: bytes, pos: int, table: List[str]):
    tag = data[pos]
    pos += 1
    if tag == _T_NULL:
        return None, pos
    if tag == _T_TRUE:
        return True, pos
    if tag == _T_FALSE:
        return False, pos
    if tag == _T_INT:
        v, pos = _read_varint(data, pos)
        return _unzigzag(v), pos
    if tag == _T_FLOAT:
        return struct.unpack(">d", data[pos:pos + 8])[0], pos + 8
    if tag == _T_STR:
        n, pos = _read_varint(data, pos)
        s = data[pos:pos + n].decode("utf-8")
        table.append(s)
        return s, pos + n
    if tag == _T_REF:
        i, pos = _read_varint(data, pos)
        return table[i], pos
    if tag == _T_BYTES:
        n, pos = _read_varint(data, pos)
        return bytes(data[pos:pos + n]), pos + n
    if tag == _T_LIST:
        n, pos = _read_varint(data, pos)
        out = []
        for _ in range(n):
            v, pos = _read_value(data, pos, table)
            out.append(v)
        return out, pos
    if tag == _T_DICT:
        n, pos = _read_varint(data, pos)
        d = {}
        for _ in range(n):
            k, pos = _read_value(data, pos, table)
            v, pos = _read_value(data, pos, table)
            d[k] = v
        return d, pos
    raise ValueError(f"bad tag {tag} at {pos - 1}")


def dumps(obj) -> bytes:
    """Wire dict -> enveloped binary payload."""
    out = bytearray(MAGIC)
    _write_value(out, obj, {})
    return bytes(out)


def loads(data: bytes):
    """Enveloped binary payload -> wire dict.  EVERY malformed input
    raises ValueError (like json.loads), so request handlers' 400 paths
    catch truncation (IndexError), short floats (struct.error),
    unhashable keys (TypeError), and hostile nesting (RecursionError)
    uniformly instead of crashing."""
    if data[:4] != MAGIC:
        raise ValueError("not a k8s binary payload (bad magic)")
    try:
        v, pos = _read_value(data, 4, [])
    except ValueError:
        raise
    except (IndexError, struct.error, TypeError, RecursionError) as e:
        raise ValueError(f"malformed binary payload: {type(e).__name__}")
    if pos != len(data):
        raise ValueError(f"trailing garbage: {len(data) - pos} bytes")
    return v


def frame(payload: bytes) -> bytes:
    """Watch-stream framing: 4-byte big-endian length + payload."""
    return struct.pack(">I", len(payload)) + payload


HEARTBEAT_FRAME = struct.pack(">I", 0)


def read_frames(stream, heartbeats: bool = False):
    """Yield payloads from a framed binary watch stream (file-like);
    EOF ends iteration.  Zero-length frames are heartbeats: skipped by
    default, yielded as None with heartbeats=True (so callers can run
    liveness/stop checks on idle streams)."""
    while True:
        hdr = stream.read(4)
        if len(hdr) < 4:
            return
        n = struct.unpack(">I", hdr)[0]
        if n == 0:
            if heartbeats:
                yield None
            continue
        payload = b""
        while len(payload) < n:
            chunk = stream.read(n - len(payload))
            if not chunk:
                return  # truncated stream: treat as disconnect
            payload += chunk
        yield payload
