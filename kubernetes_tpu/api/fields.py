"""Field selectors: server-side LIST filtering on object fields.

Reference: staging/src/k8s.io/apimachinery/pkg/fields — selectors of the
form ``metadata.name=x,spec.nodeName!=y`` parsed by ParseSelector
(selector.go:449-485, operators ``=``/``==``/``!=`` only), evaluated
against the per-kind field set each registry exposes via
GetAttrs/ToSelectableFields (e.g. pods: pkg/registry/core/pod/strategy.go
PodToSelectableFields — metadata.name, metadata.namespace, spec.nodeName,
spec.schedulerName, status.phase...).

Here selectors evaluate against the object's WIRE dict by dotted path,
which covers every field the reference registries expose without a
per-kind table; unknown paths simply compare against "" (the reference's
selectable-field maps default absent fields to the empty string too)."""

from __future__ import annotations

from typing import List, Tuple


class FieldSelector:
    def __init__(self, requirements: List[Tuple[str, str, str]]):
        self.requirements = requirements  # (dotted path, op, value)

    @staticmethod
    def parse(s: str) -> "FieldSelector":
        """ParseSelector: comma-separated terms, ``=``/``==``/``!=``;
        malformed terms raise ValueError (HTTP 400)."""
        reqs: List[Tuple[str, str, str]] = []
        for term in s.split(","):
            term = term.strip()
            if not term:
                continue
            if "!=" in term:
                path, _, value = term.partition("!=")
                op = "!="
            elif "==" in term:
                path, _, value = term.partition("==")
                op = "="
            elif "=" in term:
                path, _, value = term.partition("=")
                op = "="
            else:
                raise ValueError(f"invalid field selector term {term!r}")
            path = path.strip()
            if not path:
                raise ValueError(f"invalid field selector term {term!r}")
            reqs.append((path, op, value.strip()))
        return FieldSelector(reqs)

    @staticmethod
    def _lookup(obj: dict, path: str) -> str:
        cur = obj
        for part in path.split("."):
            if not isinstance(cur, dict):
                return ""
            cur = cur.get(part)
            if cur is None:
                return ""
        return str(cur)

    def matches(self, wire: dict) -> bool:
        for path, op, value in self.requirements:
            have = self._lookup(wire, path)
            if op == "=" and have != value:
                return False
            if op == "!=" and have == value:
                return False
        return True
