"""Object factories: build well-formed Node/Pod objects from the small
set of knobs the scheduler cares about.

The package-level analog of the reference's fixture helpers
(pkg/scheduler/algorithm/predicates/testing_helper.go, test/utils/runners.go
node/pod strategies) — shared by the test suite, bench.py, and the
sustained-density harness.  Memory values are Mi-granular so float32
device math stays exact for score parity.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

from kubernetes_tpu.api.types import Node, Pod

ZONE_KEY = "failure-domain.beta.kubernetes.io/zone"
REGION_KEY = "failure-domain.beta.kubernetes.io/region"
HOSTNAME_KEY = "kubernetes.io/hostname"


def make_node(
    name: str,
    cpu: str = "4",
    mem: str = "8Gi",
    pods: int = 110,
    labels: Optional[Dict[str, str]] = None,
    taints: Sequence[dict] = (),
    unschedulable: bool = False,
    conditions: Sequence[dict] = (),
    images: Sequence[dict] = (),
    annotations: Optional[Dict[str, str]] = None,
    allocatable_extra: Optional[Dict[str, str]] = None,
) -> Node:
    lab = {HOSTNAME_KEY: name}
    lab.update(labels or {})
    return Node.from_dict(
        {
            "metadata": {"name": name, "labels": lab, "annotations": annotations or {}},
            "spec": {"unschedulable": unschedulable, "taints": list(taints)},
            "status": {
                "allocatable": {
                    "cpu": cpu, "memory": mem, "pods": pods,
                    **(allocatable_extra or {}),
                },
                "conditions": list(conditions) or [{"type": "Ready", "status": "True"}],
                "images": list(images),
            },
        }
    )


def make_pod(
    name: str,
    namespace: str = "default",
    cpu: Optional[str] = None,
    mem: Optional[str] = None,
    labels: Optional[Dict[str, str]] = None,
    node_name: str = "",
    node_selector: Optional[Dict[str, str]] = None,
    tolerations: Sequence[dict] = (),
    affinity: Optional[dict] = None,
    ports: Sequence[dict] = (),
    priority: int = 0,
    images: Sequence[str] = (),
    owner: Optional[Tuple[str, str]] = None,  # (kind, uid)
    volumes: Sequence[dict] = (),
    requests: Optional[Dict[str, str]] = None,  # full request dict (extended
                                                # resources, ephemeral-storage…)
    limits: Optional[Dict[str, str]] = None,    # container limits dict
    init_requests: Sequence[Dict[str, str]] = (),  # one init container each
    extra_containers: Sequence[Dict[str, str]] = (),  # request dict each
    annotations: Optional[Dict[str, str]] = None,
) -> Pod:
    req = dict(requests or {})
    if cpu is not None:
        req["cpu"] = cpu
    if mem is not None:
        req["memory"] = mem
    resources: dict = {}
    if req:
        resources["requests"] = req
    if limits:
        resources["limits"] = dict(limits)
    containers = [
        {
            "name": "c0",
            "image": images[0] if images else "",
            "resources": resources,
            "ports": list(ports),
        }
    ]
    for i, img in enumerate(images[1:], 1):
        containers.append({"name": f"c{i}", "image": img})
    for i, r in enumerate(extra_containers):
        containers.append(
            {"name": f"x{i}", "image": "", "resources": {"requests": dict(r)}}
        )
    init_containers = [
        {"name": f"i{i}", "image": "", "resources": {"requests": dict(r)}}
        for i, r in enumerate(init_requests)
    ]
    meta: dict = {"name": name, "namespace": namespace, "labels": labels or {}}
    if annotations:
        meta["annotations"] = dict(annotations)
    if owner:
        meta["ownerReferences"] = [
            {"kind": owner[0], "uid": owner[1], "controller": True}
        ]
    return Pod.from_dict(
        {
            "metadata": meta,
            "spec": {
                "nodeName": node_name,
                "nodeSelector": node_selector or {},
                "tolerations": list(tolerations),
                "affinity": affinity,
                "containers": containers,
                "initContainers": init_containers,
                "priority": priority,
                "volumes": list(volumes),
            },
        }
    )


# ------------------------------------------------------- randomized clusters

