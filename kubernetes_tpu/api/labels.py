"""Label selectors.

Reference: staging/src/k8s.io/apimachinery/pkg/labels (Requirement/Selector)
and staging/src/k8s.io/apimachinery/pkg/apis/meta/v1/types.go (LabelSelector
with MatchLabels + MatchExpressions).  Operators: In, NotIn, Exists,
DoesNotExist, Gt, Lt — the same set node-affinity terms use
(pkg/apis/core/types.go NodeSelectorOperator).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence

IN = "In"
NOT_IN = "NotIn"
EXISTS = "Exists"
DOES_NOT_EXIST = "DoesNotExist"
GT = "Gt"
LT = "Lt"

OPERATORS = (IN, NOT_IN, EXISTS, DOES_NOT_EXIST, GT, LT)


@dataclass(frozen=True)
class Requirement:
    key: str
    operator: str
    values: tuple = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        has = self.key in labels
        if self.operator == IN:
            return has and labels[self.key] in self.values
        if self.operator == NOT_IN:
            # ref labels.Requirement.Matches: NotIn matches when the key is
            # absent OR the value is not in the set.
            return not has or labels[self.key] not in self.values
        if self.operator == EXISTS:
            return has
        if self.operator == DOES_NOT_EXIST:
            return not has
        if self.operator in (GT, LT):
            if not has:
                return False
            try:
                lhs = int(labels[self.key])
                rhs = int(self.values[0])
            except (ValueError, IndexError):
                return False
            return lhs > rhs if self.operator == GT else lhs < rhs
        raise ValueError(f"unknown operator {self.operator!r}")


@dataclass(frozen=True)
class Selector:
    """Conjunction of requirements. Empty selector matches everything;
    a None selector (absent) matches nothing — mirroring
    metav1.LabelSelectorAsSelector semantics."""

    requirements: tuple = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        return all(r.matches(labels) for r in self.requirements)

    @property
    def keys(self) -> List[str]:
        return [r.key for r in self.requirements]


def selector_from_match_labels(match_labels: Mapping[str, str]) -> Selector:
    """A plain map selector (Service.spec.selector, RC.spec.selector)."""
    return Selector(
        tuple(Requirement(k, IN, (v,)) for k, v in sorted(match_labels.items()))
    )


def selector_from_label_selector(ls: Optional[dict]) -> Optional[Selector]:
    """metav1.LabelSelector {matchLabels, matchExpressions} -> Selector.

    Returns None for a None input (matches nothing), and an empty Selector for
    an empty LabelSelector (matches everything) — ref
    apimachinery/pkg/apis/meta/v1/helpers.go LabelSelectorAsSelector.
    """
    if ls is None:
        return None
    reqs: List[Requirement] = []
    for k, v in sorted((ls.get("matchLabels") or {}).items()):
        reqs.append(Requirement(k, IN, (v,)))
    for expr in ls.get("matchExpressions") or []:
        reqs.append(
            Requirement(
                expr["key"], expr["operator"], tuple(expr.get("values") or ())
            )
        )
    return Selector(tuple(reqs))
