"""Label selectors.

Reference: staging/src/k8s.io/apimachinery/pkg/labels (Requirement/Selector)
and staging/src/k8s.io/apimachinery/pkg/apis/meta/v1/types.go (LabelSelector
with MatchLabels + MatchExpressions).  Operators: In, NotIn, Exists,
DoesNotExist, Gt, Lt — the same set node-affinity terms use
(pkg/apis/core/types.go NodeSelectorOperator).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence

IN = "In"
NOT_IN = "NotIn"
EXISTS = "Exists"
DOES_NOT_EXIST = "DoesNotExist"
GT = "Gt"
LT = "Lt"

OPERATORS = (IN, NOT_IN, EXISTS, DOES_NOT_EXIST, GT, LT)


@dataclass(frozen=True)
class Requirement:
    key: str
    operator: str
    values: tuple = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        has = self.key in labels
        if self.operator == IN:
            return has and labels[self.key] in self.values
        if self.operator == NOT_IN:
            # ref labels.Requirement.Matches: NotIn matches when the key is
            # absent OR the value is not in the set.
            return not has or labels[self.key] not in self.values
        if self.operator == EXISTS:
            return has
        if self.operator == DOES_NOT_EXIST:
            return not has
        if self.operator in (GT, LT):
            if not has:
                return False
            try:
                lhs = int(labels[self.key])
                rhs = int(self.values[0])
            except (ValueError, IndexError):
                return False
            return lhs > rhs if self.operator == GT else lhs < rhs
        raise ValueError(f"unknown operator {self.operator!r}")


@dataclass(frozen=True)
class Selector:
    """Conjunction of requirements. Empty selector matches everything;
    a None selector (absent) matches nothing — mirroring
    metav1.LabelSelectorAsSelector semantics."""

    requirements: tuple = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        return all(r.matches(labels) for r in self.requirements)

    @property
    def keys(self) -> List[str]:
        return [r.key for r in self.requirements]


def selector_from_match_labels(match_labels: Mapping[str, str]) -> Selector:
    """A plain map selector (Service.spec.selector, RC.spec.selector)."""
    return Selector(
        tuple(Requirement(k, IN, (v,)) for k, v in sorted(match_labels.items()))
    )


def selector_from_label_selector(ls: Optional[dict]) -> Optional[Selector]:
    """metav1.LabelSelector {matchLabels, matchExpressions} -> Selector.

    Returns None for a None input (matches nothing), and an empty Selector for
    an empty LabelSelector (matches everything) — ref
    apimachinery/pkg/apis/meta/v1/helpers.go LabelSelectorAsSelector.
    """
    if ls is None:
        return None
    reqs: List[Requirement] = []
    for k, v in sorted((ls.get("matchLabels") or {}).items()):
        reqs.append(Requirement(k, IN, (v,)))
    for expr in ls.get("matchExpressions") or []:
        reqs.append(
            Requirement(
                expr["key"], expr["operator"], tuple(expr.get("values") or ())
            )
        )
    return Selector(tuple(reqs))

def parse_selector(s: str) -> Selector:
    """labels.Parse string grammar (apimachinery/pkg/labels/selector.go):
    comma-separated terms ``k=v`` / ``k==v`` / ``k!=v`` / ``k`` (exists)
    / ``!k`` (not exists) / ``k in (a,b)`` / ``k notin (a,b)``.
    Malformed terms raise ValueError (HTTP 400 at the REST layer)."""
    import re

    reqs: List[Requirement] = []
    # split on commas NOT inside parentheses (the in/notin value sets)
    terms = re.split(r",(?![^()]*\))", s)
    for term in terms:
        term = term.strip()
        if not term:
            continue
        m = re.fullmatch(
            r"(?P<key>[^\s!=,()]+)\s+(?P<op>in|notin)\s+"
            r"\((?P<vals>[^)]*)\)", term)
        if m:
            vals = tuple(v.strip() for v in m.group("vals").split(",")
                         if v.strip())
            reqs.append(Requirement(
                m.group("key"), IN if m.group("op") == "in" else NOT_IN,
                vals))
            continue
        if term.startswith("!"):
            reqs.append(Requirement(term[1:].strip(), DOES_NOT_EXIST))
            continue
        if "!=" in term:
            k, _, v = term.partition("!=")
            reqs.append(Requirement(k.strip(), NOT_IN, (v.strip(),)))
            continue
        if "==" in term:
            k, _, v = term.partition("==")
            reqs.append(Requirement(k.strip(), IN, (v.strip(),)))
            continue
        if "=" in term:
            k, _, v = term.partition("=")
            reqs.append(Requirement(k.strip(), IN, (v.strip(),)))
            continue
        if re.fullmatch(r"[^\s!=,()]+", term):
            reqs.append(Requirement(term, EXISTS))
            continue
        raise ValueError(f"invalid label selector term {term!r}")
    return Selector(tuple(reqs))


import re as _re

_LABEL_VALUE_RE = _re.compile(r"(([A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9])?")
_QUAL_NAME_RE = _re.compile(r"([A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9]")
_SUBDOMAIN_RE = _re.compile(
    r"[a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*"
)


def is_valid_label_value(v: str) -> bool:
    """apimachinery validation.IsValidLabelValue: <= 63 chars, empty OK,
    else alphanumeric at the ends, [-_.alnum] in the middle."""
    return len(v) <= 63 and bool(_LABEL_VALUE_RE.fullmatch(v))


def is_valid_label_key(k: str) -> bool:
    """validation.IsQualifiedName: optional dns-1123-subdomain prefix '/',
    then a <=63-char name."""
    parts = k.split("/")
    if len(parts) == 2:
        prefix, name = parts
        if not prefix or len(prefix) > 253 or not _SUBDOMAIN_RE.fullmatch(prefix):
            return False
    elif len(parts) == 1:
        name = parts[0]
    else:
        return False
    return 0 < len(name) <= 63 and bool(_QUAL_NAME_RE.fullmatch(name))


def requirement_is_unbuildable(key: str, op: str, values) -> bool:
    """labels.NewRequirement error cases for NodeSelector matchExpressions —
    any of these makes NodeSelectorRequirementsAsSelector error, so the
    containing TERM never matches (v1helper.MatchNodeSelectorTerms skips
    it).  matchFields are exempt (NodeSelectorRequirementsAsFieldSelector
    does not validate label syntax):
      * invalid label key (any operator)
      * In/NotIn with zero values or any invalid value
      * Exists/DoesNotExist with values
      * Gt/Lt with a value count other than one"""
    values = list(values)
    if not is_valid_label_key(key):
        return True
    if op in (IN, NOT_IN):
        return not values or any(
            not is_valid_label_value(v) for v in values
        )
    if op in (EXISTS, DOES_NOT_EXIST):
        return bool(values)
    if op in (GT, LT):
        return len(values) != 1
    return False
