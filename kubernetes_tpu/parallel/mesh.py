"""Device-mesh sharding of the node axis.

The TPU-native answer to both of the reference's scale mechanisms:
  * the 16-goroutine node scan (generic_scheduler.go:518) -> data parallelism
    over the node axis of every ClusterTensors column;
  * multi-host scale-out (kubemark 5k-node clusters) -> the same sharding over
    a multi-host Mesh, with XLA inserting ICI/DCN collectives.

Filter/Score is embarrassingly parallel over nodes; only host selection
(argmax) and score normalization (max/min over nodes) reduce across shards —
XLA lowers those to all-reduce over ICI when the inputs carry a NamedSharding.
No hand-written collectives: pick a mesh, annotate shardings, let XLA insert
them (the scaling-book recipe).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubernetes_tpu.codec.schema import ClusterTensors

NODE_AXIS = "nodes"


def make_mesh(n_devices: Optional[int] = None, axis: str = NODE_AXIS) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"mesh ({axis},) needs {n_devices} devices, have {len(devs)}"
            )
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def build_mesh(n_devices: Optional[int] = None,
               shape: Optional[str] = None):
    """The live Scheduler's mesh constructor (config knobs shardDevices /
    meshShape) -> (Mesh, spec_axis) where spec_axis is what the node
    dimension splits over — the axis name for a 1D mesh, the flattened
    ("dcn", "ici") tuple for a two-level one.

    shape=None/"" builds the 1D node mesh over n_devices; "OxI" (e.g.
    "2x4") builds the two-level dcn x ici mesh (outer hosts x inner chips
    — make_mesh_multihost) whose total must match n_devices when both are
    given.  The total device count must be a power of two: the encoder
    pads the node axis to a pow2 width, and an uneven split cannot shard
    it."""
    if shape:
        dims = _parse_shape(shape)
        if len(dims) == 1:
            if n_devices and n_devices != dims[0]:
                raise ValueError(
                    f"shardDevices={n_devices} != meshShape {shape!r} "
                    f"total {dims[0]}"
                )
            n_devices = dims[0]
        elif len(dims) == 2:
            outer, inner = dims
            total = outer * inner
            if n_devices and n_devices != total:
                raise ValueError(
                    f"shardDevices={n_devices} != meshShape {shape!r} "
                    f"total {total}"
                )
            validate_device_count(total)
            return make_mesh_multihost(outer, inner), (DCN_AXIS, ICI_AXIS)
    if not n_devices:
        raise ValueError("sharding requested without a device count "
                         "(set shardDevices or meshShape)")
    validate_device_count(n_devices)
    return make_mesh(n_devices), NODE_AXIS


def _parse_shape(shape) -> list:
    try:
        dims = [int(p) for p in str(shape).lower().split("x")]
    except ValueError:
        raise ValueError(
            f"meshShape {shape!r} is not 'N' or 'OxI' (e.g. '8', '2x4')"
        )
    if len(dims) > 2:
        raise ValueError(f"meshShape {shape!r} has too many dimensions")
    if any(d < 1 for d in dims):
        # a negative pair like "-2x-4" multiplies to a plausible total,
        # so it would sail through the mesh_total/validate_device_count
        # preflights and die much later in np.reshape
        raise ValueError(f"meshShape {shape!r} has non-positive dimensions")
    return dims


def validate_device_count(n: int) -> None:
    """Reject device counts the sharded control plane cannot serve:
    non-pow2 (snapshot axes pad to pow2 widths) or > 512 (the node
    arena growth schedule).  Public so bench/cmd preflights can fail
    fast before provisioning devices or draining a bench leg."""
    if n < 1 or n & (n - 1):
        raise ValueError(
            f"mesh device count must be a power of two (snapshot node "
            f"axes pad to pow2 widths), got {n}"
        )
    if n > 512:
        # the encoder's node arena doubles (pow2) up to 2048 rows, then
        # grows in 512-multiples — every reachable width divides over a
        # pow2 mesh of <= 512 devices, but a larger mesh can hit a
        # non-divisible arena (e.g. 2560 % 1024) mid-run
        raise ValueError(
            f"mesh device count must be <= 512 (node arenas grow in "
            f"512-row multiples above 2048), got {n}"
        )


def mesh_device_ids(mesh: Optional[Mesh]) -> "frozenset[int]":
    """The jax device ids a mesh spans (flat order) — the vocabulary the
    fault-attribution seams (codec/faults.py device_index) and the
    per-shard breaker bank (runtime/health.ShardHealth) share."""
    if mesh is None:
        return frozenset()
    return frozenset(
        int(getattr(d, "id", -1))
        for d in np.asarray(mesh.devices).ravel()
    )


def rebuild_without(full_mesh: Mesh, lost_ids) -> Tuple[Optional[Mesh], Optional[object]]:
    """The elastic-ladder shrink/rebuild constructor: the WIDEST valid
    sub-mesh of `full_mesh`'s surviving devices -> (mesh, spec_axis), or
    (None, None) when nothing survives (the caller falls back to the
    default single-chip path).

    `lost_ids` are jax device ids (mesh_device_ids vocabulary).  The
    result is always a 1D node mesh: survivors of a two-level dcn x ici
    mesh no longer sit on clean DCN boundaries, so the hierarchical
    layout cannot be preserved — a flat mesh keeps placements
    bit-identical (sharding is layout, not semantics) at the cost of
    flat cross-shard reductions until the full mesh restores.  The width
    is the largest power of two <= the survivor count (snapshot axes pad
    to pow2, so only pow2 meshes divide them); it is <= the startup
    width, so the 512-device cap and the arena-divisibility contract
    (validate_device_count) hold by construction, and survivors keep
    their flat-order position so repeated shrinks are deterministic."""
    lost = {int(d) for d in lost_ids}
    survivors = [
        d for d in np.asarray(full_mesh.devices).ravel().tolist()
        if int(getattr(d, "id", -1)) not in lost
    ]
    width = 1
    while width * 2 <= len(survivors):
        width *= 2
    if not survivors:
        return None, None
    return Mesh(np.array(survivors[:width]), (NODE_AXIS,)), NODE_AXIS


def mesh_total(shape: Optional[str], n_devices: int = 0) -> int:
    """Total device count a (shardDevices, meshShape) pair asks for —
    shared by bench/cmd preflight checks (virtual-device provisioning
    must happen before the backend initializes)."""
    if shape:
        total = 1
        for p in _parse_shape(shape):
            total *= p
        return total
    return int(n_devices)


def _mesh_2level(outer: int, inner: int, axes) -> Mesh:
    devs = jax.devices()
    if len(devs) < outer * inner:
        raise ValueError(
            f"mesh {axes} needs {outer}x{inner} devices, have {len(devs)}")
    return Mesh(np.array(devs[: outer * inner]).reshape(outer, inner), axes)


def node_axis_spec(name: str, arr, n_nodes: int, spec_axis=NODE_AXIS) -> P:
    """THE field-classification rule, shared by shard_cluster and
    DeviceSnapshotCache: node-axis columns (leading dim == the padded
    node width) split over spec_axis; everything else — including the
    cluster-wide pair_topo_key [TP], whatever its length — replicates."""
    arr = np.asarray(arr)
    if name != "pair_topo_key" and arr.ndim >= 1 and arr.shape[0] == n_nodes:
        return P(spec_axis, *([None] * (arr.ndim - 1)))
    return P(*([None] * arr.ndim))


def shard_cluster(cluster: ClusterTensors, mesh: Mesh,
                  spec_axis=NODE_AXIS) -> ClusterTensors:
    """Place every node-axis column sharded over the mesh; small cluster-wide
    vectors (pair_topo_key [TP]) replicated.  spec_axis names the mesh
    axis (or axis tuple, e.g. ("dcn", "ici")) the node dimension splits
    over — ONE classification heuristic (node_axis_spec) for every layout."""
    n = cluster.n_nodes
    out = {}
    for f in dataclasses.fields(cluster):
        arr = np.asarray(getattr(cluster, f.name))
        spec = node_axis_spec(f.name, arr, n, spec_axis)
        out[f.name] = jax.device_put(arr, NamedSharding(mesh, spec))
    return ClusterTensors(**out)


def replicated_on_cluster_mesh(cluster):
    """Fully-replicated NamedSharding over the mesh a sharded cluster
    lives on (None = the cluster is single-device/host — use the default
    placement).  The seam both engines' host entries use to keep batch
    uploads on the SAME device set as the snapshot (multi-chip live
    path, runtime/scheduler.py shardDevices)."""
    sh = getattr(getattr(cluster, "allocatable", None), "sharding", None)
    if isinstance(sh, NamedSharding) and sh.mesh.size > 1:
        return NamedSharding(sh.mesh, P())
    return None


def replicate(tree, mesh: Mesh):
    """Replicate a pytree (PodBatch, port state, scalars) across the mesh."""
    from kubernetes_tpu.codec.transfer import note_transfer_tree

    note_transfer_tree("h2d", "batch_replicate", tree)

    def put(x):
        arr = np.asarray(x)
        return jax.device_put(arr, NamedSharding(mesh, P(*([None] * arr.ndim))))

    return jax.tree_util.tree_map(put, tree)


POD_AXIS = "pods"


def make_mesh_2d(pod_devices: int, node_devices: int) -> Mesh:
    """2D (pods x nodes) mesh: the [B, N] filter/score grid shards BOTH
    ways — the batch axis across one mesh dimension, every node-axis
    column across the other.  The speculative engine's commit matmuls
    ([B, B] incidence against per-node state) become XLA collectives
    across the pod axis automatically; placements stay bit-identical to
    the unsharded program (tests/test_mesh.py).  This is the layout that
    scales BOTH a 100k-pod backlog and a 50k-node fleet past one chip's
    HBM."""
    return _mesh_2level(pod_devices, node_devices, (POD_AXIS, NODE_AXIS))


def shard_pods(tree, mesh: Mesh, n_pods: int):
    """Shard every batch-axis leaf (leading dim == the padded pod count)
    over the mesh's pod axis; everything else replicates.  Use with
    make_mesh_2d for 2D layouts (a 1D node mesh replicates pods via
    `replicate`)."""

    def put(x):
        arr = np.asarray(x)
        if arr.ndim >= 1 and arr.shape[0] == n_pods:
            spec = P(POD_AXIS, *([None] * (arr.ndim - 1)))
        else:
            spec = P(*([None] * arr.ndim))
        return jax.device_put(arr, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, tree)


DCN_AXIS = "dcn"
ICI_AXIS = "ici"


def make_mesh_multihost(n_hosts: int, chips_per_host: int) -> Mesh:
    """Two-level (dcn x ici) mesh for multi-host scale-out: the outer axis
    spans hosts (DCN links), the inner axis the chips within each host
    (ICI links).  The node axis shards over BOTH axes flattened —
    `P(("dcn", "ici"))` — so each host owns a contiguous node block and
    each chip a sub-block.  XLA then lowers cross-shard reductions
    (argmax/min/max in host selection and score normalization)
    hierarchically: intra-host partials ride ICI, only the per-host
    partial crosses DCN — the scaling-book recipe for multi-host meshes,
    with no hand-written collectives.  On real hardware the device order
    from jax.devices() already groups chips by host (process index), so
    the reshape below maps the outer axis onto DCN boundaries; under the
    virtual CPU mesh the layout is exercised structurally and validated
    by placement identity (tests/test_mesh.py).

    This is the multi-host analog of the reference's kubemark scale-out:
    a 50k-node fleet splits across hosts at the DCN level while each
    host's chips scan their node block in parallel (SURVEY §2.4 last
    row, previously deferred)."""
    return _mesh_2level(n_hosts, chips_per_host, (DCN_AXIS, ICI_AXIS))


def shard_cluster_multihost(cluster: ClusterTensors, mesh: Mesh) -> ClusterTensors:
    """shard_cluster over the flattened (dcn, ici) axes: node columns
    split across every chip on every host; cluster-wide vectors
    replicate."""
    return shard_cluster(cluster, mesh, spec_axis=(DCN_AXIS, ICI_AXIS))
