"""Device-mesh sharding of the node axis.

The TPU-native answer to both of the reference's scale mechanisms:
  * the 16-goroutine node scan (generic_scheduler.go:518) -> data parallelism
    over the node axis of every ClusterTensors column;
  * multi-host scale-out (kubemark 5k-node clusters) -> the same sharding over
    a multi-host Mesh, with XLA inserting ICI/DCN collectives.

Filter/Score is embarrassingly parallel over nodes; only host selection
(argmax) and score normalization (max/min over nodes) reduce across shards —
XLA lowers those to all-reduce over ICI when the inputs carry a NamedSharding.
No hand-written collectives: pick a mesh, annotate shardings, let XLA insert
them (the scaling-book recipe).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubernetes_tpu.codec.schema import ClusterTensors

NODE_AXIS = "nodes"


def make_mesh(n_devices: Optional[int] = None, axis: str = NODE_AXIS) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def _mesh_2level(outer: int, inner: int, axes) -> Mesh:
    devs = jax.devices()
    if len(devs) < outer * inner:
        raise ValueError(
            f"mesh {axes} needs {outer}x{inner} devices, have {len(devs)}")
    return Mesh(np.array(devs[: outer * inner]).reshape(outer, inner), axes)


def shard_cluster(cluster: ClusterTensors, mesh: Mesh,
                  spec_axis=NODE_AXIS) -> ClusterTensors:
    """Place every node-axis column sharded over the mesh; small cluster-wide
    vectors (pair_topo_key [TP]) replicated.  spec_axis names the mesh
    axis (or axis tuple, e.g. ("dcn", "ici")) the node dimension splits
    over — ONE classification heuristic for every layout."""
    n = cluster.n_nodes
    out = {}
    for f in dataclasses.fields(cluster):
        v = getattr(cluster, f.name)
        arr = np.asarray(v)
        if arr.ndim >= 1 and arr.shape[0] == n:
            spec = P(spec_axis, *([None] * (arr.ndim - 1)))
        else:
            spec = P(*([None] * arr.ndim))
        out[f.name] = jax.device_put(arr, NamedSharding(mesh, spec))
    return ClusterTensors(**out)


def replicate(tree, mesh: Mesh):
    """Replicate a pytree (PodBatch, port state, scalars) across the mesh."""

    def put(x):
        arr = np.asarray(x)
        return jax.device_put(arr, NamedSharding(mesh, P(*([None] * arr.ndim))))

    return jax.tree_util.tree_map(put, tree)


POD_AXIS = "pods"


def make_mesh_2d(pod_devices: int, node_devices: int) -> Mesh:
    """2D (pods x nodes) mesh: the [B, N] filter/score grid shards BOTH
    ways — the batch axis across one mesh dimension, every node-axis
    column across the other.  The speculative engine's commit matmuls
    ([B, B] incidence against per-node state) become XLA collectives
    across the pod axis automatically; placements stay bit-identical to
    the unsharded program (tests/test_mesh.py).  This is the layout that
    scales BOTH a 100k-pod backlog and a 50k-node fleet past one chip's
    HBM."""
    return _mesh_2level(pod_devices, node_devices, (POD_AXIS, NODE_AXIS))


def shard_pods(tree, mesh: Mesh, n_pods: int):
    """Shard every batch-axis leaf (leading dim == the padded pod count)
    over the mesh's pod axis; everything else replicates.  Use with
    make_mesh_2d for 2D layouts (a 1D node mesh replicates pods via
    `replicate`)."""

    def put(x):
        arr = np.asarray(x)
        if arr.ndim >= 1 and arr.shape[0] == n_pods:
            spec = P(POD_AXIS, *([None] * (arr.ndim - 1)))
        else:
            spec = P(*([None] * arr.ndim))
        return jax.device_put(arr, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, tree)


DCN_AXIS = "dcn"
ICI_AXIS = "ici"


def make_mesh_multihost(n_hosts: int, chips_per_host: int) -> Mesh:
    """Two-level (dcn x ici) mesh for multi-host scale-out: the outer axis
    spans hosts (DCN links), the inner axis the chips within each host
    (ICI links).  The node axis shards over BOTH axes flattened —
    `P(("dcn", "ici"))` — so each host owns a contiguous node block and
    each chip a sub-block.  XLA then lowers cross-shard reductions
    (argmax/min/max in host selection and score normalization)
    hierarchically: intra-host partials ride ICI, only the per-host
    partial crosses DCN — the scaling-book recipe for multi-host meshes,
    with no hand-written collectives.  On real hardware the device order
    from jax.devices() already groups chips by host (process index), so
    the reshape below maps the outer axis onto DCN boundaries; under the
    virtual CPU mesh the layout is exercised structurally and validated
    by placement identity (tests/test_mesh.py).

    This is the multi-host analog of the reference's kubemark scale-out:
    a 50k-node fleet splits across hosts at the DCN level while each
    host's chips scan their node block in parallel (SURVEY §2.4 last
    row, previously deferred)."""
    return _mesh_2level(n_hosts, chips_per_host, (DCN_AXIS, ICI_AXIS))


def shard_cluster_multihost(cluster: ClusterTensors, mesh: Mesh) -> ClusterTensors:
    """shard_cluster over the flattened (dcn, ici) axes: node columns
    split across every chip on every host; cluster-wide vectors
    replicate."""
    return shard_cluster(cluster, mesh, spec_axis=(DCN_AXIS, ICI_AXIS))
