"""Device-mesh sharding of the node axis.

The TPU-native answer to both of the reference's scale mechanisms:
  * the 16-goroutine node scan (generic_scheduler.go:518) -> data parallelism
    over the node axis of every ClusterTensors column;
  * multi-host scale-out (kubemark 5k-node clusters) -> the same sharding over
    a multi-host Mesh, with XLA inserting ICI/DCN collectives.

Filter/Score is embarrassingly parallel over nodes; only host selection
(argmax) and score normalization (max/min over nodes) reduce across shards —
XLA lowers those to all-reduce over ICI when the inputs carry a NamedSharding.
No hand-written collectives: pick a mesh, annotate shardings, let XLA insert
them (the scaling-book recipe).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubernetes_tpu.codec.schema import ClusterTensors

NODE_AXIS = "nodes"


def make_mesh(n_devices: Optional[int] = None, axis: str = NODE_AXIS) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def shard_cluster(cluster: ClusterTensors, mesh: Mesh) -> ClusterTensors:
    """Place every node-axis column sharded over the mesh; small cluster-wide
    vectors (pair_topo_key [TP]) replicated."""
    n = cluster.n_nodes
    out = {}
    for f in dataclasses.fields(cluster):
        v = getattr(cluster, f.name)
        arr = np.asarray(v)
        if arr.ndim >= 1 and arr.shape[0] == n:
            spec = P(NODE_AXIS, *([None] * (arr.ndim - 1)))
        else:
            spec = P(*([None] * arr.ndim))
        out[f.name] = jax.device_put(arr, NamedSharding(mesh, spec))
    return ClusterTensors(**out)


def replicate(tree, mesh: Mesh):
    """Replicate a pytree (PodBatch, port state, scalars) across the mesh."""

    def put(x):
        arr = np.asarray(x)
        return jax.device_put(arr, NamedSharding(mesh, P(*([None] * arr.ndim))))

    return jax.tree_util.tree_map(put, tree)


POD_AXIS = "pods"


def make_mesh_2d(pod_devices: int, node_devices: int) -> Mesh:
    """2D (pods x nodes) mesh: the [B, N] filter/score grid shards BOTH
    ways — the batch axis across one mesh dimension, every node-axis
    column across the other.  The speculative engine's commit matmuls
    ([B, B] incidence against per-node state) become XLA collectives
    across the pod axis automatically; placements stay bit-identical to
    the unsharded program (tests/test_mesh.py).  This is the layout that
    scales BOTH a 100k-pod backlog and a 50k-node fleet past one chip's
    HBM."""
    devs = np.array(jax.devices()[: pod_devices * node_devices])
    return Mesh(devs.reshape(pod_devices, node_devices),
                (POD_AXIS, NODE_AXIS))


def shard_pods(tree, mesh: Mesh, n_pods: int):
    """Shard every batch-axis leaf (leading dim == the padded pod count)
    over the mesh's pod axis; everything else replicates.  Use with
    make_mesh_2d for 2D layouts (a 1D node mesh replicates pods via
    `replicate`)."""

    def put(x):
        arr = np.asarray(x)
        if arr.ndim >= 1 and arr.shape[0] == n_pods:
            spec = P(POD_AXIS, *([None] * (arr.ndim - 1)))
        else:
            spec = P(*([None] * arr.ndim))
        return jax.device_put(arr, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, tree)
