from kubernetes_tpu.parallel.mesh import (
    make_mesh,
    shard_cluster,
    replicate,
    NODE_AXIS,
)
