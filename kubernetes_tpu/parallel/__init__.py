from kubernetes_tpu.parallel.mesh import (
    build_mesh,
    make_mesh,
    mesh_total,
    shard_cluster,
    replicate,
    NODE_AXIS,
)
