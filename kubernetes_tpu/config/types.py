"""Component configuration.

Reference: pkg/scheduler/apis/config/types.go KubeSchedulerConfiguration
(:42-108) — the versioned config object every kube-scheduler binary loads,
with AlgorithmSource (provider | policy file/ConfigMap), leader election,
client connection, and the perf knobs.  Mirrored here as a dataclass with a
from_dict loader (JSON; YAML documents parse the same once loaded).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Optional

from kubernetes_tpu.config.featuregates import FeatureGates
from kubernetes_tpu.config.profile import (
    DEFAULT_PROVIDER,
    SchedulingProfile,
    algorithm_provider,
    profile_from_policy,
)


@dataclass
class LeaderElectionConfig:
    """component-base config.LeaderElectionConfiguration."""

    leader_elect: bool = True
    lease_duration_s: float = 15.0
    renew_deadline_s: float = 10.0
    retry_period_s: float = 2.0
    resource_namespace: str = "kube-system"
    resource_name: str = "kube-scheduler"


@dataclass
class KubeSchedulerConfiguration:
    scheduler_name: str = "default-scheduler"
    algorithm_provider: str = DEFAULT_PROVIDER
    policy: Optional[dict] = None            # legacy Policy JSON (wins if set)
    hard_pod_affinity_symmetric_weight: int = 1
    percentage_of_nodes_to_score: int = 100  # 100 = full scan (the TPU
                                             # default: one launch covers all
                                             # nodes); 0 = the reference's
                                             # adaptive formula; 1-99 fixed %
    bind_timeout_seconds: int = 100          # scheduler.go:48-53
    disable_preemption: bool = False
    leader_election: LeaderElectionConfig = field(default_factory=LeaderElectionConfig)
    healthz_bind_address: str = "0.0.0.0:10251"
    metrics_bind_address: str = "0.0.0.0:10251"
    feature_gates: FeatureGates = field(default_factory=FeatureGates)
    # TPU-specific: batch formation knobs (no reference analog; the reference
    # schedules one pod per cycle)
    batch_size: int = 256
    batch_window_s: float = 0.001
    # "speculative" (hybrid exactness fallback, the default) or
    # "sequential" (always the exact lax.scan)
    engine: str = "speculative"
    # commit-path knobs (runtime/scheduler.py SchedulerConfig): batched =
    # one encoder delta + batched event/metric emission per cycle;
    # pipelined = double-buffer cycles (batch k's bind/event tail overlaps
    # batch k+1's device dispatch)
    batched_commit: bool = True
    pipeline_commit: bool = False
    # device-fault resilience knobs (runtime/scheduler.py SchedulerConfig /
    # runtime/health.py DeviceHealth): classified retry with jittered
    # exponential backoff, circuit breaker, CPU-engine degradation
    device_retry_max: int = 2
    device_backoff_base_s: float = 0.005
    device_backoff_max_s: float = 0.05
    device_backoff_jitter: float = 0.5
    breaker_failure_threshold: int = 3
    breaker_open_s: float = 0.05
    cpu_fallback: bool = True
    # overload protection & backpressure knobs (runtime/queue.py bounded
    # shedding queue + runtime/scheduler.py AIMD adaptive batch sizing)
    queue_capacity: Optional[int] = None
    adaptive_batch: bool = False
    batch_size_min: int = 16
    cycle_deadline_s: float = 0.0
    # tracing (utils/trace.py + runtime/flightrecorder.py): cycles whose
    # root span exceeds this log the full phase breakdown (the utiltrace
    # 100ms convention, now a knob); <=0 disables the slow-cycle log
    # (flight-recorder span capture stays always-on)
    trace_threshold_s: float = 0.1
    # latency tiers (runtime/scheduler.py + runtime/queue.py): a small
    # pre-compiled express lane interleaved with the bulk AIMD lane for
    # annotation-opted-in / high-priority pods
    express_lane: bool = False
    express_batch_size: int = 64
    express_priority_threshold: Optional[int] = None
    # raw-speed knobs: persistent XLA compile cache directory
    # (utils/compilecache.py; None = process default, "off" disables) and
    # startup pre-warming of every AIMD pow2 width + the express width
    compile_cache_dir: Optional[str] = None
    prewarm_widths: bool = False
    # decision ledger + per-plugin attribution (runtime/ledger.py,
    # models/batched.py Attribution): record every cycle's inputs/outcomes
    # for /debug/decisions + bench --replay, and have unschedulable
    # events/annotations name the dominant failing predicate with
    # per-reason node counts
    attribution: bool = False
    decision_ledger: bool = False
    ledger_dir: Optional[str] = None
    ledger_max_cycles: int = 4096
    # cluster + device telemetry (runtime/telemetry.py): device-resident
    # fleet analytics every N cycles, HBM/compile-cache/launch facts,
    # multi-window SLO burn-rate alerting (sloObjectives entries:
    # {name, objective, fastWindowSeconds, slowWindowSeconds,
    # burnThreshold}), and the liveness heartbeat line (0 = off)
    telemetry: bool = True
    telemetry_interval_cycles: int = 1
    slo_objectives: Optional[list] = None
    heartbeat_s: float = 0.0
    # multi-chip sharding (runtime/scheduler.py + parallel/mesh.py): shard
    # the snapshot's node axis across shardDevices chips (pow2; 0 = the
    # single-chip path bit-for-bit); meshShape "OxI" (e.g. "2x4") selects
    # a two-level dcn x ici mesh instead of the 1D node mesh
    shard_devices: int = 0
    mesh_shape: Optional[str] = None
    # elastic degradation ladder (runtime/scheduler.py + runtime/health.py
    # ShardHealth): shard-attributed faults lose ONE device and rebuild
    # the mesh over the widest pow2 of survivors (meshShrinkEnabled)
    # after shardBreakerFailureThreshold consecutive attributed failures
    # (a persistent shard fault loses it immediately); invariantChecks
    # keeps the online conservation checker (runtime/invariants.py) on
    mesh_shrink: bool = True
    shard_breaker_failure_threshold: int = 2
    invariant_checks: bool = True
    # performance observatory (runtime/perfobs.py): directory for the
    # on-demand jax.profiler capture served at GET /debug/profile
    # (None = $KTPU_PROFILE_DIR or /tmp/ktpu_profile); the observatory
    # itself — host/device split, phase x width EWMA, transfer
    # accounting at /debug/perf — is always-on
    profile_dir: Optional[str] = None
    # device-resident megacycle (runtime/scheduler.py +
    # models/megacycle.py): chain up to this many pre-encoded batches
    # through the cluster state in ONE XLA launch, committing the K
    # winner vectors behind the next launch; 1 = single-cycle dispatch
    # bit-for-bit.  Only chain-safe batches ride a megacycle (no
    # pod-affinity/ports/volumes/gangs/nominated pods; lean spread)
    megacycle_batches: int = 1
    # placement-quality observatory (runtime/quality.py): in-launch
    # winner-pinned top-k width (qualityTopK; 0 disables the seam —
    # placements bit-identical either way), the amortized FFD-regret
    # sampling cadence (qualityIntervalCycles), and the dual-window
    # packing-drift step threshold (qualityDriftThreshold)
    quality_top_k: int = 3
    quality_interval_cycles: int = 32
    quality_drift_threshold: float = 0.25
    # device-resident capacity planner (runtime/capacity.py): every
    # capacityIntervalCycles the pending+unschedulable backlog is
    # class-compressed and what-if binpacked — existing headroom first,
    # the overflow over the nodeShapeCatalog ([{name, cpu, memory,
    # ephemeral-storage?, pods?, ...}]; null = the built-in default) —
    # as an amortized side-launch, emitting a scale-up/scale-down
    # recommendation at /debug/capacity + scheduler_capacity_* metrics
    capacity_planner: bool = False
    capacity_interval_cycles: int = 256
    node_shape_catalog: Optional[list] = None
    # guarded autoscaler actuation (runtime/autoscaler.py): a control
    # loop that ENACTS the capacity plan against the live store —
    # scale-up registers nodes from the winning catalog shape (paced,
    # batch-capped), scale-down cordons + drains through the PDB path
    # and deletes.  Dual-threshold hysteresis + a cooldown window bound
    # direction flapping; stuck drains and mid-batch registration
    # failures roll back; every actuation is recorded to a JSONL ledger
    # replayable offline (bench.py --replay).  Implies capacityPlanner.
    autoscaler: bool = False
    autoscaler_interval_s: float = 1.0
    autoscaler_dry_run: bool = False
    autoscaler_cooldown_s: float = 30.0
    autoscaler_max_nodes_per_round: int = 4
    autoscaler_drain_deadline_s: float = 30.0
    autoscaler_min_nodes: int = 1
    autoscaler_max_nodes: int = 256
    autoscaler_ledger_path: Optional[str] = None
    # metrics timeline store (runtime/timeline.py): every registered
    # metric family sampled once per timelineIntervalSeconds into a
    # bounded ring (counters as deltas, gauges as values, histograms as
    # p50/p99), interleaved with typed event annotations from the
    # breaker/shard/mesh/AIMD/shed/autoscaler/chaos seams and run
    # through the online anomaly detector (timelineRules: [{rule:
    # threshold|zscore|slope, series, ...}]; null = the conservative
    # defaults).  Served at /debug/timeline; exported by bench
    # --timeline-out and the scenario engine.
    timeline: bool = True
    timeline_interval_s: float = 1.0
    timeline_retention: int = 512
    timeline_rules: Optional[list] = None
    # queue-sharded scheduler replicas (runtime/replicas.py +
    # runtime/reconciler.py): run this many scheduler loops (threads)
    # over one queue/cache, each draining a stable hash-shard and
    # committing through the sequenced optimistic conflict reconciler;
    # 1 = the classic single loop bit-for-bit.  namespaceQuotas
    # ({namespace: {resource: quantity}}) are enforced at commit by the
    # same reconciler (placement-fairness quota; DRF tiebreak rides the
    # encoder's per-namespace usage columns).
    replicas: int = 1
    namespace_quotas: Optional[dict] = None

    def build_profile(self, interner=None) -> SchedulingProfile:
        """CreateFromConfig / CreateFromProvider (scheduler.go:162-192)."""
        if self.policy is not None:
            return profile_from_policy(
                self.policy, interner=interner, gates=self.feature_gates
            )
        return algorithm_provider(
            self.algorithm_provider,
            gates=self.feature_gates,
            hard_pod_affinity_weight=float(self.hard_pod_affinity_symmetric_weight),
        )

    @staticmethod
    def from_dict(d: dict) -> "KubeSchedulerConfiguration":
        le = d.get("leaderElection") or {}
        return KubeSchedulerConfiguration(
            scheduler_name=d.get("schedulerName", "default-scheduler"),
            algorithm_provider=(d.get("algorithmSource") or {}).get(
                "provider", DEFAULT_PROVIDER
            )
            or DEFAULT_PROVIDER,
            policy=(d.get("algorithmSource") or {}).get("policy"),
            hard_pod_affinity_symmetric_weight=int(
                d.get("hardPodAffinitySymmetricWeight", 1)
            ),
            percentage_of_nodes_to_score=int(d.get("percentageOfNodesToScore", 0)),
            bind_timeout_seconds=int(d.get("bindTimeoutSeconds", 100)),
            disable_preemption=bool(d.get("disablePreemption", False)),
            leader_election=LeaderElectionConfig(
                leader_elect=bool(le.get("leaderElect", True)),
                lease_duration_s=float(le.get("leaseDuration", 15.0)),
                renew_deadline_s=float(le.get("renewDeadline", 10.0)),
                retry_period_s=float(le.get("retryPeriod", 2.0)),
            ),
            healthz_bind_address=d.get("healthzBindAddress", "0.0.0.0:10251"),
            metrics_bind_address=d.get("metricsBindAddress", "0.0.0.0:10251"),
            feature_gates=FeatureGates(d.get("featureGates")),
            batch_size=int(d.get("batchSize", 256)),
            batch_window_s=float(d.get("batchWindowSeconds", 0.001)),
            engine=d.get("engine", "speculative"),
            batched_commit=bool(d.get("batchedCommit", True)),
            pipeline_commit=bool(d.get("pipelineCommit", False)),
            device_retry_max=int(d.get("deviceRetryMax", 2)),
            device_backoff_base_s=float(d.get("deviceBackoffBaseSeconds", 0.005)),
            device_backoff_max_s=float(d.get("deviceBackoffMaxSeconds", 0.05)),
            device_backoff_jitter=float(d.get("deviceBackoffJitter", 0.5)),
            breaker_failure_threshold=int(d.get("breakerFailureThreshold", 3)),
            breaker_open_s=float(d.get("breakerOpenSeconds", 0.05)),
            cpu_fallback=bool(d.get("cpuFallback", True)),
            queue_capacity=(
                int(d["queueCapacity"])
                if d.get("queueCapacity") is not None else None
            ),
            adaptive_batch=bool(d.get("adaptiveBatch", False)),
            batch_size_min=int(d.get("batchSizeMin", 16)),
            cycle_deadline_s=float(d.get("cycleDeadlineSeconds", 0.0)),
            trace_threshold_s=float(d.get("traceThresholdSeconds", 0.1)),
            express_lane=bool(d.get("expressLane", False)),
            express_batch_size=int(d.get("expressBatchSize", 64)),
            express_priority_threshold=(
                int(d["expressPriorityThreshold"])
                if d.get("expressPriorityThreshold") is not None else None
            ),
            compile_cache_dir=d.get("compileCacheDir"),
            prewarm_widths=bool(d.get("prewarmWidths", False)),
            attribution=bool(d.get("attribution", False)),
            decision_ledger=bool(d.get("decisionLedger", False)),
            ledger_dir=d.get("ledgerDir"),
            ledger_max_cycles=int(d.get("ledgerMaxCycles", 4096)),
            telemetry=bool(d.get("telemetry", True)),
            telemetry_interval_cycles=int(
                d.get("telemetryIntervalCycles", 1)
            ),
            slo_objectives=d.get("sloObjectives"),
            heartbeat_s=float(d.get("heartbeatSeconds", 0.0)),
            shard_devices=int(d.get("shardDevices", 0)),
            mesh_shape=d.get("meshShape"),
            mesh_shrink=bool(d.get("meshShrinkEnabled", True)),
            shard_breaker_failure_threshold=int(
                d.get("shardBreakerFailureThreshold", 2)
            ),
            invariant_checks=bool(d.get("invariantChecks", True)),
            profile_dir=d.get("profileDir"),
            megacycle_batches=int(d.get("megacycleBatches", 1)),
            quality_top_k=int(d.get("qualityTopK", 3)),
            quality_interval_cycles=int(d.get("qualityIntervalCycles", 32)),
            quality_drift_threshold=float(
                d.get("qualityDriftThreshold", 0.25)
            ),
            capacity_planner=bool(d.get("capacityPlanner", False)),
            capacity_interval_cycles=int(
                d.get("capacityIntervalCycles", 256)
            ),
            node_shape_catalog=d.get("nodeShapeCatalog"),
            autoscaler=bool(d.get("autoscaler", False)),
            autoscaler_interval_s=float(d.get("autoscalerIntervalSeconds", 1.0)),
            autoscaler_dry_run=bool(d.get("autoscalerDryRun", False)),
            autoscaler_cooldown_s=float(d.get("autoscalerCooldownSeconds", 30.0)),
            autoscaler_max_nodes_per_round=int(
                d.get("autoscalerMaxNodesPerRound", 4)
            ),
            autoscaler_drain_deadline_s=float(
                d.get("autoscalerDrainDeadlineSeconds", 30.0)
            ),
            autoscaler_min_nodes=int(d.get("autoscalerMinNodes", 1)),
            autoscaler_max_nodes=int(d.get("autoscalerMaxNodes", 256)),
            autoscaler_ledger_path=d.get("autoscalerLedgerPath"),
            timeline=bool(d.get("timeline", True)),
            timeline_interval_s=float(
                d.get("timelineIntervalSeconds", 1.0)
            ),
            timeline_retention=int(d.get("timelineRetention", 512)),
            timeline_rules=d.get("timelineRules"),
            replicas=int(d.get("replicas", 1)),
            namespace_quotas=d.get("namespaceQuotas"),
        )

    @staticmethod
    def from_file(path: str) -> "KubeSchedulerConfiguration":
        with open(path) as f:
            return KubeSchedulerConfiguration.from_dict(json.load(f))
