"""Scheduling profiles: algorithm providers + legacy Policy.

The reference builds a runnable scheduler from either an AlgorithmProvider
name or a JSON Policy (scheduler.go:162-192 CreateFromProvider/
CreateFromConfig; registries in factory/plugins.go; stock sets in
algorithmprovider/defaults/defaults.go).  A SchedulingProfile is the compiled
result: the enabled predicate tuple, the priority weight vector, and the
static kernel configs — everything the jitted pipeline needs, hashable so it
keys the jit cache.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.codec.schema import (
    DEFAULT_PRIORITY_WEIGHTS,
    FilterConfig,
    PREDICATE_ORDER,
    PRIO_INDEX,
    PRIORITY_ORDER,
    ScoreConfig,
)
from kubernetes_tpu.config.featuregates import FeatureGates

DEFAULT_PROVIDER = "DefaultProvider"
CLUSTER_AUTOSCALER_PROVIDER = "ClusterAutoscalerProvider"

# defaults.go defaultPredicates() — by name
_DEFAULT_PREDICATES = (
    "NoVolumeZoneConflict",
    "MaxEBSVolumeCount",
    "MaxGCEPDVolumeCount",
    "MaxAzureDiskVolumeCount",
    "MaxCSIVolumeCount",
    "MatchInterPodAffinity",
    "NoDiskConflict",
    "GeneralPredicates",
    "PodFitsHost",          # components of GeneralPredicates, kept for
    "PodFitsHostPorts",     # failure attribution granularity
    "PodMatchNodeSelector",
    "PodFitsResources",
    "CheckNodeMemoryPressure",
    "CheckNodeDiskPressure",
    "CheckNodePIDPressure",
    "CheckNodeCondition",
    "PodToleratesNodeTaints",
    "CheckVolumeBinding",
)

_DEFAULT_PRIORITIES = {
    "SelectorSpreadPriority": 1.0,
    "InterPodAffinityPriority": 1.0,
    "LeastRequestedPriority": 1.0,
    "BalancedResourceAllocation": 1.0,
    "NodePreferAvoidPodsPriority": 10000.0,
    "NodeAffinityPriority": 1.0,
    "TaintTolerationPriority": 1.0,
    "ImageLocalityPriority": 1.0,
}


@dataclass(frozen=True)
class SchedulingProfile:
    name: str
    filter_config: FilterConfig
    score_config: ScoreConfig
    weights: tuple  # len == NUM_PRIORITIES, PRIORITY_ORDER order
    hard_pod_affinity_weight: float = 1.0
    always_check_all_predicates: bool = False
    # Policy "extenders" entries (api/types.go:203-240), as
    # extender.client.ExtenderConfig
    extender_configs: tuple = ()

    def weights_array(self) -> np.ndarray:
        return np.asarray(self.weights, np.float32)


def _apply_feature_gates(pred_set: set, prio: Dict[str, float], gates: FeatureGates):
    """defaults.go ApplyFeatureGates: TaintNodesByCondition removes the
    condition predicates and makes taint/unschedulable checks mandatory;
    ResourceLimitsPriorityFunction registers its priority at weight 1."""
    if gates.enabled("TaintNodesByCondition"):
        pred_set -= {
            "CheckNodeCondition",
            "CheckNodeMemoryPressure",
            "CheckNodeDiskPressure",
            "CheckNodePIDPressure",
        }
        pred_set |= {"PodToleratesNodeTaints", "CheckNodeUnschedulable"}
    if not gates.enabled("VolumeScheduling"):
        pred_set -= {"CheckVolumeBinding"}
    if gates.enabled("ResourceLimitsPriorityFunction"):
        prio["ResourceLimitsPriority"] = 1.0


def _weights_vector(prio: Dict[str, float]) -> tuple:
    w = np.zeros(len(PRIORITY_ORDER), np.float32)
    for name, weight in prio.items():
        if name not in PRIO_INDEX:
            raise ValueError(f"unknown priority {name!r}")
        w[PRIO_INDEX[name]] = weight
    return tuple(float(x) for x in w)


def algorithm_provider(
    name: str = DEFAULT_PROVIDER,
    gates: Optional[FeatureGates] = None,
    hard_pod_affinity_weight: float = 1.0,
) -> SchedulingProfile:
    """CreateFromProvider (scheduler.go:164-173)."""
    gates = gates or FeatureGates()
    pred_set = set(_DEFAULT_PREDICATES)
    prio = dict(_DEFAULT_PRIORITIES)
    if name == CLUSTER_AUTOSCALER_PROVIDER:
        # copyAndReplace(LeastRequested -> MostRequested), defaults.go:105
        prio.pop("LeastRequestedPriority")
        prio["MostRequestedPriority"] = 1.0
    elif name != DEFAULT_PROVIDER:
        raise ValueError(f"unknown algorithm provider {name!r}")
    _apply_feature_gates(pred_set, prio, gates)
    return SchedulingProfile(
        name=name,
        filter_config=FilterConfig(
            enabled=tuple(sorted(pred_set)),
            hard_pod_affinity_weight=hard_pod_affinity_weight,
        ),
        score_config=ScoreConfig(),
        weights=_weights_vector(prio),
        hard_pod_affinity_weight=hard_pod_affinity_weight,
    )


def profile_from_policy(
    policy: dict,
    interner=None,
    gates: Optional[FeatureGates] = None,
) -> SchedulingProfile:
    """Legacy Policy JSON (pkg/scheduler/api/types.go Policy; loaded from a
    file or ConfigMap, scheduler.go:172-192).  Shape:

      {"kind": "Policy", "predicates": [{"name": ...,
          "argument": {"labelsPresence": {"labels": [...], "presence": true}}}],
       "priorities": [{"name": ..., "weight": w,
          "argument": {"labelPreference": ..., "requestedToCapacityRatioArguments": ...}}],
       "hardPodAffinitySymmetricWeight": 1, "alwaysCheckAllPredicates": false}

    An empty predicates/priorities list means "use defaults" (factory
    CreateFromConfig).  `interner` is needed to resolve label strings for
    label-presence arguments.
    """
    gates = gates or FeatureGates()
    label_keys: list = []
    label_presence = True
    label_prefs: list = []
    svc_aff_labels: list = []
    rtc_shape = None

    preds = policy.get("predicates")
    if preds is None:
        pred_set = set(_DEFAULT_PREDICATES)
    else:
        pred_set = set()
        for p in preds:
            name = p["name"]
            arg = p.get("argument") or {}
            if "labelsPresence" in arg:
                lp = arg["labelsPresence"]
                name = "CheckNodeLabelPresence"
                for lab in lp.get("labels", []):
                    label_keys.append(
                        interner.intern(lab) if interner is not None else lab
                    )
                label_presence = bool(lp.get("presence", True))
            elif "serviceAffinity" in arg:
                name = "CheckServiceAffinity"
                for lab in arg["serviceAffinity"].get("labels", []):
                    svc_aff_labels.append(
                        interner.intern(lab) if interner is not None else lab
                    )
            if name == "GeneralPredicates":
                pred_set |= {
                    "PodFitsHost", "PodFitsHostPorts",
                    "PodMatchNodeSelector", "PodFitsResources",
                }
            if name not in PREDICATE_ORDER:
                raise ValueError(f"unknown predicate {name!r}")
            pred_set.add(name)

    prios = policy.get("priorities")
    if prios is None:
        prio = dict(_DEFAULT_PRIORITIES)
    else:
        prio = {}
        for p in prios:
            name = p["name"]
            weight = float(p.get("weight", 1))
            arg = p.get("argument") or {}
            if "labelPreference" in arg:
                lp = arg["labelPreference"]
                key = lp.get("label", "")
                label_prefs.append(
                    (
                        interner.intern(key) if interner is not None else key,
                        bool(lp.get("presence", True)),
                        weight,
                    )
                )
                prio["NodeLabelPriority"] = 1.0  # weights folded per-pref
                continue
            if "requestedToCapacityRatioArguments" in arg:
                shape = arg["requestedToCapacityRatioArguments"].get("shape", [])
                rtc_shape = tuple(
                    (float(pt["utilization"]), float(pt["score"])) for pt in shape
                )
                prio["RequestedToCapacityRatioPriority"] = weight
                continue
            if name not in PRIO_INDEX:
                raise ValueError(f"unknown priority {name!r}")
            prio[name] = weight

    _apply_feature_gates(pred_set, prio, gates)
    hard_w = float(policy.get("hardPodAffinitySymmetricWeight", 1))
    fc = FilterConfig(
        enabled=tuple(sorted(pred_set)),
        hard_pod_affinity_weight=hard_w,
        label_presence_keys=tuple(label_keys),
        label_presence_present=label_presence,
        service_affinity_labels=tuple(svc_aff_labels),
    )
    sc = ScoreConfig(
        label_prefs=tuple(label_prefs),
        rtc_shape=rtc_shape if rtc_shape else ScoreConfig.rtc_shape,
    )
    from kubernetes_tpu.extender.client import ExtenderConfig

    return SchedulingProfile(
        name="policy",
        filter_config=fc,
        score_config=sc,
        weights=_weights_vector(prio),
        hard_pod_affinity_weight=hard_w,
        always_check_all_predicates=bool(policy.get("alwaysCheckAllPredicates", False)),
        extender_configs=tuple(
            ExtenderConfig.from_dict(e) for e in policy.get("extenders") or ()
        ),
    )
