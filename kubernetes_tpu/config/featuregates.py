"""Runtime feature gates.

Reference: pkg/features/kube_features.go (66 gates, queried through
utilfeature.DefaultFeatureGate.Enabled) — the scheduler-relevant subset with
the reference's v1.15 defaults.  Gates rewire the active predicate/priority
sets (algorithmprovider/defaults/defaults.go ApplyFeatureGates).
"""

from __future__ import annotations

from typing import Dict, Optional

# scheduler-relevant gates and their v1.15 defaults
DEFAULT_GATES: Dict[str, bool] = {
    "TaintNodesByCondition": True,     # conditions become taints; condition
                                       # predicates removed (defaults.go:59-97)
    "ResourceLimitsPriorityFunction": False,
    "BalanceAttachedNodeVolumes": False,
    "AttachVolumeLimit": True,         # per-node attachable-volumes-* limits
    "PodPriority": True,
    "TaintBasedEvictions": False,
    "ScheduleDaemonSetPods": True,
    "VolumeScheduling": True,          # CheckVolumeBinding enabled
    "LocalStorageCapacityIsolation": True,  # ephemeral-storage accounting
}


class FeatureGates:
    def __init__(self, overrides: Optional[Dict[str, bool]] = None):
        self._gates = dict(DEFAULT_GATES)
        for k, v in (overrides or {}).items():
            self._gates[k] = bool(v)

    def enabled(self, name: str) -> bool:
        return self._gates.get(name, False)

    @staticmethod
    def from_string(s: str) -> "FeatureGates":
        """Parse the --feature-gates flag format: "A=true,B=false"."""
        overrides = {}
        for part in filter(None, (p.strip() for p in s.split(","))):
            k, _, v = part.partition("=")
            overrides[k] = v.lower() in ("true", "1", "t")
        return FeatureGates(overrides)
