from kubernetes_tpu.config.featuregates import FeatureGates, DEFAULT_GATES
from kubernetes_tpu.config.profile import (
    SchedulingProfile,
    algorithm_provider,
    profile_from_policy,
    DEFAULT_PROVIDER,
    CLUSTER_AUTOSCALER_PROVIDER,
)
from kubernetes_tpu.config.types import KubeSchedulerConfiguration
