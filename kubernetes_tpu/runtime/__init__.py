"""Host-side control loop: queue, cache, and the scheduling service.

The analog of pkg/scheduler/scheduler.go + internal/{queue,cache}: the control
plane stays on the host (Python), the Filter/Score math lives on device.
"""

from kubernetes_tpu.runtime.queue import PriorityQueue, PodBackoff
from kubernetes_tpu.runtime.cache import SchedulerCache
from kubernetes_tpu.runtime.flightrecorder import RECORDER, FlightRecorder
from kubernetes_tpu.runtime.health import DeviceHealth
from kubernetes_tpu.runtime.quality import QualityObservatory
from kubernetes_tpu.runtime.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.runtime.telemetry import SLOObjective, TelemetryHub
