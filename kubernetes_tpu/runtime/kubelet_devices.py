"""Kubelet device/CPU managers + checkpointing (VERDICT r3 missing #5).

Reference:
  * pkg/kubelet/cm/devicemanager/manager.go:1-834 — device plugins
    register a resource name + device IDs; the manager publishes them as
    node allocatable (extended resources), allocates concrete IDs per
    container, and checkpoints pod->device assignments so a kubelet
    restart over live pods reconstructs state;
  * pkg/kubelet/cm/cpumanager (static policy) — Guaranteed pods with
    INTEGRAL cpu requests get exclusive cores carved from the shared
    pool; everything else shares the remainder; assignments checkpoint;
  * pkg/kubelet/checkpointmanager/checkpoint_manager.go:1-110 — named
    JSON checkpoints with a checksum, written atomically.

The TPU angle is the same one the scheduler takes: the managers keep
plain-data state (dicts of ids), publish allocatable through the normal
node-status path so the device-side `filter_batch` sees extended
resources like any other column, and persist through small JSON files —
no daemons, no grpc registration dance (the plugin "socket" here is the
`DevicePlugin` object handed to `register`)."""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.resource import parse_quantity
from kubernetes_tpu.api.types import Pod, qos_class


class CorruptCheckpoint(Exception):
    """Checksum mismatch: the checkpoint is ignored and rebuilt
    (checkpoint_manager.go returns ErrCorruptCheckpoint)."""


class CheckpointManager:
    """Atomic named JSON checkpoints with a crc32 checksum
    (checkpointmanager's Checksum.Verify over the serialized payload)."""

    def __init__(self, checkpoint_dir: str):
        self.dir = checkpoint_dir
        os.makedirs(checkpoint_dir, exist_ok=True)

    def _path(self, name: str) -> str:
        return os.path.join(self.dir, name)

    def create(self, name: str, data: dict) -> None:
        payload = json.dumps(data, sort_keys=True)
        doc = {"data": payload,
               "checksum": zlib.crc32(payload.encode()) & 0xFFFFFFFF}
        fd, tmp = tempfile.mkstemp(dir=self.dir, prefix=f".{name}.")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, self._path(name))  # atomic publish
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def get(self, name: str) -> Optional[dict]:
        try:
            with open(self._path(name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        payload = doc.get("data", "")
        if (zlib.crc32(payload.encode()) & 0xFFFFFFFF) != doc.get("checksum"):
            raise CorruptCheckpoint(name)
        return json.loads(payload)

    def remove(self, name: str) -> None:
        try:
            os.unlink(self._path(name))
        except OSError:
            pass

    def list(self) -> List[str]:
        return [n for n in os.listdir(self.dir) if not n.startswith(".")]


@dataclass
class DevicePlugin:
    """A registered plugin: resource name + healthy device IDs (the
    ListAndWatch stream collapsed to data)."""

    resource: str                      # e.g. "example.com/gpu"
    device_ids: Tuple[str, ...]
    unhealthy: Tuple[str, ...] = ()    # subset currently unhealthy


_DEV_CHECKPOINT = "kubelet_internal_checkpoint"


class DeviceManager:
    """devicemanager/manager.go distilled: registration -> allocatable,
    Allocate -> concrete IDs per (pod, container), checkpoint/restore."""

    def __init__(self, checkpoints: Optional[CheckpointManager] = None):
        self.plugins: Dict[str, DevicePlugin] = {}
        # (pod_uid, container) -> {resource: [ids]}
        self.allocations: Dict[Tuple[str, str], Dict[str, List[str]]] = {}
        self.checkpoints = checkpoints
        if checkpoints is not None:
            self._restore()

    # ------------------------------------------------------- registration

    def register(self, plugin: DevicePlugin) -> None:
        self.plugins[plugin.resource] = plugin

    def unregister(self, resource: str) -> None:
        self.plugins.pop(resource, None)

    def allocatable(self) -> Dict[str, int]:
        """resource -> healthy device count (what lands on
        node.status.allocatable as extended resources)."""
        return {
            r: len([d for d in p.device_ids if d not in p.unhealthy])
            for r, p in self.plugins.items()
        }

    # --------------------------------------------------------- allocation

    def _in_use(self, resource: str) -> set:
        used = set()
        for per_res in self.allocations.values():
            used.update(per_res.get(resource, ()))
        return used

    def allocate(self, pod: Pod, container: str = "main") -> Dict[str, List[str]]:
        """Satisfy the pod's extended-resource requests with concrete
        device IDs (Allocate); raises if short.  Idempotent per
        (pod, container) — a sync retry must not double-allocate."""
        key = (pod.metadata.uid or f"{pod.namespace}/{pod.name}", container)
        if key in self.allocations:
            return self.allocations[key]
        wants: Dict[str, int] = {}
        for res, q in (pod.resource_request() or {}).items():
            if res in self.plugins:
                wants[res] = int(q.value)
        if not wants:
            return {}
        granted: Dict[str, List[str]] = {}
        for res, n in wants.items():
            p = self.plugins[res]
            free = [d for d in p.device_ids
                    if d not in p.unhealthy and d not in self._in_use(res)]
            if len(free) < n:
                raise RuntimeError(
                    f"insufficient {res}: want {n}, have {len(free)}")
            granted[res] = free[:n]
        self.allocations[key] = granted
        self._checkpoint()
        return granted

    def release(self, pod: Pod) -> None:
        uid = pod.metadata.uid or f"{pod.namespace}/{pod.name}"
        for key in [k for k in self.allocations if k[0] == uid]:
            del self.allocations[key]
        self._checkpoint()

    # ------------------------------------------------------- checkpointing

    def _checkpoint(self) -> None:
        if self.checkpoints is None:
            return
        self.checkpoints.create(_DEV_CHECKPOINT, {
            "allocations": [
                {"pod": k[0], "container": k[1], "devices": v}
                for k, v in self.allocations.items()
            ],
        })

    def _restore(self) -> None:
        try:
            data = self.checkpoints.get(_DEV_CHECKPOINT)
        except CorruptCheckpoint:
            self.checkpoints.remove(_DEV_CHECKPOINT)
            return
        if not data:
            return
        for a in data.get("allocations", []):
            self.allocations[(a["pod"], a["container"])] = {
                r: list(ids) for r, ids in a["devices"].items()
            }


_CPU_CHECKPOINT = "cpu_manager_state"


class CPUManager:
    """cpumanager static policy: a Guaranteed pod whose cpu request is a
    whole number of cores gets EXCLUSIVE cpus carved out of the shared
    pool; everyone else shares what remains.  State checkpoints like the
    reference's state file."""

    def __init__(self, num_cpus: int,
                 checkpoints: Optional[CheckpointManager] = None,
                 reserved: int = 0):
        self.all_cpus = list(range(num_cpus))
        self.reserved = set(range(reserved))  # system-reserved cores
        self.assignments: Dict[str, List[int]] = {}   # pod uid -> cpus
        self.checkpoints = checkpoints
        if checkpoints is not None:
            self._restore()

    @staticmethod
    def _exclusive_cpus(pod: Pod) -> int:
        """Whole cores for a Guaranteed pod with integral request
        (policy_static.go guaranteedCPUs), else 0."""
        if qos_class(pod) != "Guaranteed":
            return 0
        cpu = (pod.resource_request() or {}).get("cpu")
        if cpu is None:
            return 0
        millis = int(round(cpu.value * 1000))
        if millis % 1000 != 0:
            return 0
        return millis // 1000

    def shared_pool(self) -> List[int]:
        used = set(self.reserved)
        for cpus in self.assignments.values():
            used.update(cpus)
        return [c for c in self.all_cpus if c not in used]

    def add_pod(self, pod: Pod) -> List[int]:
        """-> the pod's exclusive cpus ([] = shared pool)."""
        uid = pod.metadata.uid or f"{pod.namespace}/{pod.name}"
        if uid in self.assignments:
            return self.assignments[uid]
        n = self._exclusive_cpus(pod)
        if n == 0:
            return []
        free = self.shared_pool()
        if len(free) < n:
            raise RuntimeError(
                f"not enough free cpus: want {n}, shared pool {len(free)}")
        self.assignments[uid] = free[:n]
        self._checkpoint()
        return self.assignments[uid]

    def remove_pod(self, pod: Pod) -> None:
        uid = pod.metadata.uid or f"{pod.namespace}/{pod.name}"
        if self.assignments.pop(uid, None) is not None:
            self._checkpoint()

    def _checkpoint(self) -> None:
        if self.checkpoints is None:
            return
        self.checkpoints.create(_CPU_CHECKPOINT, {
            "assignments": self.assignments,
            "reserved": sorted(self.reserved),
        })

    def _restore(self) -> None:
        try:
            data = self.checkpoints.get(_CPU_CHECKPOINT)
        except CorruptCheckpoint:
            self.checkpoints.remove(_CPU_CHECKPOINT)
            return
        if not data:
            return
        self.assignments = {
            uid: list(cpus)
            for uid, cpus in (data.get("assignments") or {}).items()
        }
