"""The scheduling service: the scheduleOne loop, batched.

Mirrors Scheduler.Run / scheduleOne (ref pkg/scheduler/scheduler.go:250-593)
with the one structural change that unlocks TPU throughput: instead of one
pod per cycle, each cycle drains a batch from the queue and places it with
the sequential-commit device program (models/batched.py) — semantically the
same as running scheduleOne B times against a continuously-updated cache,
but in a single XLA launch.

Per cycle:
  1. queue.pop_batch                      (NextPod, scheduler.go:438-447)
  2. cache.snapshot -> device tensors     (the snapshot seam, :176-179)
  3. sequential-commit schedule on device
  4. per pod: assume + bind via the binder callback (async),
     or add_unschedulable on failure     (:463-475, MakeDefaultErrorFunc)
"""

from __future__ import annotations

import dataclasses
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from kubernetes_tpu.api.types import Pod, PodDisruptionBudget
from kubernetes_tpu.codec.schema import FilterConfig
from kubernetes_tpu.models.batched import (
    batch_has_pod_affinity,
    encode_batch_affinity,
    encode_batch_ports,
    encode_nominated,
    encode_nominated_block,
    make_sequential_scheduler,
)
from kubernetes_tpu.models.preemption import (
    make_preempt_eval,
    pick_preemption_node,
    preemption_candidates,
    sorted_victim_slots,
    verify_nomination,
)
from kubernetes_tpu.codec.faults import (
    FAULT_PERSISTENT,
    CorruptedFetchError,
    classify_device_error,
)
from kubernetes_tpu.codec import faults as device_faults
from kubernetes_tpu.codec.transfer import AsyncFetch, host_fetch
from kubernetes_tpu.ops.predicates import filter_batch, required_affinity_ok
from kubernetes_tpu.runtime.cache import SchedulerCache
from kubernetes_tpu.runtime.health import DeviceHealth
from kubernetes_tpu.runtime.events import (
    EVENT_TYPE_NORMAL,
    EVENT_TYPE_WARNING,
    EventRecorder,
)
from kubernetes_tpu.runtime.flightrecorder import RECORDER, FlightRecorder
from kubernetes_tpu.runtime.queue import (
    TIER_BULK,
    TIER_EXPRESS,
    PriorityQueue,
    classify_tier,
)
from kubernetes_tpu.utils import klog
from kubernetes_tpu.utils import metrics as m
from kubernetes_tpu.utils.trace import Span, current_trace_id, use_traceparent

TAINT_NODE_UNSCHEDULABLE = "node.kubernetes.io/unschedulable"


@dataclass
class SchedulerConfig:
    batch_size: int = 256
    batch_window_s: float = 0.001
    # "speculative" (default) = parallel placement + conflict repair with
    # the HYBRID exactness fallback: contention sentinels (order
    # inversion, real bounce, unscheduled pod) trigger a sequential-scan
    # redo of the batch, so the scheduled/unschedulable split always
    # matches one-at-a-time semantics while uncontended batches keep the
    # one-launch fast path.  "sequential" = always the exact lax.scan.
    # Both engines carry in-batch affinity and nominated-pod state.
    engine: str = "speculative"
    percentage_of_nodes_to_score: int = 100  # TPU path scans all; knob for parity
    disable_preemption: bool = False
    # batched commit: apply a cycle's winners as ONE cache/encoder delta
    # under a single lock acquisition, with batched event/metric emission,
    # instead of the per-pod assume->bind loop.  State-equivalent to the
    # per-pod loop (pinned by tests/test_batched_commit.py); automatically
    # bypassed when a framework with plugins is attached (Reserve/Permit/
    # Prebind are per-pod extension points).
    batched_commit: bool = True
    # pipelined commit: overlap batch k's host bind/event/requeue tail with
    # batch k+1's device dispatch (double-buffered cycles).  Placement
    # correctness is preserved because the STATE half of the commit
    # (assume + encoder delta) still happens before batch k+1 encodes;
    # only the side-effect tail (binds, events, metrics, preemption) runs
    # while the device crunches the next batch.  Bind failures roll back
    # via the standard optimistic ForgetPod + requeue, exactly like the
    # reference's async bind goroutine (scheduler.go:523).
    pipeline_commit: bool = False
    # --- device-fault resilience (runtime/health.DeviceHealth +
    # cpuref/adapter.CpuEngineAdapter; faults classified by codec/faults) ---
    # transient retries of the SAME in-flight batch before giving up on it
    device_retry_max: int = 2
    # jittered exponential backoff between those retries (base * 2^attempt,
    # jitter-scaled, hard-capped at max so chaos tests stay sub-100ms)
    device_backoff_base_s: float = 0.005
    device_backoff_max_s: float = 0.05
    device_backoff_jitter: float = 0.5
    # consecutive classified failures that trip the breaker (a persistent
    # "device lost" trips immediately regardless)
    breaker_failure_threshold: int = 3
    # open -> half-open cool-down before a canary batch probes the device
    breaker_open_s: float = 0.05
    # graceful degradation: while the breaker is open, serve cycles from
    # the CPU reference engine instead of stalling/requeueing forever.
    # False = legacy behavior (device faults requeue the batch and raise).
    cpu_fallback: bool = True
    # --- overload protection & backpressure ---
    # bound the scheduling queue (runtime/queue.py PriorityQueue capacity):
    # at capacity a new arrival sheds the lowest-priority longest-
    # unschedulable pod (backoff pods are starvation-guarded) or is itself
    # rejected; None = unbounded (legacy).  Only applied to a queue THIS
    # scheduler constructs — a caller-owned queue keeps its own capacity.
    queue_capacity: Optional[int] = None
    # AIMD adaptive batch sizing: each cycle pops up to the CURRENT batch
    # size, which grows additively (+batch_size_min) toward batch_size
    # while active-queue depth exceeds it and halves (floored at
    # batch_size_min) when a cycle overruns cycle_deadline_s — sustained
    # pressure converts into bigger device launches instead of queue
    # growth, and latency overruns shed batch width first.
    adaptive_batch: bool = False
    batch_size_min: int = 16
    # per-cycle wall-clock budget driving the multiplicative decrease;
    # 0 = no deadline (depth alone steers the batch size)
    cycle_deadline_s: float = 0.0
    # --- tracing (utils/trace.py spans + runtime/flightrecorder.py) ---
    # a cycle whose root span exceeds this logs the full span breakdown
    # (the utiltrace 100ms convention, now configurable); <=0 disables
    # the slow-cycle log (spans still record to the flight recorder)
    trace_threshold_s: float = 0.1
    # --- latency tiers (ISSUE 6): the express lane ---
    # two-tier dispatch: pods classified express at queue admission
    # (annotation opt-in, or spec.priority >= express_priority_threshold)
    # schedule through a small pre-compiled batch shape that the run loop
    # serves BEFORE each bulk cycle, so a latency-sensitive pod never
    # waits out a 2048-wide bulk dispatch.  Both lanes share the cache,
    # snapshot, rotation counter, and the full resilience stack (retry/
    # breaker/CPU degradation/shed guards) — placements are bit-identical
    # to running the same pop order through one lane (pinned by test).
    express_lane: bool = False
    # express encode width (padded to pow2; also the per-cycle pop cap) —
    # small enough that an express cycle costs ~ms, large enough to
    # absorb an arrival burst without queueing a second cycle
    express_batch_size: int = 64
    # pods at or above this priority classify express without the
    # annotation (None = annotation opt-in only)
    express_priority_threshold: Optional[int] = None
    # --- raw-speed knobs (ISSUE 6) ---
    # persistent XLA compile cache directory (utils/compilecache.py):
    # restarts pay zero recompiles.  None/"" = leave the process default
    # (cmd/scheduler and bench enable it); the literal "off" disables
    compile_cache_dir: Optional[str] = None
    # pre-pay engine compiles at startup for every AIMD pow2 width plus
    # the express width (Scheduler.prewarm) instead of stalling the first
    # cycle at each new width mid-traffic
    prewarm_widths: bool = False
    # --- decision ledger + attribution (ISSUE 7) ---
    # per-plugin attribution: the engine launch ALSO emits per-pod
    # first-failing-predicate node counts and a top-k per-plugin score
    # breakdown (models/batched.py Attribution — a separate executable
    # behind a static flag; placements stay bit-identical).  Forces the
    # sequential engine (the scan owns the per-step state the attribution
    # is computed against); FailedScheduling events and the
    # kubernetes-tpu.io/unschedulable-reason annotation then name the
    # dominant failing predicate with per-reason node counts.
    attribution: bool = False
    # decision ledger (runtime/ledger.py): record every cycle's inputs
    # (snapshot delta, encoded batch, rotation base) and outcomes
    # (winners, engine, tier, faults) off the hot path, replayable via
    # Scheduler.replay_cycle / bench.py --replay.  ledger_dir=None keeps
    # the in-memory /debug/decisions ring without touching disk.
    decision_ledger: bool = False
    ledger_dir: Optional[str] = None
    # bounded append-only file: recording stops (and counts drops) after
    # this many cycles
    ledger_max_cycles: int = 4096
    # --- cluster + device telemetry (ISSUE 8: runtime/telemetry.py) ---
    # the telemetry hub: device-resident cluster analytics (utilization/
    # fragmentation/imbalance/occupancy percentiles from ops/analytics),
    # HBM + compile-cache + launch-EWMA runtime facts, and the
    # multi-window SLO burn-rate evaluator firing slo_burn postmortems.
    # Always-on by design (the <2%-of-cycle budget is pinned by
    # perf_smoke); False removes the hook entirely.
    telemetry: bool = True
    # analytics side-launch cadence: every Nth committed cycle dispatches
    # the fused snapshot reduction (the previous launch's tiny result is
    # materialized first, so the scheduling thread never blocks on it)
    telemetry_interval_cycles: int = 1
    # SLO objectives for the burn evaluator: list of dicts ({name,
    # objective, fastWindowSeconds, slowWindowSeconds, burnThreshold});
    # None = the defaults (cycle_deadline, goodput, degraded)
    slo_objectives: Optional[list] = None
    # liveness heartbeat: a once-per-interval one-line klog summary
    # (cycles, placed/unschedulable, depths, breaker, AIMD width, HBM
    # live) so a quiet log still proves the loop is alive; 0 = off
    heartbeat_s: float = 0.0
    # --- multi-chip sharding (ISSUE 9): the live control plane over a
    # device Mesh (parallel/mesh.py).  shard_devices splits the
    # snapshot's node axis across that many devices — every engine
    # launch, the incremental dirty-row upload, and the telemetry
    # analytics side-launch then run sharded, with only the argmax/
    # normalize reductions crossing shards (XLA-inserted ICI/DCN
    # collectives; placements are bit-identical to single-chip, pinned
    # by tests/test_sharded_live.py).  0 = today's single-chip path
    # bit-for-bit.  Must be a power of two (node axes pad to pow2).
    shard_devices: int = 0
    # mesh topology: None = a 1D node mesh over shard_devices; "OxI"
    # (e.g. "2x4") = a two-level dcn x ici mesh (hosts x chips-per-host)
    # with the node axis sharded over both axes flattened, so
    # cross-shard reductions lower hierarchically (intra-host partials
    # over ICI, per-host partials over DCN)
    mesh_shape: Optional[str] = None
    # --- elastic degradation ladder (ISSUE 10) ---
    # mesh shrink-on-failure: when a classified fault is ATTRIBUTED to
    # one mesh device (codec/faults.fault_device_index) and that shard's
    # breaker trips, rebuild the mesh over the widest pow2 of the
    # surviving devices instead of tripping the global breaker — the
    # ladder full mesh -> shrunken mesh -> single chip -> CPU adapter,
    # with the in-flight batch served bit-identically by the CPU engine
    # during the one-cycle transition and a half-open canary probing the
    # LOST device to restore the original mesh on recovery.  False =
    # the PR 3 behavior (any persistent fault demotes the whole mesh).
    mesh_shrink: bool = True
    # consecutive classified failures attributed to ONE shard that lose
    # that shard (a persistent shard fault loses it immediately); below
    # the global breaker_failure_threshold by default so a single sick
    # device is carved out before the whole mesh is condemned
    shard_breaker_failure_threshold: int = 2
    # online invariant checker (runtime/invariants.py): conservation
    # (every popped pod ends bound/requeued/shed exactly once), no
    # double-bind, committed usage <= allocatable — fed from the commit
    # seams, firing scheduler_invariant_violations_total{rule=} + a
    # flight-recorder postmortem on violation.  Always-on by design
    # (dict-ops per event); False removes the hooks entirely.
    invariant_checks: bool = True
    # --- performance observatory (ISSUE 11: runtime/perfobs.py) ---
    # on-demand jax.profiler capture directory for GET /debug/profile
    # (None = $KTPU_PROFILE_DIR or /tmp/ktpu_profile).  The observatory
    # itself — host/device cycle split, phase x width EWMA, transfer
    # accounting — is always-on by design (dict ops per cycle; the <2%
    # budget is pinned by perf_smoke alongside the span/telemetry pins)
    profile_dir: Optional[str] = None
    # --- device-resident megacycle (ISSUE 12: models/megacycle.py) ---
    # chain up to this many pre-encoded batches through the cluster
    # state in ONE XLA launch (a lax.scan over the K axis), committing
    # the K winner vectors asynchronously behind the next megacycle's
    # dispatch — the host pays one dispatch + one fence per K batches.
    # 1 = today's single-cycle path bit-for-bit.  The effective K per
    # launch is the pow2 floor of the eligible batches actually queued
    # (bounding compiled shapes to the pow2 ladder); AIMD sizes it like
    # the batch width when adaptive_batch is on.  Only batches whose
    # cross-batch coupling is resources + lean SelectorSpread ride a
    # megacycle (no pod-affinity/ports/volumes/gangs/nominated pods, no
    # extender or framework fan-out) — anything else falls back to
    # single cycles, placements bit-identical either way (pinned by
    # tests/test_megacycle.py).
    megacycle_batches: int = 1
    # --- placement-quality observatory (ISSUE 13: runtime/quality.py) ---
    # in-launch top-k width: every engine launch ALSO returns, per pod,
    # the K best feasible node rows (winner pinned at column 0), their
    # scores, and the feasible-candidate count — fetched at the same
    # commit fence as attribution (one extra D2H copy, no extra sync)
    # and folded into margin/feasible/regret/drift records served at
    # /debug/quality.  Always-on by design like telemetry/perfobs (the
    # <2%-of-cycle budget is pinned by perf_smoke); 0 disables the seam
    # entirely (the engines compile their classic executables).
    # Placements are bit-identical whatever the value (pinned by
    # tests/test_quality.py).
    quality_top_k: int = 3
    # regret-counterfactual cadence: every Nth committed cycle the
    # cycle's pod requests are FFD-binpacked into the pre-cycle free
    # capacity as a side launch (dispatched now, materialized next
    # interval — the telemetry amortization), yielding the
    # scheduler_placement_regret ratio
    quality_interval_cycles: int = 32
    # dual-window EWMA step-detector threshold for the packing-drift
    # alerts (relative deviation of the fast window from the slow one)
    quality_drift_threshold: float = 0.25
    # --- device-resident capacity planner (ISSUE 15: runtime/capacity.py) ---
    # what-if binpack of the pending+unschedulable backlog: every
    # capacityIntervalCycles the backlog is CLASS-COMPRESSED (distinct
    # request vector -> count) and packed — existing node headroom
    # first, the overflow over the node-shape catalog — as an amortized
    # side-launch behind the scheduling loop, emitting a scale-up/
    # scale-down recommendation at /debug/capacity + the
    # scheduler_capacity_* families.  Placements are bit-identical with
    # the planner on or off (purely observational).
    capacity_planner: bool = False
    capacity_interval_cycles: int = 256
    # candidate node shapes ([{name, cpu, memory, ephemeral-storage?,
    # pods?, <extended resources>...}]); None = the small built-in
    # default catalog (runtime/capacity.DEFAULT_SHAPE_CATALOG)
    node_shape_catalog: Optional[list] = None
    # --- metrics timeline store (ISSUE 20: runtime/timeline.py) ---
    # bounded in-process time-series: every registered metric family is
    # sampled once per timeline_interval_s (counters as per-interval
    # deltas, gauges as values, histograms as p50/p99), interleaved with
    # typed event annotations from the existing seams (breaker/shard
    # transitions, mesh rebuilds, AIMD resizes, sheds, degraded fetches,
    # invariant violations, autoscaler rounds, chaos windows) and run
    # through the online AnomalyDetector (threshold/zscore/slope rules,
    # edge-triggered, flight-recorder postmortems).  Served at
    # /debug/timeline; exported as JSONL + static HTML by bench
    # --timeline-out and the scenario engine.  False removes the
    # sampling hook entirely.
    timeline: bool = True
    # sampling cadence (wall seconds between samples; the hook rides the
    # commit tail + the idle heartbeat path, so a busy loop samples at
    # most once per interval and an idle loop still samples)
    timeline_interval_s: float = 1.0
    # points retained per series (ring buffer; also bounds events)
    timeline_retention: int = 512
    # anomaly rules ([{rule: threshold|zscore|slope, series, ...}]);
    # None = the conservative defaults (timeline.DEFAULT_RULES: degraded
    # cycles, invariant violations, pending-depth zscore)
    timeline_rules: Optional[list] = None
    # --- queue-sharded scheduler replicas (ISSUE 14) ---
    # horizontal scale-out inside one process: run this many Scheduler
    # replicas (threads) over ONE cache/queue, each popping a stable
    # hash-shard of the PriorityQueue and dispatching against the SAME
    # resident snapshot generation, with commits sequenced through the
    # optimistic conflict reconciler (runtime/reconciler.py).  1 = the
    # classic single-loop scheduler bit-for-bit.  Consumed by
    # SchedulerReplicaSet (runtime/replicas.py) / cmd --replicas; an
    # individual Scheduler instance reads its own replica identity from
    # the replica_id/replica_of constructor args instead.
    replicas: int = 1
    # per-namespace placement quotas ({namespace: {resource: quantity}}):
    # committed usage beyond a namespace's quota is vetoed by the
    # reconciler at commit (the pod parks unschedulable with backoff).
    # None = no quotas.  Rides the encoder's per-namespace usage/quota
    # columns; also the DRF tiebreak's fairness substrate.
    namespace_quotas: Optional[dict] = None
    # multi-scheduler: only pods whose spec.schedulerName names THIS
    # scheduler enter its queue (eventhandlers.go responsibleForPod)
    scheduler_name: str = "default-scheduler"
    weights: Optional[Sequence[float]] = None
    filter_config: FilterConfig = field(default_factory=FilterConfig)
    profile: Optional[object] = None  # config.SchedulingProfile; overrides
                                      # filter_config/weights when set

    @staticmethod
    def from_component_config(cc, interner=None) -> "SchedulerConfig":
        """Build from a KubeSchedulerConfiguration (config/types.py)."""
        profile = cc.build_profile(interner=interner)
        return SchedulerConfig(
            batch_size=cc.batch_size,
            batch_window_s=cc.batch_window_s,
            engine=cc.engine,
            percentage_of_nodes_to_score=cc.percentage_of_nodes_to_score,
            disable_preemption=cc.disable_preemption,
            scheduler_name=cc.scheduler_name,
            weights=profile.weights_array(),
            filter_config=profile.filter_config,
            profile=profile,
            batched_commit=getattr(cc, "batched_commit", True),
            pipeline_commit=getattr(cc, "pipeline_commit", False),
            device_retry_max=getattr(cc, "device_retry_max", 2),
            device_backoff_base_s=getattr(cc, "device_backoff_base_s", 0.005),
            device_backoff_max_s=getattr(cc, "device_backoff_max_s", 0.05),
            device_backoff_jitter=getattr(cc, "device_backoff_jitter", 0.5),
            breaker_failure_threshold=getattr(
                cc, "breaker_failure_threshold", 3
            ),
            breaker_open_s=getattr(cc, "breaker_open_s", 0.05),
            cpu_fallback=getattr(cc, "cpu_fallback", True),
            queue_capacity=getattr(cc, "queue_capacity", None),
            adaptive_batch=getattr(cc, "adaptive_batch", False),
            batch_size_min=getattr(cc, "batch_size_min", 16),
            cycle_deadline_s=getattr(cc, "cycle_deadline_s", 0.0),
            trace_threshold_s=getattr(cc, "trace_threshold_s", 0.1),
            express_lane=getattr(cc, "express_lane", False),
            express_batch_size=getattr(cc, "express_batch_size", 64),
            express_priority_threshold=getattr(
                cc, "express_priority_threshold", None
            ),
            compile_cache_dir=getattr(cc, "compile_cache_dir", None),
            prewarm_widths=getattr(cc, "prewarm_widths", False),
            attribution=getattr(cc, "attribution", False),
            decision_ledger=getattr(cc, "decision_ledger", False),
            ledger_dir=getattr(cc, "ledger_dir", None),
            ledger_max_cycles=getattr(cc, "ledger_max_cycles", 4096),
            telemetry=getattr(cc, "telemetry", True),
            telemetry_interval_cycles=getattr(
                cc, "telemetry_interval_cycles", 1
            ),
            slo_objectives=getattr(cc, "slo_objectives", None),
            heartbeat_s=getattr(cc, "heartbeat_s", 0.0),
            shard_devices=getattr(cc, "shard_devices", 0),
            mesh_shape=getattr(cc, "mesh_shape", None),
            mesh_shrink=getattr(cc, "mesh_shrink", True),
            shard_breaker_failure_threshold=getattr(
                cc, "shard_breaker_failure_threshold", 2
            ),
            invariant_checks=getattr(cc, "invariant_checks", True),
            profile_dir=getattr(cc, "profile_dir", None),
            megacycle_batches=getattr(cc, "megacycle_batches", 1),
            quality_top_k=getattr(cc, "quality_top_k", 3),
            quality_interval_cycles=getattr(
                cc, "quality_interval_cycles", 32
            ),
            quality_drift_threshold=getattr(
                cc, "quality_drift_threshold", 0.25
            ),
            capacity_planner=getattr(cc, "capacity_planner", False),
            capacity_interval_cycles=getattr(
                cc, "capacity_interval_cycles", 256
            ),
            node_shape_catalog=getattr(cc, "node_shape_catalog", None),
            timeline=getattr(cc, "timeline", True),
            timeline_interval_s=getattr(cc, "timeline_interval_s", 1.0),
            timeline_retention=getattr(cc, "timeline_retention", 512),
            timeline_rules=getattr(cc, "timeline_rules", None),
            replicas=getattr(cc, "replicas", 1),
            namespace_quotas=getattr(cc, "namespace_quotas", None),
        )


def responsible_for(pod, scheduler) -> bool:
    """eventhandlers.go responsibleForPod: does this scheduler own the
    pod?  Shared by both event-wiring paths (runtime.cluster
    wire_scheduler and client.informer wire_scheduler_informers)."""
    my_name = getattr(getattr(scheduler, "config", None),
                      "scheduler_name", "default-scheduler")
    return (getattr(pod.spec, "scheduler_name", "default-scheduler")
            or "default-scheduler") == my_name


@dataclass
class ScheduleResult:
    pod: Pod
    node: Optional[str]          # None = unschedulable
    generation: int = 0


@dataclass
class _InFlight:
    """One dispatched-but-unfetched cycle: the double-buffer slot of the
    pipelined commit path.  `fetch` is the FETCH-IN-FLIGHT half: an
    AsyncFetch whose D2H copy was started the moment the winners buffer
    was dispatched (codec/transfer.py), materializing on a worker thread
    while the scheduling thread encodes/dispatches the next batch."""

    pods: List[Pod]
    hosts_dev: object            # device i32[B] winners buffer (None when
    #                              the cycle ran degraded on the CPU engine)
    fetch: object                # AsyncFetch (device) or _HostResult (CPU)
    generation: int
    cycle: int
    ext_failed: Dict[int, str]
    pc: object                   # shared PluginContext (framework cycles)
    t_cycle0: float
    trace: Span                  # the cycle's ROOT span (one trace id per
    #                              cycle, propagated to binds/extenders)
    # --- device-fault resilience ---
    # re-dispatch the SAME encoded batch (transient-retry path); None for
    # degraded cycles
    relaunch: Optional[Callable[[], Tuple[object, AsyncFetch]]] = None
    # compute this batch's winners on the CPU engine (degradation path);
    # returns a _HostResult
    cpu_fetch: Optional[Callable[[], "_HostResult"]] = None
    degraded: bool = False       # True once served by the CPU engine
    last_index0: int = 0         # selectHost rotation base for this batch
    tier: str = TIER_BULK        # latency tier this cycle serves: labels
    #                              the phase/e2e metrics and the span
    # --- attribution + decision ledger (ISSUE 7) ---
    attrib_dev: object = None    # device Attribution pytree (attribution
    #                              launches only; None when off/degraded)
    attrib: object = None        # host-materialized Attribution (set at
    #                              the commit fence)
    ledger_inputs: Optional[dict] = None  # the cycle's encode-time launch
    #                              inputs, stashed for the ledger record
    # --- telemetry (ISSUE 8) ---
    # host refs to the snapshot fields the analytics kernel reduces
    # (immutable by the encoder's cow contract): the fallback input when
    # the resident device buffers are unavailable (degraded cycles)
    telemetry_host: Optional[tuple] = None
    # the ENCODED batch width (batch.n_pods — the executable's padded
    # shape, NOT len(pods)): the launch-EWMA label, so the per-width
    # family tracks real executables instead of leaking a series per
    # raw pod count
    width: int = 0
    # --- performance observatory (ISSUE 11) ---
    # scheduling-thread seconds from encode start to the dispatch
    # returning (host_enqueue in the cost model)
    enqueue_s: float = 0.0
    # codec.transfer.transfer_totals() snapshot at encode time: the
    # commit tail diffs against it to get THIS cycle's wire traffic
    xfer0: Optional[dict] = None
    # --- device-resident megacycle (ISSUE 12) ---
    # (k, K) when this cycle is sub-batch k of a K-batch megacycle:
    # its winners came from one shared launch whose device window is
    # attributed 1/K to each sub-batch (span, perfobs, telemetry)
    mega: Optional[Tuple[int, int]] = None
    # --- placement-quality observatory (ISSUE 13) ---
    quality_dev: object = None   # device TopKQuality pytree (quality
    #                              launches only; None when off/degraded)
    quality: object = None       # host-materialized TopKQuality (set at
    #                              the commit fence, like attrib)
    # the encoded batch's request matrix (host ref) — the regret
    # counterfactual's pod-side input
    quality_reqs: object = None
    # the snapshot refs the regret counterfactual packs into — set ONLY
    # when they are genuinely THIS cycle's pre-dispatch state: every
    # single cycle, but only sub-batch 0 of a megacycle (windows k>0
    # placed against chained state the shared snapshot predates; FFD
    # against the emptier pre-megacycle capacity would overstate regret)
    quality_snapshot: Optional[tuple] = None
    # --- device-resident capacity planner (ISSUE 15) ---
    # the cycle's host (allocatable, requested, valid) refs for the
    # capacity solve (immutable by the encoder's cow contract) — kept
    # separate from telemetry_host/quality_snapshot so the planner
    # works whatever combination of observatories is enabled
    capacity_snapshot: Optional[tuple] = None
    # --- queue-sharded replicas (ISSUE 14) ---
    # the encoded batch's request matrix (host ref) when a conflict
    # reconciler is attached: the admission scan's pod-side input
    reqs: object = None
    # commit sequence number stamped by the reconciler (the "sequenced
    # winner" order; rides the ledger block for cross-replica audit)
    commit_seq: int = -1
    # encoder generation right after THIS cycle's state commit: a
    # megacycle propagates it to the next window's fence so chained
    # windows keep the zero-conflict fast path when no sibling
    # interleaved between sub-batch commits
    gen_after: int = -1


class _HostResult:
    """AsyncFetch-shaped handle for an already-materialized winners
    buffer (the degraded CPU-engine path, and the per-sub-batch slices
    of a fetched megacycle): never faults.  execute/materialize carry
    the reconstructed per-sub-batch share of a megacycle's one device
    window (0 for genuinely host-computed results)."""

    def __init__(self, hosts: np.ndarray, seconds: float = 0.0,
                 execute_seconds: float = 0.0,
                 materialize_seconds: float = 0.0):
        self._hosts = hosts
        self.seconds = seconds
        self.execute_seconds = execute_seconds
        self.materialize_seconds = materialize_seconds

    def ready(self) -> bool:
        return True

    def result(self) -> np.ndarray:
        return self._hosts


@dataclass
class _Staged:
    """A fetched cycle whose cache-STATE half (batched assume) has been
    applied; the side-effect tail (binds/events/metrics/preemption) is
    still pending and may overlap the next batch's device dispatch."""

    inf: _InFlight
    hosts: np.ndarray
    algo_dt: float
    batched: bool
    t_state0: float = 0.0
    state_seconds: float = 0.0
    # (batch index, pod, assumed copy, node name) per device winner
    winners: List[Tuple] = field(default_factory=list)
    fit_idx: List[int] = field(default_factory=list)
    # residual host wait at the ready fence (host_stall in the perf
    # observatory's cost model — the same window as the fetch_block
    # phase counter)
    stall_s: float = 0.0
    # THIS cycle's wire traffic (codec.transfer.transfer_delta vs the
    # encode-time watermark), taken at the commit fence — under
    # pipeline_commit the tail runs AFTER the next cycle's dispatch, so
    # computing the delta there would double-count the next cycle's
    # uploads into this cycle's span
    xfer_delta: Optional[dict] = None
    # (batch index, pod) losers of the optimistic cross-replica race
    # (ISSUE 14): their node headroom was spent by a sequenced-earlier
    # commit — the tail readds them to the owner shard (shed-exempt)
    race_lost: List[Tuple] = field(default_factory=list)
    # (batch index, pod) vetoed by a namespace quota: parked
    # unschedulable with backoff (spinning on a full quota helps nobody)
    quota_lost: List[Tuple] = field(default_factory=list)


@dataclass
class _MegaFlight:
    """One dispatched megacycle: K sub-batch _InFlight records sharing
    ONE launch (stacked i32[K, B] winners, one AsyncFetch, one relaunch
    closure).  The resilience stack treats it as one retryable unit —
    a classified fault at the fence relaunches the WHOLE megacycle with
    the same rotation bases, and giving up on the device serves the K
    batches sequentially from the CPU adapter, bit-identically
    (each sub-batch's state commit lands before the next one's adapter
    call, so the adapter sees exactly the chained state the scan saw)."""

    windows: List[_InFlight]
    hosts_dev: object
    fetch: object                # AsyncFetch of the stacked winners
    relaunch: Optional[Callable] = None
    t_cycle0: float = 0.0
    # stacked device TopKQuality ([K, B, ...] leaves) when the quality
    # seam is on; materialized at the fence and sliced per sub-batch
    quality_dev: object = None


class Scheduler:
    """Binder: callable (pod, node_name) -> bool (the POST .../binding analog,
    scheduler.go:411-435).  A False/raising binder triggers ForgetPod + requeue."""

    def __init__(
        self,
        cache: Optional[SchedulerCache] = None,
        queue: Optional[PriorityQueue] = None,
        binder: Optional[Callable[[Pod, str], bool]] = None,
        config: Optional[SchedulerConfig] = None,
        victim_deleter: Optional[Callable[[Pod], None]] = None,
        pdb_lister: Optional[Callable[[], List[PodDisruptionBudget]]] = None,
        framework=None,  # framework.v1alpha1.Framework; None = no plugins
        recorder: Optional[EventRecorder] = None,
        extenders: Optional[Sequence] = None,  # extender.client.HTTPExtender
        flight_recorder: Optional[FlightRecorder] = None,  # None = the
        #                       process-wide ring (flightrecorder.RECORDER)
        ledger=None,  # runtime/ledger.DecisionLedger; None = built from
        #               config.decision_ledger (and installed as the
        #               process default serving /debug/decisions)
        # --- queue-sharded replicas (ISSUE 14, runtime/replicas.py) ---
        replica_id: int = 0,     # this instance's replica index (= its
        #                          stable queue hash-shard)
        replica_of: int = 1,     # total replicas sharing the queue; 1 =
        #                          the classic single-loop scheduler
        reconciler=None,         # shared runtime/reconciler
        #                          .ConflictReconciler sequencing commits
        snapshot_hub=None,       # shared runtime/reconciler.SnapshotHub
        #                          (THE resident device snapshot; None =
        #                          this instance owns its own cache)
        share_engines_with=None,  # a sibling Scheduler whose compiled
        #                          engines/preempt-eval this one reuses
        #                          (replicas share executables — N
        #                          replicas must not pay N compiles)
    ):
        # NB: PriorityQueue defines __len__, so `queue or PriorityQueue()`
        # would silently replace an *empty* caller-owned queue
        self.cache = cache if cache is not None else SchedulerCache()
        self.config = config if config is not None else SchedulerConfig()
        self.queue = (
            queue if queue is not None
            else PriorityQueue(capacity=self.config.queue_capacity)
        )
        # shed audit trail: a bounded queue dropping a pod is operator-
        # visible (the FailedScheduling analog for overload); attach only
        # where no other owner wired one
        if getattr(self.queue, "on_shed", "n/a") is None:
            self.queue.on_shed = self._on_shed
        # latency-tier classifier (ISSUE 6): express_lane routes opted-in/
        # high-priority pods to the queue's express heap at admission.
        # Attach only where no other owner wired one (a caller-owned queue
        # keeps its own policy, exactly like on_shed/capacity).
        if (
            self.config.express_lane
            and getattr(self.queue, "tier_of", "n/a") is None
        ):
            self.queue.tier_of = self._tier_of
        # online invariant checker (ISSUE 10, runtime/invariants.py):
        # conservation of popped pods, no double-bind, capacity — fed
        # from the pop/bind/requeue/shed seams below.  The queue's
        # on_requeue observer funnels EVERY requeue path (unschedulable
        # verdicts, bind rollbacks, gang surplus readds, batch-loss
        # guards) through one hook.  The conservation/double-bind rules
        # are only SOUND when that seam is observable: a requeue the
        # checker never hears makes the next pop (or re-bind) read as a
        # false violation.  A caller-owned observer is chained; a
        # duck-typed queue without the hook disables the checker (with a
        # log line) rather than crying wolf on a healthy control plane.
        self.invariants = None
        if self.config.invariant_checks:
            if hasattr(self.queue, "on_requeue"):
                from kubernetes_tpu.runtime.invariants import InvariantChecker

                self.invariants = InvariantChecker(
                    on_violation=self._on_invariant_violation
                )
                prior = self.queue.on_requeue
                if prior is None:
                    self.queue.on_requeue = self.invariants.note_requeued
                else:
                    note = self.invariants.note_requeued

                    def _chained_requeue(pod, _prior=prior, _note=note):
                        _prior(pod)
                        _note(pod)

                    self.queue.on_requeue = _chained_requeue
            else:
                klog.infof(
                    "invariant checker disabled: queue %s has no "
                    "on_requeue seam to observe",
                    type(self.queue).__name__,
                )
        self.binder = binder if binder is not None else (lambda pod, node: True)
        # --- queue-sharded replicas (ISSUE 14) ---
        # replica identity (= the stable queue hash-shard this loop
        # drains), the shared sequenced reconciler, and the shared
        # snapshot hub.  replica_of == 1 with no hub/reconciler is the
        # classic single-loop scheduler bit-for-bit.
        self._replica_id = int(replica_id)
        self._replica_of = max(1, int(replica_of))
        self._reconciler = reconciler
        self._hub = snapshot_hub
        self.conflicts_total = 0        # race losers this replica requeued
        self.race_requeued_total = 0
        self.quota_vetoed_total = 0
        if self._reconciler is not None and not self.config.batched_commit:
            raise ValueError(
                "replica mode requires batched_commit: the conflict "
                "reconciler admits a cycle's winners as one sequenced "
                "critical section"
            )
        enc = self.cache.encoder
        prof = self.config.profile
        if prof is not None:
            self.config.filter_config = prof.filter_config
            self.config.weights = prof.weights_array()
        enc.hard_pod_affinity_weight = self.config.filter_config.hard_pod_affinity_weight
        self.config.filter_config = enc.adopt_filter_config(
            self.config.filter_config
        )
        self._unsched_key = enc.interner.intern(TAINT_NODE_UNSCHEDULABLE)
        # placement-quality top-k width (ISSUE 13): a STATIC output-only
        # engine flag — both engines (and the megacycle driver) return
        # the winner-pinned top-k + feasible counts alongside the
        # winners, placements bit-identical flag-on/off
        self._quality_k = max(0, int(self.config.quality_top_k))
        engine_kw = dict(
            cfg=self.config.filter_config,
            weights=self.config.weights,
            unsched_taint_key=self._unsched_key,
            zone_key_id=enc.getzone_key,
            score_cfg=prof.score_config if prof is not None else None,
            percentage_of_nodes_to_score=self.config.percentage_of_nodes_to_score,
            quality_topk=self._quality_k,
        )
        # attribution rides the sequential engine: the scan owns the
        # per-step state (resources/ports/affinity as committed so far)
        # the first-failure attribution is computed against.  The flag
        # itself is output-only (sequential winners are bit-identical
        # with it on or off, pinned by test); note that selecting the
        # sequential engine is itself semantics-preserving but can
        # rotate argmax TIES differently than the speculative engine.
        # replica siblings REUSE the first replica's compiled engines
        # (jitted callables are pure + thread-safe; N replicas paying N
        # identical XLA compiles would dwarf the scale-out win)
        self._shared_engines = share_engines_with is not None
        if self._shared_engines:
            self._schedule_fn = share_engines_with._schedule_fn
            self._preempt_eval = share_engines_with._preempt_eval
        else:
            self._schedule_fn = make_sequential_scheduler(
                **engine_kw, attribution=self.config.attribution
            )
            self._preempt_eval = make_preempt_eval(
                self.config.filter_config, self._unsched_key
            )
        # multi-chip sharding (config.shard_devices/mesh_shape): build the
        # node-axis Mesh ONCE at startup; every snapshot upload and engine
        # launch then carries NamedShardings and XLA inserts the
        # cross-shard collectives (no hand-written comms — the
        # parallel/mesh.py recipe, promoted from the bench-only harness)
        self.mesh = None
        mesh_spec_axis = None
        if self.config.shard_devices or self.config.mesh_shape:
            from kubernetes_tpu.parallel.mesh import build_mesh

            self.mesh, mesh_spec_axis = build_mesh(
                self.config.shard_devices or None, self.config.mesh_shape
            )
            # floor the node arena at the mesh size NOW: otherwise a
            # small fleet's arena (e.g. 64 rows under a 128-device mesh)
            # fails the divisibility check inside the fault-classified
            # dispatch path, where a static config error would read as a
            # device fault and flap the breaker into permanent CPU
            # degradation instead of failing at startup
            self.cache.encoder.ensure_node_capacity(self.mesh.size)
        # elastic degradation ladder (ISSUE 10): the STARTUP mesh is the
        # ladder's top rung — shrinks rebuild from it minus the lost
        # shards, the climb-back restores it whole.  ShardHealth is the
        # per-device breaker bank the fault attribution feeds.
        self._full_mesh = self.mesh
        self._full_spec_axis = mesh_spec_axis
        self._mesh_spec_axis = mesh_spec_axis
        # the compile-cache partition in use at startup (None = this
        # process never enabled one): a mesh rebuild re-points the cache
        # RELATIVE to this, and climb-back restores exactly it — whoever
        # enabled it (cmd/scheduler's topology tag, an embedded caller's
        # own convention, or nobody)
        self._startup_cache_dir = None
        if self.mesh is not None:
            import jax as _jax

            self._startup_cache_dir = getattr(
                _jax.config, "jax_compilation_cache_dir", None
            )
        self.shard_health = None
        if self.mesh is not None:
            from kubernetes_tpu.parallel.mesh import mesh_device_ids
            from kubernetes_tpu.runtime.health import ShardHealth

            self._mesh_ids = mesh_device_ids(self.mesh)
            self.shard_health = ShardHealth(
                device_ids=sorted(self._mesh_ids),
                failure_threshold=(
                    self.config.shard_breaker_failure_threshold
                ),
                open_duration_s=self.config.breaker_open_s,
                on_transition=self._on_shard_transition,
            )
        else:
            self._mesh_ids = None
        # incremental host->device snapshot upload: unchanged fields reuse
        # their resident device buffers between cycles (codec/transfer.py);
        # with a mesh, every node-axis field stays sharded across it and
        # dirty-row deltas scatter to the owning shard
        from kubernetes_tpu.codec.transfer import DeviceSnapshotCache

        self._dev_snapshot = DeviceSnapshotCache(
            mesh=self.mesh, spec_axis=mesh_spec_axis
        )
        m.MESH_WIDTH.set(float(self.mesh.size if self.mesh is not None else 0))
        if self._shared_engines:
            self._speculative_fn = share_engines_with._speculative_fn
        elif (
            self.config.engine == "speculative"
            and not self.config.attribution
        ):
            from kubernetes_tpu.models.speculative import (
                make_speculative_scheduler,
            )

            self._speculative_fn = make_speculative_scheduler(**engine_kw)
        else:
            self._speculative_fn = None
        # the engine that ACTUALLY serves device cycles (attribution
        # forces sequential whatever config.engine says): spans, ledger
        # records, and the replay header must all agree on this
        self._engine_kind = (
            "sequential" if self._speculative_fn is None else "speculative"
        )
        # device-resident megacycle (ISSUE 12): the K-batch scan driver
        # over the SAME engine impl the single-cycle path runs —
        # megacycle placements are chained-single-cycle placements by
        # construction.  Attribution cycles stay single (the per-pod
        # attribution pytree is a single-batch output shape).
        self._mega_fn = None
        if self._shared_engines:
            self._mega_fn = share_engines_with._mega_fn
        elif self.config.megacycle_batches > 1:
            if self.config.attribution:
                klog.infof(
                    "megacycleBatches=%d ignored: attribution cycles "
                    "dispatch single batches", self.config.megacycle_batches,
                )
            else:
                from kubernetes_tpu.models.megacycle import (
                    make_megacycle_scheduler,
                )

                self._mega_fn = make_megacycle_scheduler(
                    **engine_kw, engine=self._engine_kind
                )
        # effective megacycle depth (AIMD-steered like the batch width
        # when adaptive_batch is on; static = the configured cap)
        self._cur_mega = (
            1 if self.config.adaptive_batch
            else max(1, self.config.megacycle_batches)
        )
        self.megacycles_total = 0
        self.framework = framework
        # scheduler-side extender chain (core/extender.go; chained in config
        # order at generic_scheduler.go:527-554); built from the Policy's
        # "extenders" entries when not injected directly
        if extenders is None and prof is not None and prof.extender_configs:
            from kubernetes_tpu.extender.client import HTTPExtender

            extenders = [HTTPExtender(c) for c in prof.extender_configs]
        self.extenders = list(extenders or [])
        # "Scheduled"/"FailedScheduling"/"Preempted" audit trail
        # (tools/record; scheduler.go:268,433,325); wire_scheduler replaces a
        # defaulted recorder with the cluster's shared one
        self._recorder_defaulted = recorder is None
        self.recorder = recorder if recorder is not None else EventRecorder()
        # PodPreemptor.DeletePod analog (scheduler.go:319-326); default
        # removes the victim straight from the cache
        self._victim_deleter_defaulted = victim_deleter is None
        self.victim_deleter = victim_deleter or (lambda pod: self.cache.remove_pod(pod))
        self._pdb_defaulted = pdb_lister is None
        self.pdb_lister = pdb_lister or (lambda: [])
        self._last_index = 0
        # AIMD adaptive batch sizing (config.adaptive_batch): the CURRENT
        # cycle width, starting at the baseline batch_size_min and steered
        # by _adapt_batch after every non-empty cycle
        self._cur_batch = (
            max(1, self.config.batch_size_min)
            if self.config.adaptive_batch
            else self.config.batch_size
        )
        self._stop = threading.Event()
        # device-fault resilience: classified retry/backoff + circuit
        # breaker (runtime/health.py) + CPU-engine degradation
        # (cpuref/adapter.py, built lazily on first degraded cycle)
        self.device_health = DeviceHealth(
            failure_threshold=self.config.breaker_failure_threshold,
            open_duration_s=self.config.breaker_open_s,
            backoff_base_s=self.config.device_backoff_base_s,
            backoff_max_s=self.config.device_backoff_max_s,
            backoff_jitter=self.config.device_backoff_jitter,
            on_transition=self._on_breaker_transition,
        )
        self._cpu_engine = None
        # double-buffer slot for pipeline_commit: at most one dispatched
        # batch whose host tail has not run yet
        self._in_flight: Optional[_InFlight] = None
        # the most recently dispatched cycle's root span: what a
        # postmortem attaches as in_flight when an anomaly fires before
        # that cycle retires into the flight-recorder ring
        self._cur_span: Optional[Span] = None
        # latency tier of the most recently dispatched cycle — joins the
        # in-flight span in postmortem snapshots (an express-cycle anomaly
        # reads differently from a bulk one)
        self._cur_tier: str = TIER_BULK
        # per-phase seconds, cumulative (bench live-path reporting):
        # pop (queue drain — under pipeline_commit this overlaps the
        # previous batch's in-flight fetch), encode (host tensors +
        # snapshot), dispatch (async enqueue), fetch (device compute +
        # D2H, measured on the async-fetch worker — overlaps other
        # phases), host_stall (residual host wait at the ready-fence —
        # the perf observatory's name for the same window; a SUBSET of
        # fetch, so phase sums must skip it; "fetch_block" is kept as a
        # legacy ALIAS that moves in lockstep so /debug/perf and
        # detail.phases reconcile exactly, ISSUE 12 satellite), commit
        # (assume + bind + events + requeues), preempt
        self.phase_seconds: Dict[str, float] = {
            "pop": 0.0, "encode": 0.0, "dispatch": 0.0, "fetch": 0.0,
            "host_stall": 0.0, "fetch_block": 0.0, "commit": 0.0,
            "preempt": 0.0,
        }
        # always-on cycle-span ring + anomaly postmortems (ISSUE 5); the
        # default is the process-wide recorder served at /debug/traces
        self.flight_recorder = (
            flight_recorder if flight_recorder is not None else RECORDER
        )
        # decision ledger (ISSUE 7): opt-in per-cycle record + the
        # /debug/decisions ring.  A config-built ledger installs itself
        # as the process default (the RECORDER pattern) so the debug
        # endpoints serve it without extra wiring.
        self.ledger = ledger
        if self.ledger is None and self.config.decision_ledger:
            import os

            from kubernetes_tpu.runtime import ledger as ledger_mod

            path = None
            if self.config.ledger_dir:
                os.makedirs(self.config.ledger_dir, exist_ok=True)
                path = os.path.join(
                    self.config.ledger_dir, "decisions.ledger"
                )
            self.ledger = ledger_mod.DecisionLedger(
                path=path, max_cycles=self.config.ledger_max_cycles
            )
            ledger_mod.set_default(self.ledger, replica=self._replica_id)
        if self.ledger is not None:
            self.ledger.ensure_meta(self._engine_meta())
        # cluster + device telemetry (ISSUE 8): analytics side-launches,
        # HBM/compile/launch-EWMA runtime facts, SLO burn-rate alerting.
        # A config-built hub installs itself as the process default (the
        # RECORDER pattern) so /debug/cluster serves it unwired.
        self.telemetry = None
        if self.config.telemetry:
            from kubernetes_tpu.runtime import telemetry as telemetry_mod

            self.telemetry = telemetry_mod.TelemetryHub(
                interval_cycles=self.config.telemetry_interval_cycles,
                objectives=telemetry_mod.build_objectives(
                    self.config.slo_objectives
                ),
                postmortem=self._postmortem,
            )
            telemetry_mod.set_default(self.telemetry, replica=self._replica_id)
        # performance observatory (ISSUE 11, runtime/perfobs.py):
        # host/device time attribution per cycle, the phase x width
        # EWMA cost matrix, per-cycle transfer deltas, and the
        # on-demand profiler capture — always-on (dict ops per cycle;
        # the <2% budget is pinned by perf_smoke), installed as the
        # process default so /debug/perf serves it unwired
        from kubernetes_tpu.runtime import perfobs as perfobs_mod

        self.perfobs = perfobs_mod.PerfObservatory(
            profile_dir=self.config.profile_dir
        )
        perfobs_mod.set_default(self.perfobs, replica=self._replica_id)
        # placement-quality observatory (ISSUE 13, runtime/quality.py):
        # per-decision margin/feasible records off the engines' in-launch
        # top-k, amortized FFD-counterfactual regret, dual-window
        # packing-drift alerts through the postmortem seam — always-on
        # like telemetry/perfobs (<2% budget pinned by perf_smoke),
        # installed as the process default so /debug/quality serves it
        self.quality = None
        if self._quality_k > 0:
            from kubernetes_tpu.runtime import quality as quality_mod

            self.quality = quality_mod.QualityObservatory(
                top_k=self._quality_k,
                interval_cycles=self.config.quality_interval_cycles,
                postmortem=self._postmortem,
                drift_threshold=self.config.quality_drift_threshold,
            )
            quality_mod.set_default(self.quality, replica=self._replica_id)
        # device-resident capacity planner (ISSUE 15, runtime/capacity.py):
        # every capacityIntervalCycles the pending+unschedulable backlog
        # is class-compressed and what-if binpacked — existing headroom
        # first, overflow over the node-shape catalog — as an amortized
        # side-launch behind the loop (the telemetry discipline; the <2%
        # budget pinned by perf_smoke), emitting a scale-up/scale-down
        # recommendation at /debug/capacity.  Placements are
        # bit-identical planner on/off (purely observational; pinned by
        # tests/test_capacity.py).  The mesh is read through a getter at
        # dispatch time so the elastic ladder's shrinks/rebuilds are
        # always honored.
        self.capacity = None
        if self.config.capacity_planner:
            from kubernetes_tpu.runtime import capacity as capacity_mod

            self.capacity = capacity_mod.CapacityPlanner(
                catalog=self.config.node_shape_catalog,
                interval_cycles=self.config.capacity_interval_cycles,
                mesh=lambda: self.mesh,
            )
            capacity_mod.set_default(
                self.capacity, replica=self._replica_id
            )
        # metrics timeline store (ISSUE 20, runtime/timeline.py): every
        # registered metric family sampled once per timelineInterval
        # (counters as deltas, gauges as values, histograms as p50/p99)
        # into a bounded ring, interleaved with typed event annotations
        # from the breaker/shard/mesh/AIMD/shed/invariant seams, and run
        # through the online anomaly detector (edge-triggered rules ->
        # scheduler_timeline_anomalies_total + a flight-recorder
        # postmortem).  The hook rides the commit tail AND the idle
        # heartbeat path so quiet loops keep sampling; the <2% budget is
        # pinned by perf_smoke.  Installed as the process default so
        # /debug/timeline serves it unwired.
        self.timeline = None
        if self.config.timeline:
            from kubernetes_tpu.runtime import timeline as timeline_mod

            self.timeline = timeline_mod.TimelineStore(
                interval_s=self.config.timeline_interval_s,
                retention=self.config.timeline_retention,
                detector=timeline_mod.AnomalyDetector(
                    rules=self.config.timeline_rules,
                    postmortem=self._postmortem,
                ),
            )
            timeline_mod.set_default(self.timeline, replica=self._replica_id)
        # shed watermark (per-cycle deltas feed the goodput SLO) +
        # heartbeat clock + liveness totals (heartbeat line + bench)
        self._shed_seen = 0
        self._last_heartbeat = time.monotonic()
        self._outcome_totals = {"placed": 0, "unschedulable": 0}
        self.results: List[ScheduleResult] = []
        # (preemptor key, node name, victim keys) per successful preemption
        self.preemptions: List[Tuple[Tuple[str, str], str, List[Tuple[str, str]]]] = []
        # per-namespace placement quotas (ISSUE 14): seed the encoder's
        # quota columns before the first commit can consult them
        for ns, q in (self.config.namespace_quotas or {}).items():
            enc.set_namespace_quota(ns, q)
        # replica registry (ISSUE 14): GET /debug/replicas rolls every
        # registered scheduler into the process aggregate — the explicit
        # cross-replica roll-up next to the per-replica default installs
        from kubernetes_tpu.runtime import reconciler as reconciler_mod

        reconciler_mod.register_scheduler(self)
        m.REPLICAS.set(float(self._replica_of))

    def attach_hub(self, hub) -> None:
        """Late-bind the shared SnapshotHub (the ReplicaSet builds the
        hub FROM replica 0's DeviceSnapshotCache, then attaches it).
        Only valid before any cycle dispatched."""
        self._hub = hub

    def _engine_meta(self) -> dict:
        """The ledger header: everything a fresh process needs to rebuild
        a bit-identical engine for replay (runtime/ledger.build_replay_fn)."""
        from kubernetes_tpu.runtime.ledger import engine_meta

        prof = self.config.profile
        return engine_meta(
            self.config.filter_config,
            self.config.weights,
            self._unsched_key,
            self.cache.encoder.getzone_key,
            prof.score_config if prof is not None else None,
            self.config.percentage_of_nodes_to_score,
            self._engine_kind,
        )

    # ------------------------------------------------------------- one cycle

    def schedule_cycle(self, pods: Sequence[Pod],
                       tier: str = TIER_BULK) -> List[ScheduleResult]:
        """Place a batch of pods against the current cache state; assume+bind
        winners, requeue losers.  Returns per-pod results.

        Internally split into encode/dispatch -> state-commit -> tail so
        the pipelined run loop (config.pipeline_commit) can overlap batch
        k's tail with batch k+1's device dispatch; called directly it is
        strictly synchronous (any in-flight pipelined batch is drained
        first so cycles never interleave).  `tier` labels the cycle's
        metrics/span and — for TIER_EXPRESS — pins the express encode
        width; placement semantics are tier-independent."""
        self.flush_pipeline()
        try:
            # climb-back check between cycles (cheap no-op while no shard
            # is lost): runs with the pipeline drained so a mesh swap
            # never races an in-flight batch.  INSIDE the batch-loss
            # guard: an unclassified probe error (a real runtime's
            # device_put can raise anything) must requeue the
            # already-popped batch, not drop it
            self._maybe_probe_shards()
            inf = self._encode_and_dispatch(pods, tier=tier)
        except BaseException:
            # popped pods must never be lost: a fault that escaped the
            # classified-retry/degrade machinery (or a plain bug) still
            # leaves the batch schedulable later
            self.queue.add_unschedulable_batch(
                list(pods), self.queue.scheduling_cycle
            )
            raise
        if inf is None:
            return []
        return self._commit_tail(self._commit_state_or_requeue(inf))

    def _commit_state_or_requeue(self, inf: _InFlight) -> _Staged:
        """The resilient fence with the batch-loss guard: classified
        device faults retry/degrade inside _commit_state_resilient; if
        even that fails (unclassified error, or cpu_fallback disabled),
        the batch's pods — already popped from the queue — are requeued
        ALL (plain error requeue, the extender-error discipline) before
        propagating, so a device fault degrades to a retry instead of the
        batch staying Pending forever."""
        try:
            return self._commit_state_resilient(inf)
        except BaseException as e:
            self.queue.add_unschedulable_batch(inf.pods, inf.cycle)
            # the failing cycle's span retires into the ring FIRST so the
            # postmortem snapshot below contains it
            inf.trace.annotate(error=f"{type(e).__name__}: {e}")
            inf.trace.finish()
            self.flight_recorder.record(inf.trace)
            if classify_device_error(e) is None:
                # an error that escaped the classified machinery is by
                # definition the case nobody predicted: snapshot the ring
                self._postmortem(
                    "unclassified_error", f"{type(e).__name__}: {e}"
                )
            raise

    # -------------------------------------------------- tracing/postmortems

    def _phase(self, name: str, dt: float, tier: str = TIER_BULK) -> None:
        """One accumulation point for per-phase seconds: the driver-
        visible phase_seconds dict (bench reporting, tiers aggregated)
        AND the tier-labeled /metrics counter family move together.
        "host_stall" (the perfobs vocabulary for the fence wait) also
        feeds the legacy "fetch_block" alias, so the two dict entries
        can never drift — the metric family carries only host_stall."""
        self.phase_seconds[name] += dt
        if name == "host_stall":
            self.phase_seconds["fetch_block"] += dt
        m.CYCLE_PHASE_SECONDS.inc(dt, phase=name, tier=tier)

    def _postmortem(self, trigger: str, detail: str = "") -> None:
        """Dump a flight-recorder postmortem for one anomaly trigger
        (throttled per trigger inside the recorder): the last N cycle
        spans + the CURRENT cycle's in-flight span (a breaker trips
        mid-cycle, before that span retires into the ring) + queue/
        breaker/AIMD state + the metrics registry text.  State and
        metrics are passed as THUNKS: a shed storm hits this once per
        dropped pod, and throttled calls must cost ~nothing."""
        snap = self.flight_recorder.postmortem(
            trigger, detail,
            state=self._postmortem_state,
            metrics_text=m.REGISTRY.expose,
            in_flight=[self._cur_span] if self._cur_span is not None else None,
        )
        # a fired postmortem is also a timeline annotation — riding the
        # recorder's per-trigger throttle (snap is None inside the
        # window), so a shed storm marks the timeline once, not once per
        # pod.  The anomaly detector's own firings already annotate
        # kind="anomaly" inside maybe_sample — don't double-mark those.
        if snap is not None and not trigger.startswith("anomaly_"):
            self._annotate("postmortem", f"{trigger}: {detail}",
                           trigger=trigger)

    def _annotate(self, kind: str, detail: str = "", **fields) -> None:
        """Push one typed event onto the timeline store (no-op when the
        timeline is off).  Annotation must never break the loop."""
        tl = getattr(self, "timeline", None)  # None mid-__init__ too
        if tl is None:
            return
        try:
            tl.annotate(kind, detail, **fields)
        except Exception as e:  # pragma: no cover - defensive
            klog.errorf("timeline annotate failed: %s", e)

    def _postmortem_state(self) -> dict:
        """Point-in-time control-plane state for a postmortem snapshot —
        the numbers an operator reaches for first in an incident."""
        q = self.queue
        return {
            "queue_depth": len(q),
            "active_depth": (
                q.active_depth() if hasattr(q, "active_depth") else None
            ),
            "queue_capacity": getattr(q, "capacity", None),
            "shed_total": getattr(q, "shed_total", 0),
            "breaker": self.device_health.state,
            "consecutive_failures": self.device_health.consecutive_failures,
            "fault_counts": dict(self.device_health.fault_counts),
            # elastic-ladder facts: the rung + shard states a postmortem
            # reader joins against the fault class/shard on the span
            "mesh_width": self.mesh.size if self.mesh is not None else 0,
            "ladder_rung": self.ladder_rung,
            "shard_breakers": (
                {str(k): v for k, v in self.shard_health.states().items()}
                if self.shard_health is not None else None
            ),
            "invariants": (
                self.invariants.summary()
                if self.invariants is not None else None
            ),
            "adaptive_batch": self._cur_batch,
            "megacycle_depth": self._cur_mega,
            "megacycles_total": self.megacycles_total,
            "pipeline_pending": self.pipeline_pending,
            "scheduling_cycle": self.queue.scheduling_cycle,
            # latency tier of the most recently dispatched cycle — pairs
            # with the in_flight span in the postmortem
            "tier": self._cur_tier,
            "express_depth": (
                self.queue.express_depth()
                if hasattr(self.queue, "express_depth") else None
            ),
        }

    # ------------------------------------------ resident-snapshot seams
    #
    # Every touch of the resident device snapshot goes through these
    # three, so replica mode (ISSUE 14) can swap in the SHARED
    # SnapshotHub without the call sites caring: the hub re-snapshots
    # under the cache lock on every update (a retry can therefore never
    # scatter stale rows over a sibling replica's newer upload), while
    # classic mode keeps this instance's own DeviceSnapshotCache and
    # its incremental dirty-row contract bit-for-bit.

    def _device_update(self, cluster, dirty_rows):
        if self._hub is not None:
            return self._hub.refresh()[2]
        return self._dev_snapshot.update(cluster, dirty_rows=dirty_rows)

    def _device_invalidate(self) -> None:
        if self._hub is not None:
            self._hub.invalidate()
        else:
            self._dev_snapshot.invalidate()

    def _device_resident(self, fields):
        if self._hub is not None:
            return self._hub.resident(fields)
        return self._dev_snapshot.resident(fields)

    # ----------------------------------------------- device-fault handling

    @property
    def cpu_engine(self):
        """Lazy CpuEngineAdapter (cpuref/adapter.py): the degraded-mode
        engine serving cycles while the device breaker is open."""
        if self._cpu_engine is None:
            from kubernetes_tpu.cpuref.adapter import CpuEngineAdapter

            self._cpu_engine = CpuEngineAdapter(self.cache, self.config)
        return self._cpu_engine

    def _on_breaker_transition(self, frm: str, to: str) -> None:
        """Breaker transitions are operator-visible: one Event each (the
        audit trail the failure-mode table in README documents)."""
        reason = {
            "open": "BreakerOpen",
            "half_open": "BreakerHalfOpen",
            "closed": "BreakerClosed",
        }[to]
        self.recorder.eventf(
            "Scheduler", "", self.config.scheduler_name,
            EVENT_TYPE_WARNING if to == "open" else EVENT_TYPE_NORMAL,
            reason,
            "device breaker %s -> %s (consecutive failures: %d)",
            frm, to, self.device_health.consecutive_failures,
        )
        if to == "open":
            self._postmortem("breaker_open", f"{frm} -> {to}")
        self._annotate("breaker", f"{frm} -> {to}", to=to)
        m.LADDER_RUNG.set(float(self.RUNG_GAUGE[self.ladder_rung]))

    # ----------------------------------------- elastic degradation ladder
    #
    # full mesh -> shrunken mesh (widest pow2 of survivors) -> single
    # chip (a 1-device mesh) -> CPU adapter.  Shard-ATTRIBUTED faults
    # (codec/faults.fault_device_index) feed the per-shard breaker bank;
    # a shard's breaker tripping rebuilds the mesh without it instead of
    # tripping the global breaker, the in-flight batch is served
    # bit-identically by the CPU engine for the one gap cycle, and the
    # half-open canary probes the LOST device to climb back up.
    # Unattributed faults keep the PR 3 whole-mesh policy.

    RUNG_FULL = "full_mesh"
    RUNG_SHRUNKEN = "shrunken_mesh"
    RUNG_SINGLE = "single_chip"
    RUNG_CPU = "cpu"
    RUNG_GAUGE = {RUNG_FULL: 0, RUNG_SHRUNKEN: 1, RUNG_SINGLE: 2,
                  RUNG_CPU: 3}

    @property
    def ladder_rung(self) -> str:
        """Which rung currently serves cycles.  The global breaker wins
        (open/half-open = the device path as a whole is untrusted); an
        unsharded scheduler's healthy rung is single_chip."""
        if self.config.cpu_fallback and not self.device_health.device_available:
            return self.RUNG_CPU
        if self.mesh is None or self.mesh.size == 1:
            return self.RUNG_SINGLE
        if (
            self._full_mesh is not None
            and self.mesh.size < self._full_mesh.size
        ):
            return self.RUNG_SHRUNKEN
        return self.RUNG_FULL

    def _on_shard_transition(self, shard: int, frm: str, to: str) -> None:
        """Shard-breaker transitions are operator-visible, like the
        global breaker's (the per-shard rows in the README failure
        table)."""
        reason = {
            "open": "ShardBreakerOpen",
            "half_open": "ShardBreakerHalfOpen",
            "closed": "ShardBreakerClosed",
        }[to]
        self.recorder.eventf(
            "Scheduler", "", self.config.scheduler_name,
            EVENT_TYPE_WARNING if to == "open" else EVENT_TYPE_NORMAL,
            reason,
            "device shard %d breaker %s -> %s", shard, frm, to,
        )
        self._annotate("shard_breaker", f"shard {shard}: {frm} -> {to}",
                       shard=shard, to=to)

    def _on_invariant_violation(self, rule: str, detail: str) -> None:
        """An invariant violation is the anomaly class the flight
        recorder exists for: the control plane's own accounting broke."""
        self.recorder.eventf(
            "Scheduler", "", self.config.scheduler_name,
            EVENT_TYPE_WARNING, "InvariantViolation",
            "%s: %s", rule, detail,
        )
        self._postmortem("invariant_violation", f"{rule}: {detail}")

    def _shard_of(self, err: BaseException) -> Optional[int]:
        """Which shard (device id of the STARTUP mesh) a classified fault
        blames, or None for whole-mesh attribution.  Only ids the shard
        bank tracks count — a foreign id from a message pattern must not
        grow the bank."""
        if self.shard_health is None:
            return None
        idx = device_faults.fault_device_index(err)
        if idx is None or idx not in self.shard_health._state:
            return None
        return idx

    def _note_shard_fault(self, shard: Optional[int], fc: str) -> bool:
        """Feed one shard-attributed fault to the ladder.  Returns True
        when the fault was ABSORBED by a mesh shrink (the caller then
        serves the in-flight batch degraded and skips the global breaker
        accounting); False routes the fault to the whole-mesh policy —
        unattributed faults, shrink disabled, faults below the shard
        threshold (global transient retry still applies), and repeat
        faults on an already-lost shard (so a wrong rebuild cannot loop:
        the global breaker eventually trips)."""
        if (
            shard is None
            or self.shard_health is None
            or not self.config.mesh_shrink
        ):
            return False
        newly_lost = self.shard_health.record_failure(shard, fc)
        if not newly_lost:
            return False
        self._rebuild_mesh(
            direction="shrink",
            reason=f"shard {shard} lost ({fc})",
        )
        return True

    def _rebuild_mesh(self, direction: str, reason: str) -> None:
        """Rebuild the live mesh from the startup mesh minus the
        currently-lost shards (the widest valid sub-mesh), swap in a
        FRESH DeviceSnapshotCache (the invalidate seam: the next cycle's
        update() re-uploads the host-truth snapshot sharded onto the new
        mesh), and re-partition the compile-cache topology tag.  Runs on
        the scheduling thread only (the _dev_snapshot single-thread
        invariant)."""
        from kubernetes_tpu.codec.transfer import DeviceSnapshotCache
        from kubernetes_tpu.parallel.mesh import (
            mesh_device_ids,
            rebuild_without,
        )

        lost = self.shard_health.lost() if self.shard_health else frozenset()
        if lost:
            new_mesh, axis = rebuild_without(self._full_mesh, lost)
        else:
            new_mesh, axis = self._full_mesh, self._full_spec_axis
        self.mesh = new_mesh
        self._mesh_spec_axis = axis
        self._mesh_ids = mesh_device_ids(new_mesh) if new_mesh else None
        self._dev_snapshot = DeviceSnapshotCache(
            mesh=new_mesh, spec_axis=axis
        )
        self._retag_compile_cache()
        width = new_mesh.size if new_mesh is not None else 0
        m.MESH_WIDTH.set(float(width))
        m.MESH_REBUILDS.inc(direction=direction)
        m.LADDER_RUNG.set(float(self.RUNG_GAUGE[self.ladder_rung]))
        full = self._full_mesh.size if self._full_mesh is not None else 0
        klog.errorf(
            "mesh %s: %s -> serving from %d/%d devices (rung %s)",
            direction, reason, width, full, self.ladder_rung,
        )
        self.recorder.eventf(
            "Scheduler", "", self.config.scheduler_name,
            EVENT_TYPE_WARNING if direction == "shrink"
            else EVENT_TYPE_NORMAL,
            "MeshShrunk" if direction == "shrink" else "MeshRestored",
            "%s: live mesh now %d/%d devices (%s)",
            reason, width, full, self.ladder_rung,
        )
        if direction == "shrink":
            self._postmortem("mesh_shrink", reason)
        self._annotate(
            "mesh_rebuild", f"{direction}: {reason} ({width}/{full})",
            direction=direction, width=width,
        )

    def _retag_compile_cache(self) -> None:
        """Re-point the persistent compile cache at a partition for the
        CURRENT mesh width: a shrunken mesh's executables (new input
        shardings = new programs) must neither overwrite nor be served
        from the full-mesh partition.  Only when THIS process had a
        cache enabled at startup (recorded with the mesh) — a rebuild
        must never silently turn on disk caching nobody configured —
        and the shrink partition derives from that recorded directory,
        so climb-back restores the exact startup partition whatever
        convention enabled it (cmd/scheduler's topology tag, an
        embedded caller's own)."""
        base = self._startup_cache_dir
        if base is None:
            return
        if (
            self._full_mesh is not None
            and self.mesh is not None
            and self.mesh.size == self._full_mesh.size
        ):
            d = base  # back on the startup mesh: the startup partition
        else:
            width = self.mesh.size if self.mesh is not None else 1
            d = f"{base}-shrink{width}"
        try:
            import os

            import jax

            os.makedirs(d, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", d)
        except Exception as e:  # noqa: BLE001 — a cache misconfiguration
            # must never block a mesh rebuild mid-incident
            klog.errorf("compile-cache retag failed: %s", e)

    def _maybe_probe_shards(self) -> None:
        """The climb-back: once per lost shard whose cool-down elapsed,
        probe THE LOST DEVICE (not the surviving mesh — the scheduling
        cycles already canary that) and restore the widest mesh when it
        answers.  Runs on the scheduling thread between cycles, so mesh
        swaps never race a dispatch."""
        sh = self.shard_health
        if sh is None or not sh.lost():
            return
        if self._in_flight is not None:
            # a dispatched batch still references the current mesh's
            # buffers/closures: let it land first (its commit path calls
            # back here via schedule_cycle/run_once soon enough)
            return
        recovered = False
        for d in sorted(sh.lost()):
            if not sh.probe_due(d):
                continue
            try:
                self._probe_shard(d)
            except BaseException as e:
                fc = classify_device_error(e)
                if fc is None:
                    raise
                # failed canary: the shard re-opens and the cool-down
                # restarts (record_failure in HALF_OPEN always re-opens)
                sh.record_failure(d, fc)
                continue
            sh.record_success(d)
            recovered = True
        if recovered:
            self._rebuild_mesh(
                direction="restore",
                reason="lost shard recovered (half-open probe passed)",
            )

    def _probe_shard(self, shard: int) -> None:
        """One canary round-trip against a single (lost) device: the
        injection seams fire for exactly this device, and on real
        hardware a put+fetch raises the runtime's device-lost error while
        the chip is gone.  Any classified error = still lost."""
        device_faults.check(
            device_faults.SITE_DISPATCH, devices=(shard,)
        )
        dev = None
        if self._full_mesh is not None:
            dev = next(
                (d for d in np.asarray(self._full_mesh.devices).ravel()
                 if int(getattr(d, "id", -1)) == shard),
                None,
            )
        if dev is not None:
            buf = jax.device_put(np.zeros(8, np.float32), dev)
            device_faults.check(
                device_faults.SITE_FENCE, devices=(shard,)
            )
            np.asarray(buf)

    def _on_shed(self, pod: Pod, reason: str) -> None:
        """Bounded-queue shed audit (runtime/queue.py on_shed): one
        Warning event per dropped pod, mirroring the FailedScheduling
        trail (the metric lives with the queue)."""
        if self.invariants is not None:
            self.invariants.note_shed(pod)
        self.recorder.eventf(
            "Pod", pod.namespace, pod.name,
            EVENT_TYPE_WARNING, "SchedulingQueueFull",
            "pod shed from the scheduling queue (%s, capacity %s)",
            reason, self.queue.capacity,
        )
        # per-trigger throttling in the recorder turns a storm of sheds
        # into ONE postmortem capturing the lead-up, not one per pod
        self._postmortem("shed_burst", reason)

    def _adapt_batch(self, cycle_s: float) -> None:
        """AIMD batch-size update, once per non-empty cycle: halve on a
        deadline overrun (multiplicative decrease — latency wins), grow
        by +batch_size_min while the active queue outpaces the current
        width (additive increase — pressure converts into wider device
        launches), decay by halving once depth falls away (the batch
        returns to baseline after a storm, so post-overload cycles keep
        the low-latency shape)."""
        cfg = self.config
        if not cfg.adaptive_batch:
            return
        floor = max(1, cfg.batch_size_min)
        cur = self._cur_batch
        mega = self._cur_mega
        if cfg.cycle_deadline_s > 0 and cycle_s > cfg.cycle_deadline_s:
            m.CYCLE_DEADLINE_EXCEEDED.inc()
            self._postmortem(
                "cycle_deadline",
                f"cycle took {cycle_s:.3f}s > {cfg.cycle_deadline_s:.3f}s "
                f"budget (batch {cur})",
            )
            cur = max(floor, cur // 2)
            # latency overruns shed megacycle depth first too: the K-deep
            # launch is the coarsest-grained unit of committed work
            mega = max(1, mega // 2)
        else:
            depth = self.queue.active_depth()
            if depth > cur:
                cur = min(cfg.batch_size, cur + floor)
            elif depth <= cur // 2:
                cur = max(floor, cur // 2)
            # megacycle depth grows only once the width is saturated
            # (pressure converts into wider launches before deeper ones)
            # and decays with the backlog, in pow2 steps so every served
            # K is a prewarm-able ladder shape
            if cfg.megacycle_batches > 1:
                if cur >= cfg.batch_size and depth > cur * mega:
                    mega = min(cfg.megacycle_batches, mega * 2)
                elif depth <= cur * mega // 2:
                    mega = max(1, mega // 2)
        if cur != self._cur_batch:
            self._annotate(
                "aimd_resize", f"batch {self._cur_batch} -> {cur}",
                batch=cur,
            )
        self._cur_batch = cur
        if cfg.megacycle_batches > 1:
            self._cur_mega = mega
            m.MEGACYCLE_DEPTH.set(float(mega))
        m.ADAPTIVE_BATCH.set(float(cur))

    def _note_device_fault(self, fault_class: str, err: BaseException,
                           phase: str) -> None:
        klog.errorf(
            "device fault (%s) at %s: %s", fault_class, phase, err
        )
        self.recorder.eventf(
            "Scheduler", "", self.config.scheduler_name,
            EVENT_TYPE_WARNING, "DeviceFault",
            "%s device fault at %s: %s", fault_class, phase, err,
        )

    def _degrade_fetch(self, inf: _InFlight) -> None:
        """Serve an in-flight batch from the CPU engine: swap its fetch
        handle for a host-computed result and mark the cycle degraded."""
        inf.fetch = inf.cpu_fetch()
        inf.degraded = True
        # the CPU engine carries no attribution or quality seam, and the
        # device pytrees may belong to the failed launch
        inf.attrib_dev = None
        inf.quality_dev = None
        inf.quality = None
        # overwrite the dispatch-time attrs: the placements this cycle
        # commits came from the CPU engine, whatever was launched first
        inf.trace.annotate(degraded=True, engine="cpu")
        m.DEGRADED_CYCLES.inc()
        self._postmortem("degraded_cycle", "fence gave up on the device")

    def _fault_retry_allowed(
        self, fc: str, attempt: int, can_relaunch: bool = True,
        shard: Optional[int] = None,
    ) -> bool:
        """THE retry policy, shared by the dispatch and fence wrappers:
        account the classified failure, and decide whether one more
        same-batch attempt is allowed (counting the retry metric and
        sleeping the jittered backoff when it is).  On False the device
        has been given up on FOR THIS BATCH — the resident snapshot
        buffers are invalidated (a partial upload may have landed) and
        the caller degrades or raises.

        Shard-attributed faults (`shard` = a startup-mesh device id) try
        the elastic ladder first: a fault that LOSES the shard rebuilds
        the mesh without it and returns False without touching the
        global breaker — the next cycle dispatches on the shrunken mesh
        while only this batch rides the CPU adapter.  Shard faults below
        the shard threshold fall through to the global policy (same-
        batch transient retry), as do unattributed faults."""
        if self._note_shard_fault(shard, fc):
            # the mesh was rebuilt: _dev_snapshot is already a fresh
            # cache for the NEW mesh; this batch's launch state belongs
            # to the old one, so no same-batch retry
            return False
        tripped = self.device_health.record_failure(fc)
        if (
            not tripped
            and fc != FAULT_PERSISTENT
            and can_relaunch
            and attempt < self.config.device_retry_max
        ):
            m.FAULT_RETRIES.inc(**{"class": fc})
            time.sleep(self.device_health.backoff_s(attempt))
            return True
        self._device_invalidate()
        return False

    def _commit_state_resilient(self, inf: _InFlight) -> _Staged:
        """_commit_state wrapped in the classified retry/backoff/breaker
        policy: transient faults re-dispatch the SAME batch up to
        device_retry_max times with jittered backoff; a persistent fault
        (or a failure streak reaching the breaker threshold, or a failed
        half-open canary) trips the breaker and serves THIS batch from the
        CPU engine — popped pods are never lost, and commit/event
        semantics are identical either way."""
        attempt = 0
        relaunch_pending = False
        while True:
            try:
                if relaunch_pending:
                    (inf.hosts_dev, inf.fetch, inf.attrib_dev,
                     inf.quality_dev) = inf.relaunch()
                    relaunch_pending = False
                staged = self._commit_state(inf)
            except BaseException as e:
                fc = classify_device_error(e)
                if fc is None:
                    raise
                shard = self._shard_of(e)
                self._note_device_fault(
                    fc, e, "dispatch" if relaunch_pending else "fence"
                )
                # the span carries the LAST retry class + attempt count —
                # the two facts a postmortem reader joins against the
                # breaker state (plus the blamed shard when attributed)
                inf.trace.annotate(fault_class=fc, fault_attempts=attempt + 1)
                if shard is not None:
                    inf.trace.annotate(fault_shard=shard)
                if self._fault_retry_allowed(
                    fc, attempt,
                    can_relaunch=(
                        not inf.degraded and inf.relaunch is not None
                    ),
                    shard=shard,
                ):
                    attempt += 1
                    relaunch_pending = True
                    continue
                if not self.config.cpu_fallback or inf.cpu_fetch is None:
                    raise
                self._degrade_fetch(inf)
                staged = self._commit_state(inf)  # CPU result: cannot fault
            if not inf.degraded:
                # an actual device round-trip succeeded: heal the streak
                # (and close the breaker if this was the half-open canary)
                self.device_health.record_success()
                if self.shard_health is not None and self._mesh_ids:
                    # ...and the per-shard streaks of the devices that
                    # served it (keeps "consecutive" consecutive)
                    self.shard_health.heal(self._mesh_ids)
            return staged

    def _encode_and_dispatch(self, pods: Sequence[Pod],
                             tier: str = TIER_BULK) -> Optional[_InFlight]:
        """Encode the batch + snapshot under the cache lock, run the
        extender/framework fan-out, and LAUNCH the engine.  Returns with
        the device still computing (hosts_dev is an async handle).

        TIER_EXPRESS cycles encode under the encoder's batch-width
        override: the batch pads to the small express shape (its own
        pre-compiled program) instead of the bulk lane's sticky width."""
        if not pods:
            return None
        t_cycle0 = time.monotonic()
        # transfer watermark BEFORE any device work: the commit tail
        # diffs against it so the cycle's sample/span carry exactly the
        # bytes THIS cycle moved (codec/transfer.py accounting)
        from kubernetes_tpu.codec.transfer import transfer_totals

        xfer0 = transfer_totals()
        enc = self.cache.encoder
        cycle = self.queue.scheduling_cycle
        express_width = (
            self.config.express_batch_size if tier == TIER_EXPRESS else None
        )
        # the cycle's ROOT span: one fresh trace id per cycle, child spans
        # per phase, annotated with the device-path facts (batch width,
        # dirty rows, breaker state, retry class) — retired into the
        # flight recorder when the commit tail finishes
        trace = Span(
            "schedule_cycle", start=t_cycle0, pods=len(pods), cycle=cycle,
            tier=tier,
        )
        self._cur_span = trace
        self._cur_tier = tier
        enc_span = trace.child("encode")
        batch_keys = {(p.namespace, p.name) for p in pods}
        # engine choice is made BEFORE the encode so degraded cycles leave
        # the encoder's dirty-row stream unconsumed (the device cache isn't
        # listening; it is invalidated on trip and rebuilt on recovery).
        # allow_device() may transition open -> half_open: the canary.
        use_device = (
            self.device_health.allow_device()
            if self.config.cpu_fallback
            else True
        )
        with self.cache._lock, enc.batch_width(express_width):
            # in-batch affinity state when pods carry ANY pod-affinity terms
            # (required or preferred) AND can interact (B > 1); built BEFORE
            # encode_pods so novel term topology keys register (and possibly
            # grow the pair vocabulary) before any TP-wide tensor is cut
            aff_state = (
                encode_batch_affinity(enc, pods)
                if len(pods) > 1 and batch_has_pod_affinity(pods)
                else None
            )
            batch = enc.encode_pods(pods)
            ports = encode_batch_ports(enc, pods)
            # two-pass evaluation: nominated pods (other than those being
            # scheduled now) are added to their nominated nodes in pass one
            nominated_pairs = [
                (p, n)
                for p, n in self.queue.nominated_pods()
                if (p.namespace, p.name) not in batch_keys
            ]
            nominated = encode_nominated(enc, nominated_pairs)
            cluster, generation = self.cache.snapshot()
            # rows the incremental snapshot refreshed: lets the device
            # cache scatter-update just those rows instead of re-shipping
            # whole tensors (codec/transfer.py); taken under the lock so
            # the row set corresponds exactly to THIS snapshot.  In hub
            # mode the dirty stream's SINGLE consumer is the hub itself
            # (its refresh() takes under the cache lock) — a replica
            # taking here would starve its siblings' resident state.
            dirty_rows = (
                enc.take_dirty_rows()
                if use_device and self._hub is None else None
            )
            # ports + anti-affinity contributions of nominated pods (the
            # non-resource half of podFitsOnNode's pass one) as a host
            # mask folded into extra_mask below
            nom_block = encode_nominated_block(
                enc, nominated_pairs, pods, batch.n_pods, cluster.n_nodes,
            )
            # point-in-time name->row map consistent with THIS snapshot;
            # extender round-trips below run outside the lock, and the live
            # node_rows dict may be mutated (rows recycled/regrown) meanwhile
            node_row_map = dict(enc.node_rows)
        enc_span.finish()
        fwk = self.framework
        pc = None
        extra_mask = extra_score = None
        if fwk is not None:
            # ONE PluginContext per cycle, shared across every extension
            # point (the CycleState pattern: a plugin computes at the tensor
            # Filter point and consumes at Prebind)
            from kubernetes_tpu.framework.v1alpha1 import PluginContext

            pc = PluginContext()
        if fwk is not None and (fwk.tensor_filter_plugins or fwk.tensor_score_plugins):
            B, N = batch.n_pods, cluster.n_nodes
            if fwk.tensor_filter_plugins:
                extra_mask = np.asarray(
                    fwk.run_filter_tensor(pc, cluster, batch, np.ones((B, N), bool))
                )
            if fwk.tensor_score_plugins:
                extra_score = np.asarray(
                    fwk.run_score_tensor(
                        pc, cluster, batch, np.zeros((B, N), np.float32)
                    ),
                    np.float32,
                )
        ext_failed: Dict[int, str] = {}
        # bind-/preempt-only extenders don't participate in filter/score;
        # skip the fan-out (and keep extra_mask None) when none do
        if any(
            e.config.filter_verb or e.config.prioritize_verb
            for e in self.extenders
        ):
            ext_span = trace.child("extenders", n=len(self.extenders))
            extra_mask, extra_score, ext_failed = self._apply_extenders(
                pods, node_row_map, cluster, extra_mask, extra_score,
                n_rows=batch.n_pods, trace_ctx=trace.traceparent(),
            )
            ext_span.finish()
        if nom_block is not None:
            # pass-one infeasibility from nominated ports/anti-affinity
            extra_mask = (
                ~nom_block if extra_mask is None else (extra_mask & ~nom_block)
            )
        t_disp = time.monotonic()
        self._phase("encode", t_disp - t_cycle0, tier)
        fn = self._schedule_fn
        if self._speculative_fn is not None:
            fn = self._speculative_fn
        last_index0 = self._last_index
        # launch-state box (ISSUE 14): hub mode re-snapshots at every
        # (re-)dispatch, so the cluster the engine ACTUALLY consumed —
        # the one the ledger must record and the generation the
        # reconciler's fast path fences on — is written here by launch()
        launch_box = {"cluster": cluster, "generation": generation,
                      "ledger": None}

        def launch():
            """(Re-)dispatch THIS encoded batch on the device.  Captured
            by _InFlight.relaunch so the transient-retry path re-runs the
            same computation with the same rotation base; dirty_rows are
            re-passed safely — fields whose upload already landed identity-
            skip, fields whose upload faulted re-scatter.  Hub mode
            (shared resident snapshot) refreshes to the CURRENT cache
            truth instead: replicas dispatch against the newest resident
            generation, and a retry can never scatter stale rows over a
            sibling's newer upload."""
            device_faults.check(
                device_faults.SITE_DISPATCH, devices=self._mesh_ids
            )
            if self._hub is not None:
                c2, g2, dev_cluster = self._hub.refresh()
                launch_box["cluster"], launch_box["generation"] = c2, g2
                if launch_box["ledger"] is not None:
                    launch_box["ledger"]["cluster"] = c2
            else:
                dev_cluster = self._dev_snapshot.update(
                    cluster, dirty_rows=dirty_rows
                )
            out = fn(
                dev_cluster, batch, ports,
                np.int32(last_index0), nominated,
                extra_mask, extra_score, aff_state,
            )
            hosts = out[0]
            # optional extra outputs, in fixed order after new_cluster:
            # Attribution (sequential attribution launches), then the
            # quality TopKQuality — both materialized at the commit
            # fence, after the winners land
            idx = 2
            attrib = None
            if getattr(fn, "attribution", False):
                attrib = out[idx]
                idx += 1
            qual = out[idx] if self._quality_k else None
            if qual is not None:
                # enqueue the tiny top-k D2H copies alongside the
                # winners buffer so the fence materialize is a copy
                # wait, never a compute sync
                for leaf in qual:
                    if hasattr(leaf, "copy_to_host_async"):
                        leaf.copy_to_host_async()
            # async result path: only the compact winners buffer (i32[B]
            # node rows) crosses the wire — the D2H copy is enqueued NOW
            # and materializes on a worker thread, so the blocking fence in
            # _commit_state is usually a no-op by the time the pipelined
            # loop reaches it (batch k's fetch overlaps batch k's host tail
            # and batch k+1's dispatch)
            return hosts, AsyncFetch(hosts), attrib, qual

        def cpu_fetch():
            """Winners for THIS batch from the CPU reference engine, in the
            device path's exact shape (cpuref/adapter.py) — the graceful-
            degradation seam.  Reads the LIVE cache state, which at call
            time equals the state this batch's snapshot saw (single
            scheduling thread; the pipelined loop commits batch k's state
            before dispatching k+1)."""
            t0 = time.monotonic()
            if self._hub is not None:
                # degraded replica cycle: the adapter reads the LIVE
                # cache, which sibling replicas mutate concurrently —
                # serialize the host compute under the cache lock (the
                # reconciler still re-checks its verdicts at commit)
                with self.cache._lock:
                    hosts = self.cpu_engine.schedule_batch(
                        pods, last_index0,
                        extra_mask=extra_mask, extra_score=extra_score,
                        nominated=nominated_pairs,
                        masked=frozenset(ext_failed),
                        row_map=node_row_map,
                    )
            else:
                hosts = self.cpu_engine.schedule_batch(
                    pods, last_index0,
                    extra_mask=extra_mask, extra_score=extra_score,
                    nominated=nominated_pairs,
                    masked=frozenset(ext_failed),
                    row_map=node_row_map,
                )
            return _HostResult(hosts, seconds=time.monotonic() - t0)

        degraded = False
        hosts_dev = attrib_dev = quality_dev = None
        disp_span = trace.child("dispatch")
        if use_device:
            launched = self._launch_resilient(launch)
        else:
            launched = None
        if launched is None:
            # breaker open (or dispatch gave up): degraded CPU cycle
            degraded = True
            m.DEGRADED_CYCLES.inc()
            self._postmortem(
                "degraded_cycle",
                "breaker open at dispatch" if not use_device
                else "dispatch gave up on the device",
            )
            fetch = cpu_fetch()
        else:
            hosts_dev, fetch, attrib_dev, quality_dev = launched
        self._last_index += len(pods)
        disp_span.finish()
        trace.annotate(
            batch=len(pods),
            dirty_rows=len(dirty_rows) if dirty_rows is not None else -1,
            breaker=self.device_health.state,
            degraded=degraded,
            engine="cpu" if degraded else self._engine_kind,
            shards=self.mesh.size if self.mesh is not None else 0,
        )
        t_disp_end = time.monotonic()
        self._phase("dispatch", t_disp_end - t_disp, tier)
        # hub mode: the launch refreshed to the newest resident state —
        # inf carries the generation/cluster the engine ACTUALLY saw
        # (the reconciler's fast-path fence and the ledger's truth)
        cluster_used = launch_box["cluster"]
        inf = _InFlight(
            pods=list(pods), hosts_dev=hosts_dev, fetch=fetch,
            generation=launch_box["generation"], cycle=cycle,
            ext_failed=ext_failed,
            pc=pc, t_cycle0=t_cycle0, trace=trace,
            relaunch=None if degraded else launch,
            cpu_fetch=cpu_fetch, degraded=degraded,
            last_index0=last_index0, tier=tier, attrib_dev=attrib_dev,
            quality_dev=quality_dev,
            quality_reqs=(
                batch.req if self.quality is not None else None
            ),
            quality_snapshot=(
                (cluster_used.allocatable, cluster_used.requested,
                 cluster_used.valid)
                if self.quality is not None else None
            ),
            telemetry_host=(
                (cluster_used.allocatable, cluster_used.requested,
                 cluster_used.valid)
                if self.telemetry is not None else None
            ),
            capacity_snapshot=(
                (cluster_used.allocatable, cluster_used.requested,
                 cluster_used.valid)
                if self.capacity is not None else None
            ),
            width=batch.n_pods,
            enqueue_s=t_disp_end - t_cycle0,
            xfer0=xfer0,
            reqs=batch.req if self._reconciler is not None else None,
        )
        if self._replica_of > 1:
            trace.annotate(replica=self._replica_id)
        if self.ledger is not None:
            # the exact launch inputs, stashed for the off-hot-path
            # ledger write after the commit tail (the snapshot arrays are
            # immutable by the encoder's dirty-row contract, so handing
            # references to the writer thread is safe).  Registered in
            # the launch box so a hub-mode retry re-points the recorded
            # cluster at the snapshot the retry actually consumed.
            inf.ledger_inputs = dict(
                cluster=cluster_used, batch=batch, ports=ports,
                nominated=nominated, aff_state=aff_state,
                extra_mask=extra_mask, extra_score=extra_score,
                last_index0=last_index0,
            )
            launch_box["ledger"] = inf.ledger_inputs
        return inf

    def _launch_resilient(self, launch):
        """Run a device launch under the classified retry/backoff policy.
        Returns (hosts_dev, fetch), or None when the device was given up on
        for this batch (caller degrades to the CPU engine); unclassified
        errors propagate (the schedule_cycle/_run_pipelined guards requeue
        the batch)."""
        attempt = 0
        while True:
            try:
                return launch()
            except BaseException as e:
                fc = classify_device_error(e)
                if fc is None:
                    raise
                self._note_device_fault(fc, e, "dispatch")
                if self._fault_retry_allowed(
                    fc, attempt, shard=self._shard_of(e)
                ):
                    attempt += 1
                    continue
                if not self.config.cpu_fallback:
                    raise
                return None

    # ------------------------------------------- device-resident megacycle
    #
    # ISSUE 12: chain K pre-encoded batches through the donated cluster
    # state in ONE launch (models/megacycle.py), commit the K winner
    # vectors asynchronously behind the next megacycle's dispatch.  The
    # eligibility gates below admit exactly the batches whose cross-batch
    # coupling the on-device carry (resources + lean SelectorSpread)
    # reproduces bit-identically — everything else rides single cycles.

    def _megacycle_ready(self) -> bool:
        """Scheduler-level gate: can THIS control-plane state form a
        megacycle at all?  Cheap (attribute reads) — checked once per
        run_once before any extra pop."""
        cfg = self.config
        if self._mega_fn is None or cfg.megacycle_batches <= 1:
            return False
        if self.framework is not None or not cfg.batched_commit:
            return False
        if any(
            e.config.filter_verb or e.config.prioritize_verb
            for e in self.extenders
        ):
            return False  # the fan-out is per-single-batch host work
        if self.queue.nominated_pods():
            return False  # two-pass nominated state is host-recomputed
        if cfg.cpu_fallback and not self.device_health.device_available:
            return False  # breaker open: single degraded cycles
        enc = self.cache.encoder
        if enc.term_groups:
            return False  # live affinity terms: commits move topo state
        if cfg.filter_config.service_affinity_labels:
            return False  # CheckServiceAffinity reads existing-pod state
        return True

    def _megacycle_safe(self, pods: Sequence[Pod]) -> bool:
        """Pod-level gate for one window: every pod's only cross-batch
        effect must be resources + at-most-one spread group (the
        encoder's lean shape, whose counts the device carry chains
        exactly).  Mirrors encode_pods' own group-membership rule."""
        enc = self.cache.encoder
        spread = enc._spread
        memo: Dict[tuple, int] = {}
        for p in pods:
            if self.POD_GROUP_LABEL in p.labels:
                return False
            a = p.spec.affinity
            if a is not None and (
                a.pod_affinity is not None
                or a.pod_anti_affinity is not None
            ):
                return False
            if p.spec.volumes or p.host_ports():
                return False
            if spread:
                sig = (p.namespace, tuple(sorted(p.labels.items())))
                n = memo.get(sig)
                if n is None:
                    n = sum(
                        1 for ns, sel in spread
                        if ns == p.namespace and sel.matches(p.labels)
                    )
                    memo[sig] = n
                if n > 1:
                    return False
        return True

    def _pop_megacycle_windows(self, first: Sequence[Pod], width: int):
        """Pop up to K-1 more batch windows behind the already-popped
        `first` (queue depth permitting, never blocking), keeping only
        megacycle-safe ones; the kept count is floored to a power of two
        so every launched K is a prewarm-able ladder shape.  Returns
        (windows, cycles, leftovers) — leftover windows (the pow2
        remainder, or the first unsafe window) are readded to the queue
        (shed-exempt, like every requeue of a popped pod) and re-pop on
        the next iteration."""
        windows: List[List[Pod]] = [list(first)]
        cycles = [self.queue.scheduling_cycle]
        leftovers: List[List[Pod]] = []
        k_target = min(
            self._cur_mega if self.config.adaptive_batch
            else self.config.megacycle_batches,
            self.config.megacycle_batches,
        )
        t_pop = time.monotonic()
        mega_pop_kw = (
            {"shard": self._replica_id, "of": self._replica_of}
            if self._replica_of > 1 else {}
        )
        while len(windows) < k_target:
            w = self.queue.pop_batch(width, 0.0, 0.0, **mega_pop_kw)
            if not w:
                break
            if self.invariants is not None:
                self.invariants.note_popped(w, self.queue.scheduling_cycle)
            if self._megacycle_safe(w):
                windows.append(w)
                cycles.append(self.queue.scheduling_cycle)
            else:
                leftovers.append(w)
                break
        self._phase("pop", time.monotonic() - t_pop)
        k_eff = 1 << (len(windows).bit_length() - 1)  # pow2 floor
        leftovers = windows[k_eff:] + leftovers
        windows = windows[:k_eff]
        for w in leftovers:
            for p in w:
                self.queue.readd(p)
        return windows, cycles[:k_eff]

    def _dispatch_megacycle(self, windows: List[List[Pod]],
                            cycles: List[int]) -> _MegaFlight:
        """Encode the K windows against ONE snapshot, stack them, and
        launch the megacycle scan.  Returns with the device computing
        all K sub-batches; the stacked winners fetch is in flight."""
        from kubernetes_tpu.codec.transfer import transfer_totals
        from kubernetes_tpu.models.megacycle import stack_windows

        K = len(windows)
        t_cycle0 = time.monotonic()
        xfer0 = transfer_totals()
        enc = self.cache.encoder
        spans = [
            Span(
                "schedule_cycle", start=t_cycle0, pods=len(w),
                cycle=cycles[k], tier=TIER_BULK, mega=f"{k + 1}/{K}",
            )
            for k, w in enumerate(windows)
        ]
        self._cur_span = spans[0]
        self._cur_tier = TIER_BULK
        enc_span = spans[0].child("encode", windows=K)
        use_device = (
            self.device_health.allow_device()
            if self.config.cpu_fallback
            else True
        )
        with self.cache._lock:
            batches = [enc.encode_pods(w) for w in windows]
            shapes = {
                tuple(
                    np.asarray(leaf).shape
                    for leaf in jax.tree_util.tree_leaves(b)
                )
                for b in batches
            }
            if len(shapes) > 1:
                # a later window grew a sticky pad dim: one more pass
                # encodes every window at the (now stable) max shapes
                batches = [enc.encode_pods(w) for w in windows]
            ports = [encode_batch_ports(enc, w) for w in windows]
            cluster, generation = self.cache.snapshot()
            # hub mode: the hub is the dirty stream's single consumer
            # (see _encode_and_dispatch)
            dirty_rows = (
                enc.take_dirty_rows()
                if use_device and self._hub is None else None
            )
            node_row_map = dict(enc.node_rows)
        enc_span.finish()
        # per-sub-batch rotation bases: base + cumulative RAW pod counts,
        # exactly what K separate cycles would have seen
        li0: List[int] = []
        acc = self._last_index
        for w in windows:
            li0.append(acc)
            acc += len(w)
        self._last_index = acc
        li0_arr = np.asarray(li0, np.int32)
        batch_k = stack_windows(batches)
        ports_k = stack_windows(ports)
        t_disp = time.monotonic()
        self._phase("encode", t_disp - t_cycle0)
        mega_fn = self._mega_fn
        launch_box = {"cluster": cluster, "generation": generation,
                      "ledger": None}

        def launch():
            device_faults.check(
                device_faults.SITE_DISPATCH, devices=self._mesh_ids
            )
            if self._hub is not None:
                c2, g2, dev_cluster = self._hub.refresh()
                launch_box["cluster"], launch_box["generation"] = c2, g2
                if launch_box["ledger"] is not None:
                    launch_box["ledger"]["cluster"] = c2
            else:
                dev_cluster = self._dev_snapshot.update(
                    cluster, dirty_rows=dirty_rows
                )
            out = mega_fn(dev_cluster, batch_k, ports_k, li0_arr)
            hosts = out[0]
            qual = out[2] if self._quality_k else None
            if qual is not None:
                for leaf in qual:
                    if hasattr(leaf, "copy_to_host_async"):
                        leaf.copy_to_host_async()
            return hosts, AsyncFetch(hosts), qual

        disp_span = spans[0].child("dispatch", windows=K)
        launched = self._launch_resilient(launch) if use_device else None
        disp_span.finish()
        t_disp_end = time.monotonic()
        self._phase("dispatch", t_disp_end - t_disp)
        degraded_dispatch = launched is None
        hosts_dev = fetch = quality_dev = None
        if not degraded_dispatch:
            hosts_dev, fetch, quality_dev = launched
        else:
            m.DEGRADED_CYCLES.inc(K)
            self._postmortem(
                "degraded_cycle",
                "breaker open at megacycle dispatch" if not use_device
                else "megacycle dispatch gave up on the device",
            )
        infs: List[_InFlight] = []
        for k, w in enumerate(windows):
            spans[k].annotate(
                batch=len(w),
                dirty_rows=(
                    len(dirty_rows) if k == 0 and dirty_rows is not None
                    else -1
                ),
                breaker=self.device_health.state,
                degraded=degraded_dispatch,
                engine="cpu" if degraded_dispatch else self._engine_kind,
                shards=self.mesh.size if self.mesh is not None else 0,
            )

            def cpu_fetch(pods=w, base=li0[k], rows=node_row_map):
                t0 = time.monotonic()
                if self._hub is not None:
                    # degraded replica window: serialize the live-cache
                    # read against sibling commits (see the single-cycle
                    # cpu_fetch)
                    with self.cache._lock:
                        hosts = self.cpu_engine.schedule_batch(
                            pods, base,
                            extra_mask=None, extra_score=None,
                            nominated=[], masked=frozenset(), row_map=rows,
                        )
                else:
                    hosts = self.cpu_engine.schedule_batch(
                        pods, base,
                        extra_mask=None, extra_score=None,
                        nominated=[], masked=frozenset(), row_map=rows,
                    )
                return _HostResult(hosts, seconds=time.monotonic() - t0)

            inf = _InFlight(
                pods=list(w), hosts_dev=None, fetch=None,
                generation=launch_box["generation"], cycle=cycles[k],
                ext_failed={},
                pc=None, t_cycle0=t_cycle0, trace=spans[k],
                relaunch=None, cpu_fetch=cpu_fetch,
                degraded=degraded_dispatch, last_index0=li0[k],
                tier=TIER_BULK,
                quality_reqs=(
                    batches[k].req if self.quality is not None else None
                ),
                # only window 0's placements saw exactly this snapshot;
                # later windows placed against chained state, so their
                # cycles skip the FFD counterfactual (margins/feasible
                # still record — only the regret cadence passes them by)
                quality_snapshot=(
                    (cluster.allocatable, cluster.requested, cluster.valid)
                    if (self.quality is not None and k == 0) else None
                ),
                telemetry_host=(
                    (cluster.allocatable, cluster.requested, cluster.valid)
                    if self.telemetry is not None else None
                ),
                # window 0's refs suffice for the capacity planner: its
                # interval cadence samples at most one window per
                # megacycle anyway, and the backlog solve wants the
                # pre-megacycle fleet state
                capacity_snapshot=(
                    (cluster.allocatable, cluster.requested, cluster.valid)
                    if (self.capacity is not None and k == 0) else None
                ),
                width=batches[k].n_pods,
                enqueue_s=(t_disp_end - t_cycle0) / K,
                xfer0=xfer0 if k == 0 else None,
                mega=(k, K),
                reqs=(
                    batches[k].req if self._reconciler is not None
                    else None
                ),
            )
            if self._replica_of > 1:
                spans[k].annotate(replica=self._replica_id)
            if self.ledger is not None:
                # sub-batch k > 0 replays against the host snapshot taken
                # AFTER sub-batch k-1's state commit (patched in at the
                # commit loop) — the host-side twin of the device chain,
                # so every block replays through the single-batch engine
                inf.ledger_inputs = dict(
                    cluster=launch_box["cluster"] if k == 0 else None,
                    batch=batches[k], ports=ports[k],
                    nominated=None, aff_state=None,
                    extra_mask=None, extra_score=None,
                    last_index0=li0[k],
                )
                if k == 0:
                    launch_box["ledger"] = inf.ledger_inputs
            infs.append(inf)
        self.megacycles_total += 1
        m.MEGACYCLES.inc()
        m.MEGACYCLE_DEPTH.set(float(K))
        return _MegaFlight(
            windows=infs, hosts_dev=hosts_dev, fetch=fetch,
            relaunch=None if degraded_dispatch else launch,
            t_cycle0=t_cycle0, quality_dev=quality_dev,
        )

    def _commit_state_mega(self, mf: _MegaFlight,
                           staged: List[_Staged]) -> List[_Staged]:
        """The megacycle's resilient fence + per-sub-batch state
        commits.  One retryable unit: a classified fault relaunches the
        WHOLE megacycle (same encoded batches, same rotation bases);
        giving up on the device replays the K batches sequentially
        through the CPU adapter — each sub-batch's state commit lands
        before the next adapter call, so the adapter sees exactly the
        chained state the device scan would have.

        `staged` is the CALLER's list, appended sub-batch by sub-batch
        as each state commit lands: on an error escaping mid-loop the
        caller (_commit_state_mega_or_requeue) still sees exactly which
        windows committed — their winners sit assumed and their tails
        must run; everything after them requeues."""
        K = len(mf.windows)
        attempt = 0
        relaunch_pending = False
        hosts_all = None
        qual_all = None
        t_fence0 = time.monotonic()
        while mf.fetch is not None:
            try:
                if relaunch_pending:
                    # relaunch at the TOP of the try (the single-cycle
                    # loop's relaunch_pending discipline): a classified
                    # fault raised by the re-dispatch itself must feed
                    # the same retry/degrade policy, not escape it
                    mf.hosts_dev, mf.fetch, mf.quality_dev = mf.relaunch()
                    relaunch_pending = False
                hosts_all = np.asarray(mf.fetch.result())
                for k, inf in enumerate(mf.windows):
                    self._validate_hosts(hosts_all[k], len(inf.pods))
                if mf.quality_dev is not None:
                    # the stacked top-k rides the same fence discipline
                    # as the winners: by now the launch has computed, so
                    # this is the pre-enqueued copy landing; a fault here
                    # retries/degrades the whole megacycle
                    qual_all = type(mf.quality_dev)(
                        *(np.asarray(x) for x in mf.quality_dev)
                    )
                break
            except BaseException as e:
                fc = classify_device_error(e)
                if fc is None:
                    raise
                shard = self._shard_of(e)
                self._note_device_fault(
                    fc, e,
                    "megacycle-dispatch" if relaunch_pending
                    else "megacycle-fence",
                )
                mf.windows[0].trace.annotate(
                    fault_class=fc, fault_attempts=attempt + 1
                )
                if shard is not None:
                    mf.windows[0].trace.annotate(fault_shard=shard)
                if self._fault_retry_allowed(
                    fc, attempt,
                    can_relaunch=mf.relaunch is not None, shard=shard,
                ):
                    attempt += 1
                    relaunch_pending = True
                    continue
                if not self.config.cpu_fallback:
                    raise
                hosts_all = None
                break
        stall = time.monotonic() - t_fence0
        if hosts_all is None:
            # degraded megacycle: K sequential CPU-adapter sub-batches
            if mf.fetch is not None:
                m.DEGRADED_CYCLES.inc(K)
                self._postmortem(
                    "degraded_cycle", "megacycle fence gave up on the device"
                )
            for inf in mf.windows:
                self._stage_mega_window(inf, None)
                inf.fetch = inf.cpu_fetch()
                inf.degraded = True
                inf.trace.annotate(degraded=True, engine="cpu")
                staged.append(self._commit_state(inf))
            return staged
        # device success: heal streaks, slice the one fetched window
        # into per-sub-batch handles carrying 1/K of the device timings
        self.device_health.record_success()
        if self.shard_health is not None and self._mesh_ids:
            self.shard_health.heal(self._mesh_ids)
        self._phase("host_stall", stall)
        f = mf.fetch
        prev_gen = -1
        for k, inf in enumerate(mf.windows):
            self._stage_mega_window(inf, None)
            if k > 0 and prev_gen >= 0:
                # chained fence (ISSUE 14): window k placed against the
                # state window k-1's commit produced — if no sibling
                # replica interleaved since, the zero-conflict fast path
                # still applies (commits only ever make real usage <=
                # what the on-device chain assumed, so verdicts hold)
                inf.generation = prev_gen
            if qual_all is not None:
                # slice sub-batch k's already-host quality rows; the
                # fence's materialize in _commit_state is then a no-op
                inf.quality = type(qual_all)(
                    *(np.asarray(x)[k] for x in qual_all)
                )
            inf.fetch = _HostResult(
                hosts_all[k],
                seconds=f.seconds / K,
                execute_seconds=getattr(f, "execute_seconds", 0.0) / K,
                materialize_seconds=(
                    getattr(f, "materialize_seconds", 0.0) / K
                ),
            )
            st = self._commit_state(inf)
            prev_gen = inf.gen_after
            if k == 0:
                st.stall_s += stall
            staged.append(st)
        return staged

    def _stage_mega_window(self, inf: _InFlight, _unused) -> None:
        """Pre-commit hook for one megacycle sub-batch: patch the ledger
        record's snapshot to the CURRENT host truth (sub-batches after
        the first replay against the state their predecessors committed
        — the host-side twin of the on-device chain)."""
        if (
            self.ledger is not None
            and inf.ledger_inputs is not None
            and inf.ledger_inputs.get("cluster") is None
        ):
            with self.cache._lock:
                inf.ledger_inputs["cluster"] = self.cache.snapshot()[0]

    def _commit_state_mega_or_requeue(
        self, mf: _MegaFlight
    ) -> List[_Staged]:
        """The megacycle batch-loss guard (the _commit_state_or_requeue
        analog): on an error that escaped the classified machinery, the
        pods of every sub-batch whose state was NOT yet committed are
        requeued (the shared `staged` list tracks exactly which windows
        landed before the error), the tails of already-committed
        sub-batches still run (their winners sit assumed and must bind
        or roll back), every un-staged window's span retires into the
        flight recorder with the error, and the error propagates."""
        staged: List[_Staged] = []
        try:
            return self._commit_state_mega(mf, staged)
        except BaseException as e:
            done = {id(st.inf) for st in staged}
            err = f"{type(e).__name__}: {e}"
            for inf in mf.windows:
                if id(inf) not in done:
                    self.queue.add_unschedulable_batch(inf.pods, inf.cycle)
                    # staged windows' spans retire via their tails below;
                    # the failed ones must still reach /debug/traces
                    inf.trace.annotate(error=err)
                    inf.trace.finish()
                    self.flight_recorder.record(inf.trace)
            if classify_device_error(e) is None:
                self._postmortem("unclassified_error", err)
            for st in staged:
                self._commit_tail(st)
            raise

    def _commit_state_prev(self, prev) -> List[_Staged]:
        """Normalize the in-flight slot's state-commit: a megacycle
        yields K staged sub-batches, a plain cycle one."""
        if isinstance(prev, _MegaFlight):
            return self._commit_state_mega_or_requeue(prev)
        return [self._commit_state_or_requeue(prev)]

    def schedule_megacycle(
        self, windows: List[List[Pod]], cycles: Optional[List[int]] = None,
    ) -> List[ScheduleResult]:
        """Place K batch windows through one megacycle launch,
        synchronously (the schedule_cycle analog; the pipelined run
        loop uses the in-flight slot instead).  Caller guarantees
        _megacycle_ready() and per-window _megacycle_safe()."""
        self.flush_pipeline()
        if cycles is None:
            cycles = [self.queue.scheduling_cycle] * len(windows)
        try:
            self._maybe_probe_shards()
            mf = self._dispatch_megacycle(windows, cycles)
        except BaseException:
            for w in windows:
                self.queue.add_unschedulable_batch(
                    list(w), self.queue.scheduling_cycle
                )
            raise
        results: List[ScheduleResult] = []
        for st in self._commit_state_mega_or_requeue(mf):
            results.extend(self._commit_tail(st))
        return results

    def _validate_hosts(self, hosts, n_pods: int) -> np.ndarray:
        """Structural validation of a fetched winners buffer: a corrupted
        D2H transfer must surface as a CLASSIFIED fault (retried like a
        transient error) instead of a KeyError deep in row_name or a
        silently-wrong placement on a never-allocated row.  In-range
        corruption is undetectable without a checksum — out of scope; the
        injector's corrupt mode scrambles values out of range on purpose."""
        hosts = np.asarray(hosts)
        enc = self.cache.encoder
        structural = (
            hosts.ndim == 1
            and hosts.shape[0] >= n_pods
            and hosts.dtype.kind in ("i", "u")
        )
        if structural and n_pods:
            head = hosts[:n_pods]
            # winners live in [-1, next_row): -1 = unschedulable, rows
            # below the arena high-water mark; anything outside (either
            # direction) is wire corruption, not a placement
            structural = (
                int(head.max(initial=-1)) < max(enc._next_row, 1)
                and int(head.min(initial=0)) >= -1
            )
        if not structural:
            raise CorruptedFetchError(
                "fetched winners buffer failed validation: shape=%s "
                "dtype=%s row_range=%s live_rows<%d"
                % (
                    hosts.shape, hosts.dtype,
                    (int(hosts[:n_pods].min(initial=0)),
                     int(hosts[:n_pods].max(initial=-1)))
                    if hosts.ndim == 1 and hosts.shape[0] >= n_pods
                    else "?",
                    enc._next_row,
                )
            )
        return hosts

    def _commit_state(self, inf: _InFlight) -> _Staged:
        """Fetch the placements and apply the cache-STATE half of the
        commit.  In batched mode (config.batched_commit, no framework) the
        whole batch of winners is assumed as ONE encoder delta under a
        single lock acquisition; the side-effect tail runs in
        _commit_tail.  In per-pod mode this only fetches — the tail runs
        the classic loop."""
        pods = inf.pods
        t_fetch0 = time.monotonic()
        hosts = inf.fetch.result()  # ready-fence: blocks only if the async
        #                             D2H copy hasn't landed yet
        hosts = self._validate_hosts(hosts, len(pods))
        if inf.attrib_dev is not None:
            # attribution rides the same launch: by the time the winners
            # landed the rest of the outputs are computed, so this fetch
            # costs one extra D2H copy, not a second device round-trip.
            # Inside the resilient fence on purpose — a fault here
            # retries/degrades exactly like a winners-fetch fault.
            inf.attrib = type(inf.attrib_dev)(
                *(np.asarray(x) for x in inf.attrib_dev)
            )
        if inf.quality_dev is not None:
            # the quality top-k rides the same launch and the same
            # discipline: its async copies were enqueued at dispatch, so
            # this is a copy wait behind the landed winners, never a new
            # sync; a fault here retries/degrades like the winners fetch
            inf.quality = type(inf.quality_dev)(
                *(np.asarray(x) for x in inf.quality_dev)
            )
        t_state0 = time.monotonic()
        # "fetch" records the ASYNC window (dispatch -> copy-complete,
        # measured on the fetch worker): it overlaps the dispatch/commit
        # host phases, so sum-of-phases exceeding wall clock is the
        # overlap working, not double counting.  "fetch_block" is the
        # residual host stall at the fence — the number the async path
        # exists to drive to ~0.
        self._phase("fetch", inf.fetch.seconds, inf.tier)
        self._phase("host_stall", t_state0 - t_fetch0, inf.tier)
        # fetch = the ASYNC device window (stamped on the fetch worker,
        # reconstructed here from its measured duration); fetch_block =
        # the residual host stall at the fence, a SUBSET of fetch
        inf.trace.add_child(
            "fetch", t_state0 - inf.fetch.seconds, t_state0, overlapped=True,
        )
        inf.trace.add_child("fetch_block", t_fetch0, t_state0)
        # algorithm latency: encode + device filter/score/select, amortized
        # per pod (metrics.go SchedulingAlgorithmLatency)
        algo_dt = (time.monotonic() - inf.t_cycle0) / len(pods)
        m.ALGO_LATENCY.observe_n(algo_dt, len(pods))
        batched = self.config.batched_commit and self.framework is None
        staged = _Staged(
            inf=inf, hosts=hosts, algo_dt=algo_dt, batched=batched,
            t_state0=t_state0, stall_s=t_state0 - t_fetch0,
        )
        if inf.xfer0 is not None:
            # the fence is the honest cycle boundary for transfer
            # accounting: every upload/fetch this cycle caused has
            # landed (AsyncFetch notes bytes before its done-event), and
            # the pipelined loop has not dispatched the next batch yet
            from kubernetes_tpu.codec.transfer import transfer_delta

            staged.xfer_delta = transfer_delta(inf.xfer0)
        if not batched:
            return staged
        import copy

        enc = self.cache.encoder
        winners = staged.winners
        for i, pod in enumerate(pods):
            if i in inf.ext_failed:
                continue
            row = int(hosts[i])
            if row < 0:
                staged.fit_idx.append(i)
                continue
            node_name = enc.row_name(row)
            # shallow-copy + set beats two dataclasses.replace calls ~2x
            # at 10k commits/s (Pod/PodSpec are plain mutable dataclasses)
            spec = copy.copy(pod.spec)
            spec.node_name = node_name
            assumed = copy.copy(pod)
            assumed.spec = spec
            winners.append((i, pod, assumed, node_name))
        if self._reconciler is not None:
            # SEQUENCED optimistic-concurrency commit (ISSUE 14): the
            # admission scan and the assume run as ONE critical section
            # under the cache lock, so the headroom the scan read is
            # exactly the headroom the delta lands on.  Race losers
            # readd to their owner shard in the tail; quota losers park
            # unschedulable.  Zero-conflict cycles (generation fence
            # unchanged, no quotas) admit with one integer comparison.
            with self.cache._lock:
                kept, race_lost, quota_lost = self._reconciler.reconcile(
                    self, inf, winners, hosts
                )
                if kept is not winners:
                    staged.winners = winners = list(kept)
                staged.race_lost = race_lost
                staged.quota_lost = quota_lost
                self.cache.assume_pods([a for _, _, a, _ in winners])
                inf.gen_after = enc.generation
                if self.invariants is not None and winners:
                    rows = sorted(
                        {int(hosts[i]) for i, _, _, _ in winners}
                    )
                    self.invariants.check_capacity(
                        rows, enc.a_requested, enc.a_allocatable,
                        row_name=enc.row_name,
                    )
            if race_lost or quota_lost:
                self.conflicts_total += len(race_lost)
                self.race_requeued_total += len(race_lost)
                self.quota_vetoed_total += len(quota_lost)
                inf.trace.annotate(
                    conflicts=len(race_lost), quota_vetoed=len(quota_lost)
                )
            staged.state_seconds = time.monotonic() - t_state0
            inf.trace.add_child(
                "commit", t_state0, time.monotonic(), winners=len(winners),
            )
            return staged
        # ONE lock acquisition + one encoder delta for the whole batch
        self.cache.assume_pods([a for _, _, a, _ in winners])
        if self.invariants is not None and winners:
            # capacity invariant over exactly the rows this batch
            # committed to — O(batch), read under the cache lock so the
            # arrays are consistent with the delta just applied
            rows = sorted({int(hosts[i]) for i, _, _, _ in winners})
            with self.cache._lock:
                self.invariants.check_capacity(
                    rows, enc.a_requested, enc.a_allocatable,
                    row_name=enc.row_name,
                )
        staged.state_seconds = time.monotonic() - t_state0
        inf.trace.add_child(
            "commit", t_state0, time.monotonic(), winners=len(winners),
        )
        return staged

    def _commit_tail(self, staged: _Staged) -> List[ScheduleResult]:
        """Side-effect tail of a cycle: binds, events, metrics, requeues,
        preemption.  Under pipeline_commit this overlaps the next batch's
        device dispatch (the state half already ran, so the next snapshot
        is exact)."""
        inf = staged.inf
        pods = inf.pods
        t_tail0 = time.monotonic()
        # the cycle's trace context is CURRENT for the whole tail: binds
        # (RemoteBinder / bind-verb extenders attach the traceparent
        # header) and Scheduled/FailedScheduling events (trace_id field)
        # all join back to this cycle's root span
        with use_traceparent(inf.trace):
            tail_span = inf.trace.child("bind-tail")
            if staged.batched:
                results, fit_errors = self._tail_batched(staged)
            else:
                results, fit_errors = self._tail_perpod(staged)
            tail_span.finish()
            if not self.config.disable_preemption:
                t_p = time.monotonic()
                p_span = inf.trace.child("preempt", failed=len(fit_errors))
                for pod in fit_errors:
                    self.preempt(pod)
                p_span.finish()
                self._phase("preempt", time.monotonic() - t_p, inf.tier)
        placed = sum(1 for r in results if r.node is not None)
        inf.trace.annotate(placed=placed, unschedulable=len(results) - placed)
        # the cycle's wire traffic (taken at the commit fence — see
        # _Staged.xfer_delta), annotated onto the span before it
        # retires (ISSUE 11): total bytes + the dominant seam — the two
        # facts a Perfetto reader joins against the phase children
        xfer_delta = staged.xfer_delta
        if xfer_delta:
            top = max(xfer_delta.items(), key=lambda kv: kv[1]["bytes"])
            inf.trace.annotate(
                transfer_bytes=sum(
                    v["bytes"] for v in xfer_delta.values()
                ),
                transfer_top_seam=top[0],
            )
        inf.trace.finish()
        self.flight_recorder.record(inf.trace)
        if self.ledger is not None and inf.ledger_inputs is not None:
            self._ledger_record(inf, staged, results)
        self._outcome_totals["placed"] += placed
        self._outcome_totals["unschedulable"] += len(results) - placed
        if self.telemetry is not None:
            t_tel = time.perf_counter()
            try:
                self._telemetry_cycle(inf, results, placed)
            except Exception as e:  # noqa: BLE001 — telemetry must never
                # fail a cycle whose placements are already committed: a
                # device fault in the analytics SIDE-launch (dispatched
                # outside the resilient fence on purpose) costs one
                # sample, not the batch
                klog.errorf(
                    "telemetry hook failed (cycle %d): %s", inf.cycle, e
                )
            finally:
                m.TELEMETRY_SECONDS.inc(time.perf_counter() - t_tel)
        # performance observatory (ISSUE 11): fold this cycle's
        # host/device split + transfer delta into the cost model.  Like
        # telemetry, the hook must never fail a committed cycle, and its
        # scheduling-thread cost is stamped into its own counter (the
        # <2% budget perf_smoke pins).
        t_perf = time.perf_counter()
        try:
            fetch = inf.fetch
            commit_s = staged.state_seconds + time.monotonic() - t_tail0
            wall_s = time.monotonic() - inf.t_cycle0
            if inf.mega is not None:
                # one launch served K sub-batches: attribute 1/K of the
                # shared wall to each (floored at its own host split so
                # every sample stays self-consistent); the device pair
                # was already sliced 1/K onto the fetch handle
                host_s = inf.enqueue_s + staged.stall_s + commit_s
                wall_s = max(wall_s / inf.mega[1], host_s)
            self.perfobs.on_cycle(
                width=inf.width or len(inf.pods),
                tier=inf.tier,
                degraded=inf.degraded,
                enqueue_s=inf.enqueue_s,
                execute_s=getattr(fetch, "execute_seconds", 0.0),
                materialize_s=getattr(fetch, "materialize_seconds", 0.0),
                stall_s=staged.stall_s,
                commit_s=commit_s,
                wall_s=wall_s,
                transfers=xfer_delta,
                trace_id=inf.trace.trace_id,
                mega=inf.mega,
            )
        except Exception as e:  # noqa: BLE001 — observability must
            # never fail a cycle whose placements are already committed
            klog.errorf(
                "perf observatory hook failed (cycle %d): %s", inf.cycle, e
            )
        finally:
            m.PERFOBS_SECONDS.inc(time.perf_counter() - t_perf)
        # placement-quality observatory (ISSUE 13): margins off the
        # in-launch top-k, feasible counts, drift detectors, and the
        # amortized regret counterfactual.  Same discipline as the
        # telemetry/perfobs hooks — never fails a committed cycle, cost
        # stamped into its own counter (the <2% budget perf_smoke pins).
        if self.quality is not None:
            t_q = time.perf_counter()
            try:
                self.quality.on_cycle(
                    cycle=inf.cycle,
                    tier=inf.tier,
                    degraded=inf.degraded,
                    hosts=staged.hosts,
                    n_pods=len(inf.pods),
                    quality=inf.quality,
                    reqs=inf.quality_reqs,
                    snapshot=inf.quality_snapshot,
                    attrib=inf.attrib,
                    analytics=(
                        self.telemetry.analytics
                        if self.telemetry is not None else None
                    ),
                )
            except Exception as e:  # noqa: BLE001
                klog.errorf(
                    "quality hook failed (cycle %d): %s", inf.cycle, e
                )
            finally:
                m.QUALITY_SECONDS.inc(time.perf_counter() - t_q)
        # device-resident capacity planner (ISSUE 15): the amortized
        # class-compressed what-if solve over the backlog + shape
        # catalog.  Same discipline as the telemetry/quality hooks —
        # never fails a committed cycle, cost stamped into its own
        # counter (the <2% budget perf_smoke pins).
        if self.capacity is not None:
            t_cap = time.perf_counter()
            try:
                self._capacity_cycle(inf)
            except Exception as e:  # noqa: BLE001
                klog.errorf(
                    "capacity hook failed (cycle %d): %s", inf.cycle, e
                )
            finally:
                m.CAPACITY_SECONDS.inc(time.perf_counter() - t_cap)
        m.PENDING_PODS.set(float(len(self.queue)))
        # metrics timeline (ISSUE 20): the cadence-gated sampling sweep
        # + online anomaly detection, AFTER every gauge above settled so
        # the sample reads this cycle's truth.  Same discipline as the
        # telemetry/quality/capacity hooks — never fails a committed
        # cycle, cost stamped into its own counter (the <2% budget
        # perf_smoke pins).  The idle path in run_once ticks the same
        # store so quiet loops keep sampling.
        if self.timeline is not None:
            t_tl = time.perf_counter()
            try:
                self.timeline.maybe_sample()
            except Exception as e:  # noqa: BLE001
                klog.errorf(
                    "timeline hook failed (cycle %d): %s", inf.cycle, e
                )
            finally:
                m.TIMELINE_SECONDS.inc(time.perf_counter() - t_tl)
        self.results.extend(results)
        # slow-cycle log LAST, once the ENTIRE tail (ledger record +
        # telemetry included) has run: the span was finished above, so
        # the logged total is the same duration the span tree at
        # /debug/traces reports — on pipelined cycles the log used to
        # fire mid-tail, reporting a duration the rest of the tail then
        # outgrew (regression-pinned by tests/test_tracing.py)
        if self.config.trace_threshold_s > 0:
            inf.trace.log_if_long(self.config.trace_threshold_s)
        return results

    def _telemetry_cycle(self, inf: _InFlight, results, placed: int) -> None:
        """Feed the telemetry hub one committed cycle: SLO good/bad
        events (deadline overrun, goodput vs shed, degraded), per-tier
        pending pressure, the per-width launch EWMA, and the amortized
        analytics side-launch over the RESIDENT snapshot buffers (host
        fallback when the device state is untrusted)."""
        hub = self.telemetry
        q = self.queue
        shed_total = getattr(q, "shed_total", 0)
        shed_delta = shed_total - self._shed_seen
        self._shed_seen = shed_total
        express = (
            q.express_depth() if hasattr(q, "express_depth") else 0
        )
        active = q.active_depth() if hasattr(q, "active_depth") else len(q)
        hub.record_pressure(
            bulk=max(0, active - express), express=express,
            parked=max(0, len(q) - active),
        )
        # ladder telemetry (ISSUE 10): live mesh width, the rung serving
        # cycles, per-shard breaker states, invariant-checker totals —
        # sampled fresh every cycle so /debug/cluster reflects rebuilds
        rung = self.ladder_rung
        m.LADDER_RUNG.set(float(self.RUNG_GAUGE[rung]))
        hub.record_mesh(
            width=self.mesh.size if self.mesh is not None else 0,
            full_width=(
                self._full_mesh.size if self._full_mesh is not None else 0
            ),
            rung=rung,
            shard_states=(
                self.shard_health.states()
                if self.shard_health is not None else None
            ),
            invariants=(
                self.invariants.summary()
                if self.invariants is not None else None
            ),
        )
        if not inf.degraded and inf.fetch is not None:
            hub.note_launch(inf.width or len(inf.pods), inf.fetch.seconds)
        from kubernetes_tpu.runtime.telemetry import ANALYTICS_FIELDS

        resident = (
            None if inf.degraded
            else self._device_resident(ANALYTICS_FIELDS)
        )
        hub.on_cycle(
            cycle=inf.cycle,
            tier=inf.tier,
            cycle_s=time.monotonic() - inf.t_cycle0,
            placed=placed,
            unschedulable=len(results) - placed,
            shed=shed_delta,
            degraded=inf.degraded,
            deadline_s=self.config.cycle_deadline_s,
            resident=resident,
            host_snapshot=inf.telemetry_host,
            span=inf.trace,
        )

    def _capacity_cycle(self, inf: _InFlight) -> None:
        """Feed the capacity planner one committed cycle: the cycle's
        host snapshot refs, a lazy backlog reader (invoked only on due
        interval cycles), the node-name resolver for the drainable
        report, and the encoder's read-only extended-resource column
        lookup for catalog vectors."""
        enc = self.cache.encoder

        def node_names():
            return {row: name for name, row in enc.node_rows.items()}

        self.capacity.on_cycle(
            cycle=inf.cycle,
            backlog=self._capacity_backlog,
            snapshot=inf.capacity_snapshot,
            node_names=node_names,
            res_col=enc.res_col_readonly,
        )

    def _capacity_backlog(self, cap: int):
        """The pending+unschedulable backlog in the planner's
        PRE-GROUPED form (distinct request vectors f32[G, R], counts
        i[G]; bounded at `cap` pods), encoded READ-ONLY — the planner
        must not grow the encoder's resource axis or intern anything.
        Controller-stamped backlogs collapse to a handful of distinct
        request contents, so pods group by content (the encoder's
        _req_memo key scheme) and each distinct content encodes once —
        the walk is dict ops per pod and the planner never
        materializes (or re-sorts) a per-pod matrix (the
        <2%-of-cycle hook budget)."""
        enc = self.cache.encoder
        q = self.queue
        pods = (
            q.backlog_pods(cap) if hasattr(q, "backlog_pods") else []
        )
        if not pods:
            return np.zeros((0, enc.dims.R), np.float32)
        groups: Dict[tuple, list] = {}
        for p in pods:
            rk = (
                tuple(
                    tuple(c.requests.items()) for c in p.spec.containers
                ),
                () if not p.spec.init_containers else tuple(
                    tuple(c.requests.items())
                    for c in p.spec.init_containers
                ),
            )
            g = groups.get(rk)
            if g is None:
                groups[rk] = [enc.backlog_req_vector(p), 1]
            else:
                g[1] += 1
        vecs = np.stack([v for v, _ in groups.values()])
        counts = np.asarray([c for _, c in groups.values()], np.int64)
        return vecs, counts

    def _ledger_record(self, inf: _InFlight, staged: _Staged,
                       results: List[ScheduleResult]) -> None:
        """Submit this cycle to the decision ledger: the stashed launch
        inputs (snapshot delta computed on the writer thread), the
        outcome facts, and the per-pod decision summaries the
        /debug/decisions ring serves (cross-linked by trace id)."""
        pods = inf.pods
        attrs = inf.trace.attrs
        decisions: List[dict] = []
        for i, pod in enumerate(pods):
            r = results[i] if i < len(results) else None
            node = r.node if r is not None else None
            d: dict = {"pod": f"{pod.namespace}/{pod.name}", "node": node}
            if node is None and inf.attrib is not None:
                from kubernetes_tpu.runtime.ledger import (
                    explain_unschedulable,
                )

                dominant, msg = explain_unschedulable(
                    inf.attrib.reason_counts[i]
                )
                if dominant:
                    d["reason"] = dominant
                    d["detail"] = msg
            decisions.append(d)
        outcome = {
            "cycle": inf.cycle,
            "tier": inf.tier,
            "engine": "cpu" if inf.degraded else self._engine_kind,
            "degraded": inf.degraded,
            "fault_class": attrs.get("fault_class"),
            "fault_attempts": int(attrs.get("fault_attempts", 0)),
            "trace_id": inf.trace.trace_id,
            "n_pods": len(pods),
            "pods": [[p.namespace, p.name] for p in pods],
            "winners": np.asarray(staged.hosts[: len(pods)], np.int32),
            "time": time.time(),
            # sub-batch k of a K-deep megacycle launch: the record is one
            # of K replayable blocks (each against the host snapshot its
            # predecessors' commits produced)
            **({"mega": list(inf.mega)} if inf.mega is not None else {}),
            # queue-sharded replicas (ISSUE 14): which replica dispatched
            # this cycle, and its reconciler commit sequence number —
            # cross-replica replay stays deterministic because every
            # block carries the exact snapshot its launch consumed, and
            # the sequence orders the interleaving for audit
            "replica": self._replica_id,
            **(
                {"seq": inf.commit_seq} if inf.commit_seq >= 0 else {}
            ),
            # quality top-k (ISSUE 13): the winner-pinned ranking rides
            # the block so bench --replay recomputes margins offline
            **(
                {
                    "quality_top_nodes": np.asarray(
                        inf.quality.top_nodes[: len(pods)], np.int32
                    ),
                    "quality_top_scores": np.asarray(
                        inf.quality.top_scores[: len(pods)], np.float32
                    ),
                    "quality_feasible": np.asarray(
                        inf.quality.feasible[: len(pods)], np.int32
                    ),
                }
                if inf.quality is not None else {}
            ),
        }
        self.ledger.record_cycle(inf.ledger_inputs, outcome, decisions)

    def replay_cycle(self, rec: dict) -> np.ndarray:
        """Re-execute one recorded cycle (a runtime/ledger.read_ledger
        record) through THIS scheduler's engine against the record's
        reconstructed snapshot, asserting bit-identical winners — the
        substrate the offline weight-tuning loop (ROADMAP item 4)
        re-scores against.  Offline: touches neither the cache, the
        resident device snapshot, nor the rotation counter."""
        from kubernetes_tpu.runtime.ledger import replay_record

        fn = (
            self._speculative_fn
            if self._speculative_fn is not None
            else self._schedule_fn
        )
        if rec.get("engine") == "cpu":
            # a degraded cycle's winners carry the CPU reference
            # engine's (= the sequential scan's) tie-rotation semantics
            fn = self._schedule_fn
        got = replay_record(fn, rec)
        want = np.asarray(rec["winners"])[: int(rec["n_pods"])]
        if not np.array_equal(got, want):
            raise AssertionError(
                f"replay mismatch at cycle {rec.get('cycle')}: "
                f"recorded {want.tolist()} != replayed {got.tolist()}"
            )
        return got

    # the dominant-failing-predicate explanation stamped onto an
    # unschedulable pod (the kubectl-describe FitError parity surface)
    UNSCHED_REASON_ANNOTATION = "kubernetes-tpu.io/unschedulable-reason"

    def _unsched_message(self, inf: _InFlight, i: int, n_nodes: int,
                         pod: Pod) -> str:
        """FailedScheduling audit text for batch index i: with
        attribution on, name the dominant failing predicate with
        per-reason node counts ("0/5000 nodes are available: 4987
        Insufficient resources, 13 node(s) had taints that the pod
        didn't tolerate.") and stamp the unschedulable-reason annotation
        + the per-plugin counter; else the classic count-only line."""
        if inf.attrib is not None:
            from kubernetes_tpu.runtime.ledger import explain_unschedulable

            dominant, msg = explain_unschedulable(
                inf.attrib.reason_counts[i]
            )
            if dominant:
                pod.metadata.annotations[
                    self.UNSCHED_REASON_ANNOTATION
                ] = msg
                m.UNSCHEDULABLE_REASONS.inc(plugin=dominant)
                return msg
        return "0/%d nodes are available" % n_nodes

    def _tail_perpod(self, staged: _Staged):
        """The classic per-pod commit loop (framework cycles, or
        config.batched_commit=False): reserve/assume/bind one pod at a
        time, emitting events and metrics inline."""
        inf = staged.inf
        pods, hosts = inf.pods, staged.hosts
        generation, cycle, pc = inf.generation, inf.cycle, inf.pc
        ext_failed, algo_dt = inf.ext_failed, staged.algo_dt
        t_commit0 = time.monotonic()
        enc = self.cache.encoder
        results = []
        fit_errors: List[Pod] = []
        for i, pod in enumerate(pods):
            row = int(hosts[i])
            if i in ext_failed:
                # non-ignorable extender error: plain error requeue, NOT a
                # FitError — no preemption (scheduler.go:463 preempts only
                # on core.FitError; extender errors surface as plain errors)
                self.queue.add_unschedulable(pod, cycle)
                results.append(ScheduleResult(pod, None, generation))
                m.SCHEDULE_ATTEMPTS.inc(result=m.SCHEDULE_ERROR)
                self.recorder.eventf(
                    "Pod", pod.namespace, pod.name,
                    EVENT_TYPE_WARNING, "FailedScheduling",
                    "extender error: %s", ext_failed[i],
                    trace_id=inf.trace.trace_id,
                )
                continue
            if row < 0:
                # FitError path: park in unschedulableQ with backoff
                # (factory.go MakeDefaultErrorFunc), then try preemption
                # (scheduler.go:463-475)
                self.queue.add_unschedulable(pod, cycle)
                results.append(ScheduleResult(pod, None, generation))
                fit_errors.append(pod)
                m.SCHEDULE_ATTEMPTS.inc(result=m.UNSCHEDULABLE)
                self.recorder.eventf(
                    "Pod", pod.namespace, pod.name,
                    EVENT_TYPE_WARNING, "FailedScheduling",
                    "%s", self._unsched_message(
                        inf, i, len(self.cache.encoder.node_rows), pod
                    ),
                    trace_id=inf.trace.trace_id,
                )
                continue
            node_name = enc.row_name(row)
            assumed = dataclasses.replace(
                pod, spec=dataclasses.replace(pod.spec, node_name=node_name)
            )
            # post-assume failures (permit/prebind/bind) requeue WITHOUT
            # preemption: the reference preempts only on a scheduling
            # FitError (scheduler.go:463: `if fitError, ok := err.(...)`),
            # not on binding hiccups for a pod that fits somewhere
            t_pod = time.monotonic()
            outcome = self._reserve_and_bind(
                pod, assumed, node_name, cycle, pc, algo_dt, t_pod
            )
            if outcome == "failed":
                results.append(ScheduleResult(pod, None, generation))
                m.SCHEDULE_ATTEMPTS.inc(result=m.SCHEDULE_ERROR)
            else:
                self.queue.delete_nominated_pod_if_exists(pod)
                results.append(ScheduleResult(pod, node_name, generation))
                if outcome == "bound":
                    # "waiting" pods record on async bind completion instead
                    self._record_scheduled(
                        pod, node_name, algo_dt + (time.monotonic() - t_pod),
                        tier=inf.tier,
                    )
        self._phase("commit", time.monotonic() - t_commit0, inf.tier)
        return results, fit_errors

    def _tail_batched(self, staged: _Staged):
        """Batched side-effect tail: per-pod bind callbacks (the only
        irreducibly per-pod step — each is an external call), then ONE
        batched emission each for requeues, metrics histograms, counters,
        and events, all in batch-index order so the audit trail matches
        the per-pod loop exactly."""
        inf = staged.inf
        pods, hosts = inf.pods, staged.hosts
        generation, cycle = inf.generation, inf.cycle
        t_tail0 = time.monotonic()
        B = len(pods)
        results: List[Optional[ScheduleResult]] = [None] * B
        events: List[Optional[Tuple]] = [None] * B
        n_nodes = len(self.cache.encoder.node_rows)
        # every event of this cycle joins the cycle's trace (7th tuple
        # element; eventf_batch splits it off the aggregation key)
        tid = inf.trace.trace_id
        losers: List[Pod] = []
        for i in staged.fit_idx:
            pod = pods[i]
            results[i] = ScheduleResult(pod, None, generation)
            losers.append(pod)
            events[i] = (
                "Pod", pod.namespace, pod.name,
                EVENT_TYPE_WARNING, "FailedScheduling",
                self._unsched_message(inf, i, n_nodes, pod), tid,
            )
        for i, msg in inf.ext_failed.items():
            pod = pods[i]
            results[i] = ScheduleResult(pod, None, generation)
            losers.append(pod)
            events[i] = (
                "Pod", pod.namespace, pod.name,
                EVENT_TYPE_WARNING, "FailedScheduling",
                "extender error: %s" % msg, tid,
            )
        # optimistic-concurrency losers (ISSUE 14): a sequenced-earlier
        # replica commit spent this pod's node headroom — requeue it to
        # its OWNER SHARD via readd (active queue, shed-exempt: no
        # popped pod is ever lost), not the unschedulable parking lot
        # (the pod fits elsewhere; it lost a race, not a FitError)
        for i, pod in staged.race_lost:
            results[i] = ScheduleResult(pod, None, generation)
            events[i] = (
                "Pod", pod.namespace, pod.name,
                EVENT_TYPE_NORMAL, "PlacementConflict",
                "lost optimistic concurrency race for node headroom; "
                "requeued", tid,
            )
        # namespace-quota vetoes park unschedulable WITH backoff: the
        # quota stays full until something terminates, and spinning the
        # pod through the active queue would starve its shard
        for i, pod in staged.quota_lost:
            results[i] = ScheduleResult(pod, None, generation)
            losers.append(pod)
            events[i] = (
                "Pod", pod.namespace, pod.name,
                EVENT_TYPE_WARNING, "QuotaExceeded",
                "namespace %s placement quota exhausted" % pod.namespace,
                tid,
            )
        # enqueue stamps BEFORE the bind fan-out: a bind's informer echo
        # (bound-pod update -> queue.delete) races a later take and would
        # drop the queue wait from the e2e histogram; failed binds restore
        # their stamp below so a requeued pod keeps its first-enqueue time
        winner_qts = self.queue.take_enqueue_times(
            [pod for _, pod, _, _ in staged.winners]
        )
        # bind fan-out: one _invoke_binder call per winner (each is an
        # external call — the only irreducibly per-pod step)
        bind_dts: List[float] = []
        bound: List[Tuple[int, Pod, str]] = []
        bound_qts: List[Optional[float]] = []
        bound_ts: List[float] = []   # per-pod bind-commit stamp: e2e must
        #                              end at THIS pod's bind, not the
        #                              whole fan-out's end (the per-pod
        #                              loop stamps each pod individually)
        n_bind_failed = 0
        for w, (i, pod, assumed, node_name) in enumerate(staged.winners):
            t0b = time.monotonic()
            ok = self._invoke_binder(pod, assumed, node_name)
            tb = time.monotonic()
            bind_dts.append(tb - t0b)
            if ok:
                bound.append((i, pod, node_name))
                bound_qts.append(winner_qts[w])
                bound_ts.append(tb)
                if self.invariants is not None:
                    self.invariants.note_bound(pod, node_name)
                # a pod that failed an earlier cycle may carry the
                # unschedulable-reason annotation: stale once it binds
                pod.metadata.annotations.pop(
                    self.UNSCHED_REASON_ANNOTATION, None
                )
                results[i] = ScheduleResult(pod, node_name, generation)
                events[i] = (
                    "Pod", pod.namespace, pod.name,
                    EVENT_TYPE_NORMAL, "Scheduled",
                    "Successfully assigned %s/%s to %s"
                    % (pod.namespace, pod.name, node_name), tid,
                )
            else:
                # optimistic rollback: ForgetPod + requeue, exactly the
                # per-pod _reject_assumed path (scheduler.go:416-426)
                self.cache.forget_pod(assumed)
                self.queue.restore_enqueue_time(pod, winner_qts[w])
                n_bind_failed += 1
                losers.append(pod)
                results[i] = ScheduleResult(pod, None, generation)
                events[i] = (
                    "Pod", pod.namespace, pod.name,
                    EVENT_TYPE_WARNING, "FailedScheduling",
                    self._BIND_REJECT_MSG
                    % (pod.namespace, pod.name, node_name), tid,
                )
        # batched bookkeeping: one lock acquisition per structure
        self.queue.add_unschedulable_batch(losers, cycle)
        for _, pod in staged.race_lost:
            self.queue.readd(pod)
        if staged.quota_lost:
            m.SCHEDULE_ATTEMPTS.inc(
                len(staged.quota_lost), result=m.UNSCHEDULABLE
            )
        if bound and self.queue.has_nominated():
            self.queue.delete_nominated_batch([p for _, p, _ in bound])
        m.BINDING_LATENCY.observe_batch(bind_dts)
        if staged.fit_idx:
            m.SCHEDULE_ATTEMPTS.inc(len(staged.fit_idx), result=m.UNSCHEDULABLE)
        if inf.ext_failed or n_bind_failed:
            m.SCHEDULE_ATTEMPTS.inc(
                len(inf.ext_failed) + n_bind_failed, result=m.SCHEDULE_ERROR
            )
        if bound:
            m.SCHEDULE_ATTEMPTS.inc(len(bound), result=m.SCHEDULED)
            e2es = [
                tb - qt if qt is not None
                else staged.algo_dt + (tb - staged.t_state0)
                for qt, tb in zip(bound_qts, bound_ts)
            ]
            m.E2E_LATENCY.observe_batch(e2es, tier=inf.tier)
            if klog.V(2).enabled:
                for (_, pod, node_name), e2e in zip(bound, e2es):
                    klog.V(2).infof(
                        "scheduled %s/%s to %s (%.1fms e2e)",
                        pod.namespace, pod.name, node_name, e2e * 1000,
                    )
        entries = [e for e in events if e is not None]
        eventf_batch = getattr(self.recorder, "eventf_batch", None)
        if eventf_batch is not None:
            eventf_batch(entries)
        else:  # duck-typed recorder without the batch entry point
            for kind, ns, name, type_, reason, msg, _tid in entries:
                self.recorder.eventf(kind, ns, name, type_, reason, "%s", msg)
        self._phase(
            "commit", staged.state_seconds + time.monotonic() - t_tail0,
            inf.tier,
        )
        return list(results), [pods[i] for i in staged.fit_idx]

    # --------------------------------------------------------- extenders

    def _apply_extenders(self, pods, rows, cluster, extra_mask, extra_score,
                         n_rows=None, trace_ctx=""):
        """Chain the configured HTTP extenders per pod: each filter
        round-trip intersects the feasibility mask (an extender can only
        veto, never resurrect — generic_scheduler.go:527-554), prioritize
        results add score*weight (:774-804, merged before selectHost).

        `rows` is the snapshot-consistent name->row map captured under the
        cache lock.  The extender chain is sequential per pod (each link
        sees the previous link's narrowed list), but pods fan out across a
        small thread pool — the reference's 16-goroutine analog for the
        network-bound section.  Returns (mask, score, failed{batch index:
        message}); a pod whose non-ignorable extender errored is fully
        masked and listed in failed."""
        from concurrent.futures import ThreadPoolExecutor

        from kubernetes_tpu.extender.client import ExtenderError

        # mask/score are allocated at the ENGINE batch width (n_rows =
        # batch.n_pods, a pow2 pad >= len(pods)); the pad-row tail stays
        # all-true/zero and pods.valid masks it on device.  Allocating at
        # len(pods) broke any non-pow2 batch with extenders configured.
        B, N = len(pods), cluster.n_nodes
        Bp = n_rows if n_rows is not None else B
        mask = (
            np.ones((Bp, N), bool)
            if extra_mask is None else np.array(extra_mask, bool)
        )
        score = (
            np.zeros((Bp, N), np.float32)
            if extra_score is None else np.array(extra_score, np.float32)
        )
        failed: Dict[int, str] = {}
        all_names = [n for n, r in rows.items() if r < N]

        def one_pod(i_pod):
            i, pod = i_pod
            # pool workers re-enter the CYCLE's trace context explicitly
            # (thread-locals don't cross the executor boundary), so every
            # extender round-trip carries the cycle's traceparent header
            with use_traceparent(trace_ctx):
                return _one_pod_traced(i, pod)

        def _one_pod_traced(i, pod):
            names = list(all_names)
            for ext in self.extenders:
                if not ext.is_interested(pod):
                    continue
                try:
                    ok, _failed_nodes = ext.filter(pod, names)
                except ExtenderError as e:
                    if ext.is_ignorable:
                        # skip it, let the rest decide (:534-537)
                        continue
                    failed[i] = str(e)
                    mask[i, :] = False
                    return
                okset = set(ok)
                for n in names:
                    if n not in okset:
                        mask[i, rows[n]] = False
                names = [n for n in names if n in okset]
                if not names:
                    return
            for ext in self.extenders:
                if not ext.is_interested(pod) or not ext.config.prioritize_verb:
                    continue
                try:
                    scores, weight = ext.prioritize(pod, names)
                except ExtenderError:
                    # prioritize errors are ignorable by design (:784-787)
                    continue
                for n, s in scores.items():
                    r = rows.get(n)
                    if r is not None and r < N:
                        score[i, r] += s * weight

        if B == 1:
            one_pod((0, pods[0]))
        else:
            with ThreadPoolExecutor(max_workers=16) as pool:
                list(pool.map(one_pod, enumerate(pods)))
        return mask, score, failed

    # ------------------------------------------------- reserve/permit/bind

    def _record_scheduled(self, pod: Pod, node_name: str, e2e: float,
                          tier: str = TIER_BULK) -> None:
        """Scheduled event + counters, only once a bind actually succeeded
        (scheduler.go:268 emits after bind, not at assume).  The e2e
        histogram records queue-add -> bind-commit when the pod came
        through the queue (the density SLO pair: throughput + p99,
        density.go:988-990); the caller's algo+bind figure is the fallback
        for direct schedule_cycle() calls."""
        if self.invariants is not None:
            self.invariants.note_bound(pod, node_name)
        qt = self.queue.take_enqueue_time(pod)
        if qt is not None:
            e2e = time.monotonic() - qt
        # a FitError retry that now succeeded: the explain annotation a
        # previous cycle stamped is stale the moment the pod binds
        pod.metadata.annotations.pop(self.UNSCHED_REASON_ANNOTATION, None)
        klog.V(2).infof(
            "scheduled %s/%s to %s (%.1fms e2e)",
            pod.namespace, pod.name, node_name, e2e * 1000,
        )
        m.SCHEDULE_ATTEMPTS.inc(result=m.SCHEDULED)
        m.E2E_LATENCY.observe(e2e, tier=tier)
        self.recorder.eventf(
            "Pod", pod.namespace, pod.name,
            EVENT_TYPE_NORMAL, "Scheduled",
            "Successfully assigned %s/%s to %s",
            pod.namespace, pod.name, node_name,
            trace_id=current_trace_id(),  # set during the cycle tail;
            #                               "" on gang/async-bind paths
        )

    def _reserve_and_bind(
        self, pod: Pod, assumed: Pod, node_name: str, cycle: int, pc=None,
        algo_dt: float = 0.0, t_pod: float = 0.0,
    ) -> str:
        """Framework extension points around assume->bind (scheduleOne,
        scheduler.go:507-580): Reserve -> assume -> Permit -> Prebind ->
        bind, with Unreserve + ForgetPod + requeue on any later rejection.
        `pc` is the cycle's shared PluginContext.  Returns "bound",
        "waiting" (bind completes asynchronously), or "failed"."""
        fwk = self.framework
        if fwk is not None:
            st = fwk.run_reserve_plugins(pc, assumed, node_name)
            if not st.is_success():
                # reserve failure is an internal error: requeue, no preemption
                self.queue.add_unschedulable(pod, cycle)
                return "failed"
        self.cache.assume_pod(assumed)
        if fwk is not None and fwk.permit_plugins:
            status, wp, timeout = fwk.start_permit(pc, assumed, node_name)
            if wp is not None:
                # the GOROUTINE BOUNDARY (scheduler.go:523): binding of a
                # waiting pod completes asynchronously; the cycle moves on
                # with the pod optimistically assumed
                threading.Thread(
                    target=self._finish_waiting_pod,
                    args=(fwk, pc, pod, assumed, node_name, cycle, wp, timeout,
                          algo_dt, t_pod),
                    daemon=True,
                ).start()
                return "waiting"
            if not status.is_success():
                self._reject_assumed(
                    fwk, pc, pod, assumed, node_name, cycle, status.message
                )
                return "failed"
        ok = self._prebind_and_bind(fwk, pc, pod, assumed, node_name, cycle)
        return "bound" if ok else "failed"

    # single source of truth for the bind-rejection audit message (the
    # batched/per-pod equivalence test compares event text verbatim)
    _BIND_REJECT_MSG = "Binding rejected for %s/%s on %s"

    def _invoke_binder(self, pod, assumed, node_name) -> bool:
        """The actual bind call, shared by the per-pod and batched commit
        paths: a bind-verb extender binds pods it manages in place of the
        default binder (extender.go:360-387; scheduler.go bind path); any
        exception counts as a rejection."""
        binder_ext = next(
            (e for e in self.extenders
             if e.is_binder and e.is_interested(pod)),
            None,
        )
        try:
            if binder_ext is not None:
                binder_ext.bind(
                    pod.namespace, pod.name, pod.metadata.uid, node_name
                )
                return True
            return bool(self.binder(assumed, node_name))
        except Exception:
            return False

    def _prebind_and_bind(self, fwk, pc, pod, assumed, node_name, cycle) -> bool:
        if fwk is not None and fwk.prebind_plugins:
            st = fwk.run_prebind_plugins(pc, assumed, node_name)
            if not st.is_success():
                self._reject_assumed(
                    fwk, pc, pod, assumed, node_name, cycle, st.message
                )
                return False
        t0 = time.monotonic()
        ok = self._invoke_binder(pod, assumed, node_name)
        m.BINDING_LATENCY.observe(time.monotonic() - t0)
        if not ok:
            self._reject_assumed(
                fwk, pc, pod, assumed, node_name, cycle,
                self._BIND_REJECT_MSG
                % (pod.namespace, pod.name, node_name),
            )
            return False
        return True

    def _reject_assumed(
        self, fwk, pc, pod, assumed, node_name, cycle, message: str = ""
    ) -> None:
        """Rollback for a pod rejected after assume (scheduler.go:416-426
        ForgetPod + MakeDefaultErrorFunc requeue + unreserve plugins +
        FailedScheduling event, scheduler.go:433)."""
        self.cache.forget_pod(assumed)
        if fwk is not None:
            fwk.run_unreserve_plugins(pc, assumed, node_name)
        self.queue.add_unschedulable(pod, cycle)
        self.recorder.eventf(
            "Pod", pod.namespace, pod.name,
            EVENT_TYPE_WARNING, "FailedScheduling",
            "%s", message or f"rejected after assume on {node_name}",
            trace_id=current_trace_id(),
        )

    def _finish_waiting_pod(
        self, fwk, pc, pod, assumed, node_name, cycle, wp, timeout,
        algo_dt: float = 0.0, t_pod: float = 0.0,
    ) -> None:
        try:
            st = wp.wait(timeout)
        finally:
            fwk.waiting_pods.remove(assumed)
        if st.is_success():
            if self._prebind_and_bind(fwk, pc, pod, assumed, node_name, cycle):
                self._record_scheduled(
                    pod, node_name,
                    algo_dt + (time.monotonic() - t_pod) if t_pod else algo_dt,
                )
        else:
            self._reject_assumed(
                fwk, pc, pod, assumed, node_name, cycle, st.message
            )

    # ---------------------------------------------------------- preemption

    def preempt(self, pod: Pod) -> Optional[str]:
        """Try to make room for a pod that failed to fit: pick a node +
        minimal victim set on device, verify the nomination host-side against
        the full predicate set, delete the victims, and record the nominated
        node so the two-pass evaluation protects the claim.

        Mirrors Scheduler.preempt (scheduler.go:292-342) + genericScheduler
        .Preempt (generic_scheduler.go:310-369).  Returns the nominated node
        name, or None if preemption does not help."""
        if self.config.disable_preemption:
            return None
        m.PREEMPTION_ATTEMPTS.inc()
        t0 = time.monotonic()
        try:
            return self._preempt_inner(pod)
        finally:
            # every attempt's evaluation cost lands in the histogram, not
            # just successful nominations
            m.PREEMPTION_LATENCY.observe(time.monotonic() - t0)

    def _preempt_inner(self, pod: Pod) -> Optional[str]:
        enc = self.cache.encoder
        # preemption must not consume the breaker's half-open canary (the
        # scheduling cycle is the probe), so it keys off the NON-mutating
        # availability check: anything but CLOSED routes the candidate scan
        # through the CPU engine
        use_device = (
            self.device_health.device_available
            if self.config.cpu_fallback
            else True
        )
        with self.cache._lock:
            if not self._eligible_to_preempt(pod):
                return None
            batch = enc.encode_pods([pod])
            cluster, _ = self.cache.snapshot()
            dirty_rows = (
                enc.take_dirty_rows()
                if use_device and self._hub is None else None
            )
        # device work OUTSIDE the cache lock: a first-shape preempt pays a
        # multi-second XLA compile, and informer/event threads must not
        # stall on the lock for it.  The snapshot is a point-in-time copy;
        # cands may be one event stale vs the re-acquired state below —
        # the same optimistic semantics as the reference (the pick loop's
        # verify/veto and the next cycle resolve races).
        # Resident-buffer reuse + explicit device_put: preemption runs
        # right after a failed cycle (snapshot mostly byte-identical), and
        # host-numpy jit ARGUMENTS cross the tunnel on the slow
        # synchronous path (codec/transfer.py).
        #
        # SINGLE-SCHEDULING-THREAD INVARIANT: _dev_snapshot (and the
        # encoder's take_dirty_rows stream feeding it) is the same mutable
        # DeviceSnapshotCache schedule_cycle uses, mutated here OUTSIDE
        # cache._lock.  This is safe only because preempt is invoked solely
        # from the scheduling thread's commit tail (_commit_tail) — the
        # pipelined commit path keeps every _dev_snapshot.update on that
        # one thread, interleaved never concurrent.  If preempt ever
        # becomes callable from another thread, give preemption its own
        # DeviceSnapshotCache (and its own dirty-row take stream).
        if use_device:
            try:
                cluster = self._device_update(cluster, dirty_rows)
                if jax.default_backend() != "cpu":
                    if self.mesh is not None:
                        from kubernetes_tpu.parallel.mesh import replicate

                        batch = replicate(batch, self.mesh)
                    else:
                        batch = jax.device_put(batch)
                cands = host_fetch(
                    self._preempt_eval(cluster, batch), tag="preempt"
                )[0].copy()
            except BaseException as e:
                fc = classify_device_error(e)
                if fc is None:
                    raise
                # preempt device faults feed the same breaker accounting
                # (shard-attributed ones the ladder, like a cycle fault);
                # the candidate scan degrades to the CPU engine in place
                self._note_device_fault(fc, e, "preempt")
                if not self._note_shard_fault(self._shard_of(e), fc):
                    self.device_health.record_failure(fc)
                    self._device_invalidate()
                if not self.config.cpu_fallback:
                    raise
                cands = self.cpu_engine.preempt_candidates(
                    pod, cluster.n_nodes
                )
        else:
            cands = self.cpu_engine.preempt_candidates(pod, cluster.n_nodes)
        if not cands.any():
            # nodesWherePreemptionMightHelp came back empty: clear any
            # previous nomination (generic_scheduler.go:328-333)
            self._clear_nomination(pod)
            return None
        with self.cache._lock:
            if not self._eligible_to_preempt(pod):
                return None
            arena = enc.pods_snapshot()
            violating = self._pdb_violating_flags(enc, len(arena.node))
            slots = sorted_victim_slots(
                arena.priority,
                arena.valid,
                arena.node,
                pod.spec.priority,
                violating,
                arena.start,
            )
            row, _, victims, res = pick_preemption_node(
                enc, pod, cands, arena, slots, violating,
                self.config.filter_config.max_vols,
            )
            if row < 0:
                self._clear_nomination(pod)
                return None
            node_name = enc.row_name(row)
        # preempt-verb extenders vet the candidate + victim set
        # (processPreemptionWithExtenders, generic_scheduler.go:342-369);
        # HTTP round-trips happen outside the cache lock
        victims = self._extender_preemption(pod, node_name, victims, res)
        if victims is None:
            self._clear_nomination(pod)
            return None
        for v in victims:
            self.victim_deleter(v)
            if self.invariants is not None:
                # the victim left the cluster: a same-name successor must
                # not read as a double-bind
                self.invariants.note_removed(v)
            self.recorder.eventf(
                "Pod", v.namespace, v.name,
                EVENT_TYPE_NORMAL, "Preempted",
                "by %s/%s on node %s", pod.namespace, pod.name, node_name,
            )
        m.PREEMPTION_VICTIMS.set(float(len(victims)))
        pod.status.nominated_node_name = node_name
        self.queue.update_nominated_pod(pod, node_name)
        self.preemptions.append(
            (
                (pod.namespace, pod.name),
                node_name,
                [(v.namespace, v.name) for v in victims],
            )
        )
        # victim deletions are cluster events (eventhandlers.go ->
        # MoveAllToActiveQueue); in standalone mode emulate the move so the
        # preemptor retries promptly
        self.queue.move_all_to_active()
        return node_name

    def _extender_preemption(self, pod, node_name, victims, res):
        """Run ProcessPreemption through every preempt-verb extender that is
        interested; each may narrow the victim set or drop the node entirely
        (return None -> abort, nothing evicted).  Non-preempt-verb extenders
        are skipped, ignorable errors skip just that extender
        (generic_scheduler.go:342-369)."""
        chain = [
            e for e in self.extenders
            if e.supports_preemption and e.is_interested(pod)
        ]
        if not chain:
            return victims
        from kubernetes_tpu.extender.client import ExtenderError

        meta = {
            node_name: {
                "pods": [{"uid": v.metadata.uid or f"{v.namespace}/{v.name}"}
                         for v in victims],
                "numPDBViolations": int(getattr(res, "n_pdb_violations", 0)),
            }
        }
        for ext in chain:
            try:
                meta = ext.process_preemption(pod, meta)
            except ExtenderError:
                if ext.is_ignorable:
                    continue
                return None
            if node_name not in meta:
                return None
        keep = {
            p.get("uid") for p in meta[node_name].get("pods", [])
        }
        return [
            v for v in victims
            if (v.metadata.uid or f"{v.namespace}/{v.name}") in keep
        ]

    def _eligible_to_preempt(self, pod: Pod) -> bool:
        """podEligibleToPreemptOthers (generic_scheduler.go:1159-1180): if the
        pod already nominated a node and a lower-priority pod there is still
        terminating, wait instead of preempting more."""
        nom = pod.status.nominated_node_name
        if not nom:
            return True
        enc = self.cache.encoder
        row = enc.node_rows.get(nom, -1)
        if row < 0:
            return True
        for key in enc._row_pods.get(row, ()):
            rec = enc.pods.get(key)
            if (
                rec is not None
                and rec.pod is not None
                and rec.pod.metadata.deletion_timestamp is not None
                and rec.priority < pod.spec.priority
            ):
                return False
        return True

    def _clear_nomination(self, pod: Pod) -> None:
        pod.status.nominated_node_name = ""
        self.queue.delete_nominated_pod_if_exists(pod)

    def _pdb_violating_flags(self, enc, m_cap: int) -> np.ndarray:
        """bool[M]: evicting arena pod m would violate a PodDisruptionBudget
        (filterPodsWithPDBViolation, generic_scheduler.go:990-1035)."""
        flags = np.zeros(m_cap, bool)
        pdbs = [p for p in self.pdb_lister() if p.disruptions_allowed <= 0]
        if not pdbs:
            return flags
        for rec in enc.pods.values():
            if rec.pod is None or rec.node_row < 0:
                continue
            if any(pdb.matches(rec.pod) for pdb in pdbs):
                flags[rec.m] = True
        return flags

    def _verify_preemption(self, pod: Pod, row: int, victims: List[Pod]) -> bool:
        return verify_nomination(
            self.cache.encoder, pod, row, victims, self.config.filter_config.max_vols
        )

    # ------------------------------------------------------------- run loop

    # pods carrying this label pair schedule as all-or-nothing PodGroups
    # (the scheduler-plugins lightweight-coscheduling convention:
    # .../name = group, .../min-available = minMember).  Scope and limits,
    # deliberately matching the convention's own semantics:
    #  * atomicity covers the members CO-PENDING in one scheduling cycle
    #    (the plugin likewise gates on min-available pods being Pending);
    #    a group split across cycles schedules per co-arriving cohort, and
    #    min-available larger than the engine batch width can never be
    #    satisfied in one cycle and parks with backoff each retry;
    #  * gangs do not trigger preemption (a failed gang parks like a
    #    FitError pod but never evicts victims);
    #  * when EXTENDERS are configured the gang path is bypassed (members
    #    schedule as plain pods, no atomicity) — the gang launch cannot
    #    consult extender filter verdicts, and silently ignoring them
    #    would place pods on extender-vetoed nodes.
    POD_GROUP_LABEL = "pod-group.scheduling.sigs.k8s.io/name"
    POD_GROUP_MIN_MEMBER = "pod-group.scheduling.sigs.k8s.io/min-available"

    def _tier_of(self, pod: Pod) -> str:
        """The queue's admission-time tier classifier (wired when
        config.express_lane): annotation opt-in / priority threshold via
        classify_tier, EXCEPT gang members — the express lane has no gang
        path (atomicity needs the bulk cycle's gang machinery), so a
        pod-group pod always rides bulk whatever its priority."""
        if self.POD_GROUP_LABEL in pod.labels:
            return TIER_BULK
        return classify_tier(pod, self.config.express_priority_threshold)

    def _run_express(self) -> int:
        """Serve ONE express-lane cycle if express pods are pending: pop up
        to express_batch_size from the express heap (never blocking — the
        tier exists to remove waiting, not add batch-formation windows)
        and schedule them synchronously at the express encode width.
        Returns pods placed.  Bounded to one small batch per call, so the
        interleave with the caller's bulk cycle is the starvation guard in
        BOTH directions: sustained express load still yields a bulk cycle
        per iteration, and a saturating bulk backlog still yields an
        express cycle per iteration."""
        pop_express = getattr(self.queue, "pop_express_batch", None)
        if pop_express is None:
            return 0  # caller-owned queue without tier lanes
        t_pop = time.monotonic()
        pods = pop_express(max(1, self.config.express_batch_size))
        if not pods:
            return 0
        if self.invariants is not None:
            self.invariants.note_popped(pods, self.queue.scheduling_cycle)
        self._phase("pop", time.monotonic() - t_pop, TIER_EXPRESS)
        results = self.schedule_cycle(pods, tier=TIER_EXPRESS)
        return sum(1 for r in results if r.node is not None)

    def _maybe_heartbeat(self) -> None:
        """Once per config.heartbeat_s (0 = off): ONE klog line with the
        liveness numbers an operator greps for first — so a quiet log
        still proves the loop is alive.  Called from run_once on every
        iteration (including idle polls: an empty queue must still
        heartbeat)."""
        hb = self.config.heartbeat_s
        if hb <= 0:
            return
        now = time.monotonic()
        if now - self._last_heartbeat < hb:
            return
        self._last_heartbeat = now
        q = self.queue
        express = q.express_depth() if hasattr(q, "express_depth") else 0
        active = q.active_depth() if hasattr(q, "active_depth") else len(q)
        hbm = self.telemetry.hbm_in_use() if self.telemetry is not None else 0
        # observatory window since the last heartbeat (ISSUE 11): host
        # vs device milliseconds and the transfer seam that moved the
        # most bytes — the three numbers that say WHERE the interval's
        # wall time went without opening /debug/perf
        host_ms, dev_ms, xfer_top = self.perfobs.heartbeat_window()
        # placement-quality satellites (ISSUE 13): sliding margin p50 +
        # the last sampled regret ratio — decision confidence and
        # packing density on the same liveness line
        q_margin, q_regret = (
            self.quality.heartbeat_fields()
            if self.quality is not None else (0.0, 0.0)
        )
        # timeline satellites (ISSUE 20): anomaly firings so far + how
        # far sampling lags its cadence — detection liveness on the same
        # line as the loop's
        tl = self.timeline
        tl_anoms = (
            tl.detector.anomalies_total
            if tl is not None and tl.detector is not None else 0
        )
        tl_lag = tl.lag_s if tl is not None else 0.0
        klog.infof(
            "heartbeat: cycles=%d placed=%d unschedulable=%d depth=%d "
            "active=%d express=%d breaker=%s batch=%d hbm_bytes=%d "
            "mesh=%d rung=%s shards_lost=%d invariant_violations=%d "
            "host_ms=%d dev_ms=%d xfer_top=%s margin=%.4f regret=%.2f "
            "replicas=%d conflicts=%d anomalies=%d timeline_lag_s=%.3f",
            q.scheduling_cycle,
            self._outcome_totals["placed"],
            self._outcome_totals["unschedulable"],
            len(q), active, express,
            self.device_health.state, self._cur_batch, hbm,
            self.mesh.size if self.mesh is not None else 0,
            self.ladder_rung,
            len(self.shard_health.lost()) if self.shard_health else 0,
            (
                self.invariants.violations_total()
                if self.invariants is not None else 0
            ),
            int(host_ms), int(dev_ms), xfer_top,
            q_margin, q_regret,
            self._replica_of, self.conflicts_total,
            tl_anoms, tl_lag,
        )

    def prewarm(self, widths: Optional[Sequence[int]] = None,
                pod_factory: Optional[Callable[[int], Pod]] = None) -> Dict[int, float]:
        """Pre-pay the engine's XLA compiles for every batch width the
        runtime can dispatch — the AIMD pow2 ladder (shared with bench
        warmup via codec.schema.aimd_pow2_widths) plus the express width —
        against the CURRENT snapshot shape, so the first cycle at each
        width serves traffic instead of stalling on a compile.  With a
        persistent compile cache (utils/compilecache.py) warm, each width
        is a cache hit and this is seconds, not minutes.

        Runs the engine on throwaway pods and discards the result: nothing
        commits, the rotation counter does not advance, and the resident
        device snapshot ends exactly as a normal cycle would leave it.
        Returns {width: seconds}.

        `pod_factory(i) -> Pod` should build a pod REPRESENTATIVE of the
        live workload: jit executables are keyed on every PodBatch leaf
        shape, and per-pod pad dims (selector/affinity/port/volume axes)
        grow from the pods actually encoded — warming with pods shaped
        differently from traffic pre-grows the wrong dims and the first
        real batch at each width still compiles.  Default: minimal
        cpu-request-only pods (right for homogeneous simple workloads)."""
        from kubernetes_tpu.api.factory import make_pod
        from kubernetes_tpu.codec.schema import _pow2, aimd_pow2_widths
        from kubernetes_tpu.models.batched import encode_batch_ports

        cfg = self.config
        if widths is None:
            widths = aimd_pow2_widths(
                cfg.batch_size_min if cfg.adaptive_batch else cfg.batch_size,
                cfg.batch_size,
            )
            if cfg.express_lane:
                widths = sorted(
                    set(widths) | {_pow2(max(1, cfg.express_batch_size))}
                )
        enc = self.cache.encoder
        fn = (
            self._speculative_fn
            if self._speculative_fn is not None
            else self._schedule_fn
        )
        if pod_factory is None:
            def pod_factory(i: int) -> Pod:  # noqa: F811 — default factory
                return make_pod(f"prewarm-{i}", cpu="1m")
        # extra-mask/score presence also selects a jit variant: with
        # filter/prioritize extenders or tensor framework plugins
        # configured, every live cycle passes non-None arrays — warm THAT
        # variant (all-true mask / zero score match the no-op fan-out).
        # Nominated-pod cycles still pick a transient different variant;
        # those are rare and self-limiting, not the steady state.
        fwk = self.framework
        want_mask = any(
            e.config.filter_verb or e.config.prioritize_verb
            for e in self.extenders
        ) or bool(fwk is not None and fwk.tensor_filter_plugins)
        want_score = any(
            e.config.filter_verb or e.config.prioritize_verb
            for e in self.extenders
        ) or bool(fwk is not None and fwk.tensor_score_plugins)
        timings: Dict[int, float] = {}
        for w in widths:
            t0 = time.monotonic()
            pods = [pod_factory(i) for i in range(w)]
            # the width override pins each warm batch to its own pow2
            # shape WITHOUT growing the sticky dims.B floor — runtime
            # width selection stays exactly as it would be unwarmed
            with self.cache._lock, enc.batch_width(w):
                # in-batch affinity state exactly as _encode_and_dispatch
                # builds it: its presence selects a DIFFERENT traced
                # variant, so an affinity-carrying pod_factory must warm
                # that one (and the encode ordering matters — novel term
                # topology keys register before the TP-wide tensors cut)
                aff_state = (
                    encode_batch_affinity(enc, pods)
                    if len(pods) > 1 and batch_has_pod_affinity(pods)
                    else None
                )
                batch = enc.encode_pods(pods)
                ports = encode_batch_ports(enc, pods)
                cluster, _ = self.cache.snapshot()
                dirty_rows = (
                    enc.take_dirty_rows() if self._hub is None else None
                )
            dev_cluster = self._device_update(cluster, dirty_rows)
            B, N = batch.n_pods, cluster.n_nodes
            extra_mask = np.ones((B, N), bool) if want_mask else None
            extra_score = (
                np.zeros((B, N), np.float32) if want_score else None
            )
            # index instead of unpack: the attribution variant returns a
            # third output this warm launch discards
            hosts = fn(
                dev_cluster, batch, ports, np.int32(self._last_index),
                None, extra_mask, extra_score, aff_state,
            )[0]
            jax.block_until_ready(hosts)
            timings[w] = time.monotonic() - t0
            klog.V(1).infof(
                "prewarm: width %d compiled in %.2fs", w, timings[w]
            )
        # megacycle shapes (ISSUE 12 satellite): the K x pow2-width
        # ladder, capped by megacycleBatches, so the first megacycle
        # after cold start is a cache hit instead of a fresh compile.
        # Keys are "megaKxW" strings (the plain-width keys stay ints).
        if self._mega_fn is not None and self.config.megacycle_batches > 1:
            from kubernetes_tpu.models.megacycle import stack_windows

            k_ladder = []
            k = 2
            while k <= self.config.megacycle_batches:
                k_ladder.append(k)
                k *= 2
            for K in k_ladder:
                for w in widths:
                    t0 = time.monotonic()
                    wins = [
                        [pod_factory(i + j * w) for i in range(w)]
                        for j in range(K)
                    ]
                    with self.cache._lock, enc.batch_width(w):
                        batches = [enc.encode_pods(ws) for ws in wins]
                        ports_l = [
                            encode_batch_ports(enc, ws) for ws in wins
                        ]
                        cluster, _ = self.cache.snapshot()
                        dirty_rows = (
                            enc.take_dirty_rows()
                            if self._hub is None else None
                        )
                    dev_cluster = self._device_update(
                        cluster, dirty_rows
                    )
                    li0 = np.arange(K, dtype=np.int32) * w + np.int32(
                        self._last_index
                    )
                    # index instead of unpack: the quality variant
                    # returns a third output this warm launch discards
                    hosts = self._mega_fn(
                        dev_cluster, stack_windows(batches),
                        stack_windows(ports_l), li0,
                    )[0]
                    jax.block_until_ready(hosts)
                    timings[f"mega{K}x{w}"] = time.monotonic() - t0
                    klog.V(1).infof(
                        "prewarm: megacycle %dx%d compiled in %.2fs",
                        K, w, timings[f"mega{K}x{w}"],
                    )
        return timings

    @property
    def pipeline_pending(self) -> bool:
        """True while a dispatched batch awaits its commit tail (the
        public liveness predicate for drain loops)."""
        return self._in_flight is not None

    def flush_pipeline(self) -> int:
        """Drain the double-buffer slot: fetch + commit any in-flight
        pipelined batch (or megacycle).  No-op when nothing is in
        flight.  Returns the number of pods placed from the drain."""
        inf, self._in_flight = self._in_flight, None
        if inf is None:
            return 0
        n = 0
        for st in self._commit_state_prev(inf):
            results = self._commit_tail(st)
            n += sum(1 for r in results if r.node is not None)
        return n

    def _run_pipelined(self, pods: Sequence[Pod],
                       mega: Optional[Tuple[List[List[Pod]], List[int]]]
                       = None) -> int:
        """Double-buffered cycle: apply the in-flight batch's STATE half
        (fetch + batched assume — the part the next snapshot must see),
        dispatch the new batch, then run the previous batch's side-effect
        tail while the device computes.  Device idle time shrinks to the
        fetch->dispatch gap (assume + encode), and the per-pod Python tail
        (binds, events, metrics, preemption) hides behind device compute.

        With `mega` = (windows, cycles), the new dispatch is a megacycle
        (ISSUE 12) and the in-flight slot may hold one: all K in-flight
        state commits land before the new launch encodes, and all K host
        tails overlap the new device window — host_commit fully behind
        device_execute."""
        prev, self._in_flight = self._in_flight, None
        n = 0
        staged: List[_Staged] = []
        dispatched = False
        try:
            if prev is not None:
                staged = self._commit_state_prev(prev)
            if mega is not None:
                self._in_flight = self._dispatch_megacycle(*mega)
            else:
                self._in_flight = self._encode_and_dispatch(pods)
            dispatched = True
        finally:
            if not dispatched:
                # batch k+1 was popped but never reached the device
                # (batch k's ready-fence raised, or the dispatch itself
                # did): requeue it — popped pods must never be lost
                lost = (
                    [p for w in mega[0] for p in w]
                    if mega is not None else list(pods)
                )
                self.queue.add_unschedulable_batch(
                    lost, self.queue.scheduling_cycle
                )
            # batch k's tail MUST run even if batch k+1's dispatch raises:
            # its losers were already popped from the queue (the requeue
            # happens in the tail) and its winners sit assumed-but-unbound
            for st in staged:
                results = self._commit_tail(st)
                n += sum(1 for r in results if r.node is not None)
        return n

    def run_once(self, timeout: float = 0.1) -> int:
        """Pop one cycle's batch and schedule it; returns the number of
        pods PLACED (both the gang and plain paths count placements).

        With config.pipeline_commit, plain batches double-buffer: the call
        dispatches this batch and returns the PREVIOUS batch's placements
        (flush_pipeline drains the last one); gang cycles and empty polls
        drain the pipeline first so snapshots never go stale."""
        self._maybe_heartbeat()
        self._maybe_probe_shards()
        # idle-path timeline tick (ISSUE 20): an empty queue must still
        # sample — the commit tail only runs on committed cycles, and a
        # quiet interval is exactly when a breaker/SLO excursion needs
        # surrounding samples.  Cadence-gated inside the store, so a
        # busy loop that just sampled in the commit tail pays one
        # monotonic read here.
        if self.timeline is not None:
            t_tl = time.perf_counter()
            try:
                self.timeline.maybe_sample()
            except Exception as e:  # noqa: BLE001
                klog.errorf("timeline idle tick failed: %s", e)
            finally:
                m.TIMELINE_SECONDS.inc(time.perf_counter() - t_tl)
        t_pop = time.monotonic()
        express = self.config.express_lane
        # tiered mode only adds the kwarg (an express arrival interrupts
        # the bulk wait so the express cycle below runs immediately), and
        # only for a queue that actually has tier lanes — a caller-owned
        # duck-typed queue without them never sees it
        pop_kw = (
            {"yield_to_express": True}
            if express and hasattr(self.queue, "pop_express_batch")
            else {}
        )
        if self._replica_of > 1:
            # queue-sharded replica (ISSUE 14): drain only this
            # replica's stable hash-shard — pops are disjoint across
            # replicas by construction, and every requeue of a popped
            # pod lands back on this shard
            pop_kw.update(shard=self._replica_id, of=self._replica_of)
        pods = self.queue.pop_batch(
            # adaptive mode pops at the CURRENT AIMD width; static mode
            # keeps the configured batch size
            self._cur_batch if self.config.adaptive_batch
            else self.config.batch_size,
            # with a batch in flight, don't block in the pop: its binds/
            # events/requeues must not wait out the poll timeout when the
            # queue momentarily empties (trickle arrival, burst tails)
            0.0 if self.pipeline_pending else timeout,
            self.config.batch_window_s,
            **pop_kw,
        )
        if self.invariants is not None:
            self.invariants.note_popped(pods, self.queue.scheduling_cycle)
        self._phase("pop", time.monotonic() - t_pop)
        # express lane between the bulk pop and the bulk dispatch: pending
        # latency-sensitive pods schedule (and commit) BEFORE this cycle's
        # bulk batch, and at most one small express batch runs per
        # iteration (the bulk lane's starvation guard)
        try:
            n_express = self._run_express() if express else 0
        except BaseException:
            # the just-popped bulk batch is held only in this frame: an
            # express-cycle failure must not strand it (popped pods are
            # never lost; the express cycle's own pods were requeued by
            # schedule_cycle's guard)
            self.queue.add_unschedulable_batch(
                list(pods), self.queue.scheduling_cycle
            )
            raise
        # the AIMD deadline window starts AFTER the express cycle: express
        # work must not read as a bulk overrun and shrink the bulk batch
        t_cycle0 = time.monotonic()
        if not pods:
            # idle poll: drain any in-flight batch so binds/events/requeues
            # don't wait for the next arrival; idle cycles also DECAY the
            # adaptive batch width (no pressure -> back toward baseline,
            # even when the last pop emptied the queue in one gulp)
            n = self.flush_pipeline()
            self._adapt_batch(0.0)
            return n + n_express
        # gang-eligibility is conservative: extenders and framework
        # plugins enforce verdicts the gang launch cannot consult, and an
        # outstanding preemption nomination must not be absorbed by a
        # gang (the plain path's two-pass protection, scheduler.py
        # nominated handling) — any of these routes the members through
        # the plain cycle (no atomicity) rather than risk a placement
        # the normal path would reject.  The same demotion applies while
        # the device breaker is not closed: the gang launch has its own
        # device path with no degraded engine, so during an outage
        # members schedule as plain pods (liveness over atomicity)
        # through the CPU fallback.
        gang_eligible = (
            not self.extenders
            and self.framework is None
            and not self.queue.nominated_pods()
            and (
                self.device_health.device_available
                or not self.config.cpu_fallback
            )
            # replica mode demotes gangs to plain pods (no atomicity):
            # the gang launch snapshots and commits outside the
            # sequenced reconciler section, so its claims could race a
            # sibling's — same liveness-over-atomicity policy as the
            # extender/breaker demotions above
            and self._replica_of == 1
        )
        plain = [p for p in pods
                 if not gang_eligible or self.POD_GROUP_LABEL not in p.labels]
        grouped: dict = {}
        if gang_eligible:
            for p in pods:
                gname = p.labels.get(self.POD_GROUP_LABEL)
                if gname is not None:
                    grouped.setdefault((p.namespace, gname), []).append(p)
        n = 0
        if grouped:
            # gangs first: they were popped in priority order and the
            # plain sub-cycle must not strip capacity from them.  Gang
            # launches snapshot the cache directly, so any in-flight
            # pipelined batch must land its state first
            n += self.flush_pipeline()
            from kubernetes_tpu.models.gang import GangScheduler, PodGroup

            cycle = self.queue.scheduling_cycle
            gangs = []
            for (ns, gname), members in grouped.items():
                mm = 0
                for p in members:
                    try:
                        mm = max(mm, int(
                            p.labels.get(self.POD_GROUP_MIN_MEMBER, 0)))
                    except ValueError:
                        pass
                gangs.append(
                    (PodGroup(gname, namespace=ns, min_member=mm), members)
                )
            t_cycle = time.monotonic()
            try:
                results = GangScheduler(self).schedule_gangs(gangs)
            except BaseException as e:
                # popped gang members must never be lost — but
                # schedule_gangs commits gang-by-gang, so members of
                # gangs that already committed are ASSUMED+BOUND: record
                # their success and recover only the genuinely unplaced
                # ones (re-scheduling a bound pod would double-bind and
                # double-charge the cache).  A CLASSIFIED device fault
                # feeds the breaker and demotes the unplaced members to
                # THIS cycle's plain path (which owns retry/degrade);
                # anything else requeues them and propagates.
                enc = self.cache.encoder
                unplaced = []
                for _, ms in gangs:
                    for p in ms:
                        rec = enc.pods.get((p.namespace, p.name))
                        if (
                            rec is not None
                            and rec.node_row >= 0
                            and rec.pod is not None
                            and rec.pod.spec.node_name
                        ):
                            node = rec.pod.spec.node_name
                            n += 1
                            self._outcome_totals["placed"] += 1
                            self.results.append(ScheduleResult(p, node))
                            self._record_scheduled(
                                p, node, time.monotonic() - t_cycle
                            )
                        else:
                            unplaced.append(p)
                fc = classify_device_error(e)
                if fc is None:
                    self.queue.add_unschedulable_batch(unplaced, cycle)
                    raise
                self._note_device_fault(fc, e, "gang")
                if not self._note_shard_fault(self._shard_of(e), fc):
                    self.device_health.record_failure(fc)
                    self._device_invalidate()
                plain = plain + unplaced
                gangs, results = [], []
            for (group, members), (nodes, placed) in zip(gangs, results):
                if nodes is None:
                    # gang did not reach min_member: members park in the
                    # unschedulableQ with backoff like any failed pod,
                    # with the same failure bookkeeping
                    for p in members:
                        self.queue.add_unschedulable(p, cycle)
                        self.results.append(ScheduleResult(p, None))
                        self._outcome_totals["unschedulable"] += 1
                        m.SCHEDULE_ATTEMPTS.inc(result=m.UNSCHEDULABLE)
                        self.recorder.eventf(
                            "Pod", p.namespace, p.name,
                            EVENT_TYPE_WARNING, "FailedScheduling",
                            "pod group %s/%s: %d/%d members placed",
                            group.namespace, group.name, placed,
                            group.min_member or len(members),
                        )
                    continue
                n += placed
                for p, node in zip(members, nodes):
                    if not node:
                        # surplus member beyond min_member was NOT bound:
                        # requeue (still-pending pod, not a failure) —
                        # shed-exempt like every requeue of a popped pod
                        self.queue.readd(p)
                        continue
                    # success bookkeeping identical to the plain path:
                    # Scheduled event, counters, e2e histogram, results
                    self._outcome_totals["placed"] += 1
                    self.results.append(ScheduleResult(p, node))
                    self._record_scheduled(
                        p, node, time.monotonic() - t_cycle
                    )
        if plain:
            # megacycle formation (ISSUE 12): when the control plane and
            # this window are chain-safe, pop up to K-1 more windows and
            # launch them as ONE device scan; the commit of the K winner
            # vectors runs behind the NEXT megacycle's dispatch (the
            # pipelined slot).  Any ineligible window falls back to the
            # single-cycle path below, placements identical either way.
            windows = None
            if self._megacycle_ready() and self._megacycle_safe(plain):
                windows, win_cycles = self._pop_megacycle_windows(
                    plain,
                    self._cur_batch if self.config.adaptive_batch
                    else self.config.batch_size,
                )
            if windows is not None and len(windows) > 1:
                if (
                    self.config.pipeline_commit
                    and self.framework is None
                ):
                    n += self._run_pipelined(
                        plain, mega=(windows, win_cycles)
                    )
                else:
                    n += sum(
                        1
                        for r in self.schedule_megacycle(windows, win_cycles)
                        if r.node is not None
                    )
            elif (
                self.config.pipeline_commit
                and self.config.batched_commit
                and self.framework is None
            ):
                n += self._run_pipelined(plain)
            else:
                n += sum(
                    1 for r in self.schedule_cycle(plain) if r.node is not None
                )
        # the cycle deadline budget covers the SCHEDULING work (encode ->
        # commit), not the pop wait — an idle poll must not read as an
        # overrun and shrink the batch
        self._adapt_batch(time.monotonic() - t_cycle0)
        return n + n_express

    def run(self) -> None:
        """wait.Until(scheduleOne) analog (scheduler.go:250-256)."""
        while not self._stop.is_set():
            self.run_once(timeout=0.5)
        self.flush_pipeline()

    def stop(self) -> None:
        self._stop.set()
        self.queue.close()
