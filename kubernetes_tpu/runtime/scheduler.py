"""The scheduling service: the scheduleOne loop, batched.

Mirrors Scheduler.Run / scheduleOne (ref pkg/scheduler/scheduler.go:250-593)
with the one structural change that unlocks TPU throughput: instead of one
pod per cycle, each cycle drains a batch from the queue and places it with
the sequential-commit device program (models/batched.py) — semantically the
same as running scheduleOne B times against a continuously-updated cache,
but in a single XLA launch.

Per cycle:
  1. queue.pop_batch                      (NextPod, scheduler.go:438-447)
  2. cache.snapshot -> device tensors     (the snapshot seam, :176-179)
  3. sequential-commit schedule on device
  4. per pod: assume + bind via the binder callback (async),
     or add_unschedulable on failure     (:463-475, MakeDefaultErrorFunc)
"""

from __future__ import annotations

import dataclasses
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.codec.schema import FilterConfig
from kubernetes_tpu.models.batched import encode_batch_ports, make_sequential_scheduler
from kubernetes_tpu.runtime.cache import SchedulerCache
from kubernetes_tpu.runtime.queue import PriorityQueue
from kubernetes_tpu.utils.trace import Trace

TAINT_NODE_UNSCHEDULABLE = "node.kubernetes.io/unschedulable"


@dataclass
class SchedulerConfig:
    batch_size: int = 256
    batch_window_s: float = 0.001
    percentage_of_nodes_to_score: int = 100  # TPU path scans all; knob for parity
    disable_preemption: bool = False
    weights: Optional[Sequence[float]] = None
    filter_config: FilterConfig = field(default_factory=FilterConfig)
    profile: Optional[object] = None  # config.SchedulingProfile; overrides
                                      # filter_config/weights when set

    @staticmethod
    def from_component_config(cc, interner=None) -> "SchedulerConfig":
        """Build from a KubeSchedulerConfiguration (config/types.py)."""
        profile = cc.build_profile(interner=interner)
        return SchedulerConfig(
            batch_size=cc.batch_size,
            batch_window_s=cc.batch_window_s,
            percentage_of_nodes_to_score=cc.percentage_of_nodes_to_score or 100,
            disable_preemption=cc.disable_preemption,
            weights=profile.weights_array(),
            filter_config=profile.filter_config,
            profile=profile,
        )


@dataclass
class ScheduleResult:
    pod: Pod
    node: Optional[str]          # None = unschedulable
    generation: int = 0


class Scheduler:
    """Binder: callable (pod, node_name) -> bool (the POST .../binding analog,
    scheduler.go:411-435).  A False/raising binder triggers ForgetPod + requeue."""

    def __init__(
        self,
        cache: Optional[SchedulerCache] = None,
        queue: Optional[PriorityQueue] = None,
        binder: Optional[Callable[[Pod, str], bool]] = None,
        config: Optional[SchedulerConfig] = None,
    ):
        # NB: PriorityQueue defines __len__, so `queue or PriorityQueue()`
        # would silently replace an *empty* caller-owned queue
        self.cache = cache if cache is not None else SchedulerCache()
        self.queue = queue if queue is not None else PriorityQueue()
        self.binder = binder if binder is not None else (lambda pod, node: True)
        self.config = config if config is not None else SchedulerConfig()
        enc = self.cache.encoder
        prof = self.config.profile
        if prof is not None:
            self.config.filter_config = prof.filter_config
            self.config.weights = prof.weights_array()
        enc.hard_pod_affinity_weight = self.config.filter_config.hard_pod_affinity_weight
        self._unsched_key = enc.interner.intern(TAINT_NODE_UNSCHEDULABLE)
        self._schedule_fn = make_sequential_scheduler(
            cfg=self.config.filter_config,
            weights=self.config.weights,
            unsched_taint_key=self._unsched_key,
            zone_key_id=enc.zone_key,
            score_cfg=prof.score_config if prof is not None else None,
        )
        self._last_index = 0
        self._stop = threading.Event()
        self.results: List[ScheduleResult] = []

    # ------------------------------------------------------------- one cycle

    def schedule_cycle(self, pods: Sequence[Pod]) -> List[ScheduleResult]:
        """Place a batch of pods against the current cache state; assume+bind
        winners, requeue losers.  Returns per-pod results."""
        if not pods:
            return []
        trace = Trace("schedule_cycle", pods=len(pods))
        enc = self.cache.encoder
        cycle = self.queue.scheduling_cycle
        with self.cache._lock:
            batch = enc.encode_pods(pods)
            ports = encode_batch_ports(enc, pods, enc.dims.N)
            cluster, generation = self.cache.snapshot()
        trace.step("encode")
        hosts, _ = self._schedule_fn(
            cluster, batch, ports, np.int32(self._last_index)
        )
        hosts = np.asarray(hosts)
        self._last_index += len(pods)
        trace.step("device")
        results = []
        for i, pod in enumerate(pods):
            row = int(hosts[i])
            if row < 0:
                # FitError path: park in unschedulableQ with backoff
                # (factory.go MakeDefaultErrorFunc)
                self.queue.add_unschedulable(pod, cycle)
                results.append(ScheduleResult(pod, None, generation))
                continue
            node_name = enc.row_name(row)
            assumed = dataclasses.replace(
                pod, spec=dataclasses.replace(pod.spec, node_name=node_name)
            )
            self.cache.assume_pod(assumed)
            ok = False
            try:
                ok = self.binder(assumed, node_name)
            except Exception:
                ok = False
            if not ok:
                self.cache.forget_pod(assumed)
                self.queue.add_unschedulable(pod, cycle)
                results.append(ScheduleResult(pod, None, generation))
            else:
                results.append(ScheduleResult(pod, node_name, generation))
        trace.step("commit")
        trace.log_if_long(0.1)
        self.results.extend(results)
        return results

    # ------------------------------------------------------------- run loop

    def run_once(self, timeout: float = 0.1) -> int:
        pods = self.queue.pop_batch(
            self.config.batch_size, timeout, self.config.batch_window_s
        )
        return len(self.schedule_cycle(pods))

    def run(self) -> None:
        """wait.Until(scheduleOne) analog (scheduler.go:250-256)."""
        while not self._stop.is_set():
            self.run_once(timeout=0.5)

    def stop(self) -> None:
        self._stop.set()
        self.queue.close()
