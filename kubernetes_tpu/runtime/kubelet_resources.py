"""Kubelet resource management: cgroups/QoS, volume manager, stats.

Reference:
  * pkg/kubelet/cm/cgroup_manager_linux.go (593 LoC) +
    qos_container_manager_linux.go + helpers_linux.go: the kubepods
    cgroup hierarchy — Guaranteed pods parented directly under
    ``kubepods``, Burstable under ``kubepods/burstable``, BestEffort
    under ``kubepods/besteffort``; cpu.shares from requests
    (MilliCPUToShares: milli*1024/1000, floor MinShares=2), cpu quota +
    memory limits from limits.  This framework has no OS cgroupfs to
    write, so the hierarchy is held AS DATA — the accounting model the
    rest of the kubelet (eviction, stats) reads.
  * pkg/kubelet/volumemanager (3.3k LoC): desired-state-of-world vs
    actual-state-of-world reconciler — a pod's PV-backed volume waits
    for the attach-detach controller to surface the attachment on
    node.status.volumesAttached, then mounts; pod deletion unmounts.
  * pkg/kubelet/stats + cadvisor seam: OBSERVED per-pod usage (not the
    declared requests) feeding /stats/summary — here a pluggable
    ``usage_fn`` stands in for cadvisor, and ``publish`` surfaces the
    samples to the store so the metrics.k8s.io endpoint serves measured
    values; eviction ranks by observed-over-request
    (eviction/helpers.go rankMemoryPressure).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import Pod, qos_class
from kubernetes_tpu.runtime.cluster import ConflictError, LocalCluster

MIN_SHARES = 2           # cm/helpers_linux.go MinShares
SHARES_PER_CPU = 1024    # SharesPerCPU
QUOTA_PERIOD = 100000    # QuotaPeriod (100ms, microseconds)


def milli_cpu_to_shares(milli: float) -> int:
    """MilliCPUToShares (helpers_linux.go:52-63)."""
    if milli <= 0:
        return MIN_SHARES
    return max(MIN_SHARES, int(milli * SHARES_PER_CPU / 1000))


def milli_cpu_to_quota(milli: float) -> Optional[int]:
    """MilliCPUToQuota: cpu limit -> CFS quota per period (helpers_linux.go
    :37-50); no limit -> no quota."""
    if milli <= 0:
        return None
    return max(1000, int(milli * QUOTA_PERIOD / 1000))  # minQuotaPeriod


def _pod_milli_requests(pod: Pod) -> float:
    return sum(float(c.requests["cpu"].milli)
               for c in pod.spec.containers if "cpu" in c.requests)


def _pod_milli_limits(pod: Pod) -> float:
    return sum(float(c.limits["cpu"].milli)
               for c in pod.spec.containers if "cpu" in c.limits)


def _pod_memory_limits(pod: Pod) -> Optional[float]:
    """Sum of container memory limits; None unless EVERY container sets
    one (an unlimited container makes the pod cgroup unlimited)."""
    total = 0.0
    for c in pod.spec.containers:
        if "memory" not in c.limits:
            return None
        total += float(c.limits["memory"])
    return total if pod.spec.containers else None


@dataclasses.dataclass
class Cgroup:
    """One node of the hierarchy, as data (cgroup_manager's CgroupConfig)."""

    name: str                       # slash path, e.g. kubepods/burstable/pod<uid>
    cpu_shares: int = MIN_SHARES
    cpu_quota: Optional[int] = None       # CFS quota (us per 100ms period)
    memory_limit: Optional[float] = None  # bytes; None = unlimited
    children: Dict[str, "Cgroup"] = dataclasses.field(default_factory=dict)


class CgroupManager:
    """The kubepods hierarchy: qos_container_manager's structure +
    cgroup_manager's per-cgroup resource math, held as data."""

    def __init__(self):
        self.root = Cgroup("kubepods")
        self.root.children["burstable"] = Cgroup("kubepods/burstable")
        self.root.children["besteffort"] = Cgroup(
            "kubepods/besteffort", cpu_shares=MIN_SHARES)
        self._pod_parent: Dict[str, Cgroup] = {}

    def pod_cgroup_name(self, pod: Pod) -> str:
        qos = qos_class(pod)
        # uid when present (the reference's pod<UID>); otherwise ns+name so
        # same-named pods in different namespaces can never collide
        ident = pod.metadata.uid or f"{pod.namespace}-{pod.name}"
        leaf = f"pod{ident}"
        if qos == "Guaranteed":
            return f"kubepods/{leaf}"
        return f"kubepods/{qos.lower()}/{leaf}"

    def _parent_for(self, pod: Pod) -> Cgroup:
        qos = qos_class(pod)
        if qos == "Guaranteed":
            return self.root
        return self.root.children[qos.lower()]

    def create_pod_cgroup(self, pod: Pod) -> Cgroup:
        """ResourceConfigForPod (helpers_linux.go:85-160): shares from
        requests, quota from cpu limits, memory limit iff every container
        sets one."""
        name = self.pod_cgroup_name(pod)
        cg = Cgroup(
            name,
            cpu_shares=milli_cpu_to_shares(_pod_milli_requests(pod)),
            cpu_quota=milli_cpu_to_quota(_pod_milli_limits(pod)),
            memory_limit=_pod_memory_limits(pod),
        )
        parent = self._parent_for(pod)
        parent.children[name.rsplit("/", 1)[-1]] = cg
        self._pod_parent[name] = parent
        self._update_qos_shares()
        return cg

    def remove_pod_cgroup(self, pod: Pod) -> None:
        name = self.pod_cgroup_name(pod)
        parent = self._pod_parent.pop(name, None)
        if parent is not None:
            parent.children.pop(name.rsplit("/", 1)[-1], None)
            self._update_qos_shares()

    def _update_qos_shares(self) -> None:
        """UpdateCgroups (qos_container_manager_linux.go:get*CPURequests):
        burstable shares track the sum of its pods' request-derived
        shares; besteffort stays at MinShares."""
        burst = self.root.children["burstable"]
        total = sum(c.cpu_shares for c in burst.children.values())
        burst.cpu_shares = max(MIN_SHARES, total)

    def get(self, name: str) -> Optional[Cgroup]:
        node = self.root
        parts = name.split("/")
        if parts[0] != "kubepods":
            return None
        for p in parts[1:]:
            node = node.children.get(p)
            if node is None:
                return None
        return node


# ------------------------------------------------------------ volumemanager

WAIT_FOR_ATTACH = "WaitForAttach"
MOUNTED = "Mounted"


class VolumeManager:
    """Desired-vs-actual volume reconciler for ONE node
    (volumemanager/reconciler/reconciler.go, collapsed to the state
    machine): a PV-backed volume is mountable once the attach-detach
    controller lists the PV on node.status.volumesAttached; non-PV
    volumes (emptyDir and friends) mount immediately."""

    def __init__(self, cluster: LocalCluster, node_name: str):
        self.cluster = cluster
        self.node_name = node_name
        # (pod_key, volume_name_or_claim) -> state
        self.state: Dict[Tuple[tuple, str], str] = {}

    def _desired(self) -> Dict[Tuple[tuple, str], Optional[str]]:
        """(pod key, volume id) -> PV name (None for non-PV volumes)."""
        out: Dict[Tuple[tuple, str], Optional[str]] = {}
        for p in self.cluster.list("pods"):
            if p.spec.node_name != self.node_name:
                continue
            if p.status.phase in ("Succeeded", "Failed"):
                continue
            key = (p.namespace, p.name)
            for i, v in enumerate(p.spec.volumes):
                # key by the VOLUME slot (name-or-index), never by claim:
                # a pod may mount one claim through two volume entries and
                # each must reach Mounted for all_mounted to hold
                vid = v.get("name") or f"vol-{i}"
                claim = (v.get("persistentVolumeClaim") or {})
                cn = claim.get("claimName")
                if cn:
                    pvc = self.cluster.get(
                        "persistentvolumeclaims", p.namespace, cn)
                    pv = (pvc.volume_name
                          if pvc is not None and pvc.volume_name else None)
                    out[(key, vid)] = pv or ""
                else:
                    out[(key, vid)] = None
        return out

    def sync(self) -> Dict[Tuple[tuple, str], str]:
        """One reconcile pass; returns the actual-state map."""
        desired = self._desired()
        node = self.cluster.get("nodes", "", self.node_name)
        attached = set(node.status.volumes_attached) if node else set()
        for dkey, pv in desired.items():
            if pv is None:
                self.state[dkey] = MOUNTED       # emptyDir-class: no attach
            elif pv and pv in attached:
                self.state[dkey] = MOUNTED       # attach observed -> mount
            elif self.state.get(dkey) != MOUNTED:
                # unbound claim or attach not yet surfaced; an
                # already-MOUNTED volume stays mounted after a detach
                # blip (unmount happens on pod departure, not here)
                self.state[dkey] = WAIT_FOR_ATTACH
        # unmount volumes whose pod left (the reconciler's unmount arm)
        for dkey in list(self.state):
            if dkey not in desired:
                del self.state[dkey]
        return dict(self.state)

    def all_mounted(self, pod: Pod) -> bool:
        """WaitForAttachAndMount's answer for one pod (volume_manager.go):
        every declared volume reached Mounted."""
        self.sync()
        key = (pod.namespace, pod.name)
        states = [s for (k, _v), s in self.state.items() if k == key]
        n_declared = len(pod.spec.volumes)
        return len(states) >= n_declared and all(
            s == MOUNTED for s in states)


# ------------------------------------------------------------------- stats


class StatsProvider:
    """Observed usage (the cadvisor seam, pkg/kubelet/stats): usage_fn
    stands in for the measurement source; publish() surfaces samples to
    the store as podmetrics objects so metrics.k8s.io serves MEASURED
    values instead of declared requests."""

    def __init__(self, cluster: LocalCluster, node_name: str,
                 usage_fn: Optional[Callable] = None):
        self.cluster = cluster
        self.node_name = node_name
        self.usage_fn = usage_fn or self._default_usage

    @staticmethod
    def _default_usage(pod: Pod) -> Tuple[float, float]:
        """Deterministic 'measured' usage distinct from the declared
        requests: a per-pod utilization factor in [0.55, 0.95) derived
        from the pod identity (the hollow-world cadvisor).  Containers
        with NO request still consume (the scheduler's non-zero
        defaults, util/non_zero.go) — which is exactly why BestEffort
        pods always exceed their (zero) requests and rank first for
        eviction."""
        import zlib

        from kubernetes_tpu.api.types import (
            DEFAULT_MEMORY_REQUEST,
            DEFAULT_MILLI_CPU_REQUEST,
        )

        cpu = mem = 0.0
        for c in pod.spec.containers:
            cpu += (float(c.requests["cpu"].milli) if "cpu" in c.requests
                    else DEFAULT_MILLI_CPU_REQUEST)
            mem += (float(c.requests["memory"]) if "memory" in c.requests
                    else DEFAULT_MEMORY_REQUEST)
        # crc32, not hash(): PYTHONHASHSEED randomizes str hashing per
        # process, which would make "measured" usage differ run to run
        f = 0.55 + (zlib.crc32(
            f"{pod.namespace}/{pod.name}".encode()) % 40) / 100.0
        return cpu * f, mem * f

    def pod_stats(self) -> Dict[tuple, Tuple[float, float]]:
        out = {}
        for p in self.cluster.list("pods"):
            if p.spec.node_name != self.node_name:
                continue
            if p.status.phase != "Running":
                continue
            out[(p.namespace, p.name)] = self.usage_fn(p)
        return out

    def node_summary(self) -> Tuple[float, float]:
        stats = self.pod_stats().values()
        return (sum(c for c, _ in stats), sum(m for _, m in stats))

    def publish(self) -> int:
        """Write podmetrics samples into the store (the metrics-server
        scrape path collapsed: kubelet /stats/summary -> metrics.k8s.io)
        and reap THIS node's samples for pods no longer reporting — a
        departed pod must not keep serving stale 'measured' usage.
        Returns samples written."""
        self.cluster.register_kind("podmetrics")
        stats = self.pod_stats()
        n = 0
        for (ns, name), (cpu, mem) in stats.items():
            sample = {
                "namespace": ns, "name": name,
                "node": self.node_name,
                "cpu_milli": round(cpu, 3), "memory_bytes": round(mem),
            }
            try:
                self.cluster.create("podmetrics", sample)
            except ConflictError:
                self.cluster.update("podmetrics", sample)
            n += 1
        for s in list(self.cluster.list("podmetrics")):
            if (s.get("node") == self.node_name
                    and (s.get("namespace"), s.get("name")) not in stats):
                self.cluster.delete(
                    "podmetrics", s.get("namespace", ""), s.get("name", ""))
        return n


def rank_for_memory_eviction(pods: List[Pod], usage_fn: Callable,
                             ) -> List[Tuple[Pod, float]]:
    """eviction/helpers.go rankMemoryPressure: order by (1) whether
    memory usage exceeds requests (exceeders first), (2) pod priority
    (lower first), (3) usage-over-request (larger first).  Returns
    (pod, usage_minus_request) pairs so callers share the one exceeder
    predicate (over > 0)."""
    scored = []
    for pod in pods:
        _cpu, mem = usage_fn(pod)
        req = sum(float(c.requests["memory"])
                  for c in pod.spec.containers if "memory" in c.requests)
        scored.append((pod, mem - req))
    scored.sort(key=lambda po: (0 if po[1] > 0 else 1,
                                po[0].spec.priority, -po[1]))
    return scored
