"""Trace-driven scenario engine (ISSUE 18): replay real or synthetic
cluster traces through the LIVE scheduler, compose cluster-lifecycle
chaos at trace time, and score the run with the invariant checker as
the pass/fail oracle.

Three layers:

1.  **Trace frontend** — `load_trace(path)` reads a cluster trace in
    CSV or JSON.  Column names are resolved through an alias table
    covering the Alibaba cluster-trace (``start_time``/``plan_cpu``/
    ``plan_mem``) and Google cluster-trace (``submit_time``/
    ``cpu_request``/``memory_request``/``scheduling_class``) shapes, so
    a trimmed export of either replays without massaging.
    `synthesize_trace(seed, ...)` emits the SAME `TraceEvent` schema
    from a seeded generator (Poisson arrivals, optional diurnal rate
    modulation, exponential lifetimes, a small resource catalog), so
    synthetic and real traces are interchangeable downstream.

2.  **Replay** — `ScenarioRunner` owns a cluster + live scheduler
    (batched commit, AIMD adaptive batch, invariant checks on) and
    replays a trace against it under a deterministic virtual clock:
    event ORDER and virtual timestamps come from the trace alone;
    `compression` only rescales virtual seconds to wall seconds
    (compression=60 replays an hour-long trace in a minute).  Chaos is
    injected as ``(virtual_t, callable)`` pairs interleaved with
    arrivals — the callables are typically bound methods of
    `runtime.chaos.Disruptions` (rolling_drain / zone_outage), so a
    scenario is "this trace, and at t=300 the upgrade monkey drains
    half the fleet".

3.  **Scoring** — the runner watches the store and banks per-pod bind
    and displacement timestamps, producing: displaced-pod reschedule
    p50/p99, goodput ratio during the chaos window vs before it,
    time-to-drain after the last arrival, shed/lost accounting (lost
    MUST be zero: conservation), and the scheduler's own invariant
    summary (violations MUST be zero).  Pass ``ledger`` to record every
    cycle for the offline ``bench.py --replay`` bit-identity gate.

`run_scenario(kind, ...)` packages the four named campaigns — drain,
zone, diurnal, trace — behind one call; `bench.py --scenario` is a thin
CLI over it and tests/test_scenario.py drives it directly.
"""

from __future__ import annotations

import csv
import dataclasses
import heapq
import json
import math
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api.factory import ZONE_KEY, make_node, make_pod
from kubernetes_tpu.runtime.cluster import (
    DISPLACED_BY_ANNOTATION,
    LocalCluster,
    make_cluster_binder,
    wire_scheduler,
)

# ----------------------------------------------------------- trace schema


@dataclass(frozen=True)
class TraceEvent:
    """One trace row, normalized.  kind "arrival" submits a pod at
    virtual time `t`; kind "evict" is a workload-initiated kill of a
    previously arrived pod (the trace's own terminations, distinct from
    chaos-driven displacement).  `lifetime_s` None = runs to the end of
    the scenario; otherwise the pod completes (phase Succeeded) that
    many virtual seconds after it BINDS — lifetimes model run time, and
    a pod that never starts never finishes."""

    t: float                        # virtual seconds from trace start
    name: str
    kind: str = "arrival"           # "arrival" | "evict"
    namespace: str = "default"
    cpu: str = "500m"               # resource vector (factory strings)
    mem: str = "512Mi"
    priority: int = 0
    lifetime_s: Optional[float] = None


# Column aliases, checked in order: first present wins.  Covers the
# Alibaba cluster-trace batch_task table and the Google cluster-data
# task_events table, plus the obvious generic names.
_COLS = {
    "t": ("t", "time", "timestamp", "start_time", "submit_time",
          "arrive_time", "create_time"),
    "name": ("name", "pod", "pod_name", "task_name", "job_name",
             "job_id", "task_id", "instance_name", "collection_id"),
    "namespace": ("namespace", "ns", "user", "tenant"),
    "cpu": ("cpu", "plan_cpu", "cpu_request", "request_cpu", "cpus",
            "resource_request_cpu"),
    "mem": ("mem", "memory", "plan_mem", "memory_request",
            "request_memory", "resource_request_memory"),
    "priority": ("priority", "scheduling_class", "sched_class", "qos"),
    "lifetime": ("lifetime", "lifetime_s", "duration", "run_time",
                 "runtime"),
    "end": ("end_time", "finish_time"),
    "kind": ("kind", "event_type", "event", "type", "status"),
}

_EVICT_VALUES = {"evict", "evicted", "eviction", "kill", "killed", "fail"}


def _pick(row: dict, key: str):
    for alias in _COLS[key]:
        if alias in row and row[alias] not in (None, ""):
            return row[alias]
    return None


def _norm_cpu(v, scale: float) -> str:
    """Numeric cpu -> a factory request string.  Alibaba plan_cpu is
    cores*100 and Google requests are normalized [0,1] — `cpu_scale`
    maps whatever unit the trace uses onto cores; the scaled value is
    emitted in millicores."""
    if v is None:
        return "500m"
    try:
        cores = float(v) * scale
    except (TypeError, ValueError):
        return str(v)            # already a k8s quantity string
    return f"{max(1, int(round(cores * 1000)))}m"


def _norm_mem(v, scale: float) -> str:
    """Numeric mem -> Mi after scaling (`mem_scale` maps trace units
    onto MiB)."""
    if v is None:
        return "512Mi"
    try:
        mib = float(v) * scale
    except (TypeError, ValueError):
        return str(v)
    return f"{max(1, int(round(mib)))}Mi"


def load_trace(path: str, *, cpu_scale: float = 1.0,
               mem_scale: float = 1.0,
               limit: Optional[int] = None) -> List[TraceEvent]:
    """Load a cluster trace (CSV with a header row, a JSON array, or
    JSON lines) into the normalized TraceEvent schema.  Times are
    rebased so the first arrival is t=0; rows whose kind column matches
    an eviction value become "evict" events; an end-time column (minus
    start) becomes the lifetime when no explicit lifetime column
    exists.  Rows without a name get one synthesized from their index
    (traces keyed on numeric job ids stay usable)."""
    rows: List[dict] = []
    if path.endswith(".json") or path.endswith(".jsonl"):
        with open(path) as f:
            head = f.read(1)
            f.seek(0)
            if head == "[":
                rows = json.load(f)
            else:
                rows = [json.loads(line) for line in f if line.strip()]
    else:
        with open(path, newline="") as f:
            rows = list(csv.DictReader(f))
    events: List[TraceEvent] = []
    for i, row in enumerate(rows):
        if limit is not None and i >= limit:
            break
        t = float(_pick(row, "t") or 0.0)
        kind_raw = str(_pick(row, "kind") or "").strip().lower()
        kind = "evict" if kind_raw in _EVICT_VALUES else "arrival"
        lifetime = _pick(row, "lifetime")
        if lifetime is None:
            end = _pick(row, "end")
            if end is not None:
                try:
                    lifetime = max(0.0, float(end) - t)
                except (TypeError, ValueError):
                    lifetime = None
        events.append(TraceEvent(
            t=t,
            name=str(_pick(row, "name") or f"trace-{i}"),
            kind=kind,
            namespace=str(_pick(row, "namespace") or "default"),
            cpu=_norm_cpu(_pick(row, "cpu"), cpu_scale),
            mem=_norm_mem(_pick(row, "mem"), mem_scale),
            priority=int(float(_pick(row, "priority") or 0)),
            lifetime_s=float(lifetime) if lifetime is not None else None,
        ))
    events.sort(key=lambda e: (e.t, e.name))
    if events:
        t0 = events[0].t
        if t0:
            events = [dataclasses.replace(e, t=e.t - t0) for e in events]
    return events


# the synthetic resource catalog: (weight, cpu, mem) — small pods
# dominate, with a tail of chunky ones, like every real trace
_CATALOG: Sequence[Tuple[int, str, str]] = (
    (6, "250m", "256Mi"),
    (3, "500m", "1Gi"),
    (2, "1",    "2Gi"),
    (1, "2",    "4Gi"),
)


def synthesize_trace(
    seed: int,
    count: int = 200,
    rate: float = 50.0,
    mean_lifetime_s: float = 30.0,
    hi_priority_fraction: float = 0.1,
    diurnal: Optional[Tuple[float, float]] = None,
    prefix: str = "syn",
) -> List[TraceEvent]:
    """Seeded synthetic trace in the same schema: Poisson arrivals at
    `rate`/s (exponential inter-arrival), exponential lifetimes around
    `mean_lifetime_s` (0 disables completion), resource vectors drawn
    from a weighted catalog, ~`hi_priority_fraction` of pods at
    priority 100.  `diurnal=(period_s, amplitude)` modulates the
    arrival rate sinusoidally — r(t) = rate*(1 + a*sin(2πt/period)) —
    by thinning/stretching inter-arrival draws, the load swing that
    drives AIMD batch breathing.  Same seed, same trace, always."""
    rng = random.Random(seed)
    bag: List[Tuple[str, str]] = []
    for w, cpu, mem in _CATALOG:
        bag.extend([(cpu, mem)] * w)
    events: List[TraceEvent] = []
    t = 0.0
    for i in range(count):
        r = rate
        if diurnal is not None:
            period, amp = diurnal
            r = rate * (1.0 + max(0.0, min(amp, 0.999))
                        * math.sin(2.0 * math.pi * t / period))
        t += rng.expovariate(max(r, 1e-6))
        cpu, mem = rng.choice(bag)
        life = (rng.expovariate(1.0 / mean_lifetime_s)
                if mean_lifetime_s > 0 else None)
        events.append(TraceEvent(
            t=t,
            name=f"{prefix}-{i}",
            cpu=cpu,
            mem=mem,
            priority=100 if rng.random() < hi_priority_fraction else 0,
            lifetime_s=life,
        ))
    return events


# ------------------------------------------------------------- the runner


@dataclass
class ScenarioResult:
    """What a replay banks.  `lost` and `violations` are the pass/fail
    oracle: both MUST be zero — every arrived pod is bound, completed,
    shed (accounted), evicted by the trace, or still queued; nothing
    vanishes, and the online conservation/double-bind/capacity checks
    all held."""

    arrivals: int = 0
    bound: int = 0                  # distinct pods that ever bound
    completed: int = 0
    trace_evictions: int = 0
    shed: int = 0
    queued_end: int = 0             # still in queue at scenario end
    lost: int = 0
    violations: int = 0
    displaced: int = 0
    redisplaced: int = 0            # displacement of an already-displaced pod
    rescheduled: int = 0            # displaced pods that rebound
    displaced_unrescheduled: int = 0
    reschedule_ms: Dict[str, float] = field(default_factory=dict)
    first_bind_ms: Dict[str, float] = field(default_factory=dict)
    goodput_before: float = 0.0     # binds/s before the chaos window
    goodput_during: float = 0.0     # binds/s inside it
    goodput_ratio: float = 1.0      # during/before (1.0 when no chaos)
    time_to_drain_s: float = 0.0    # last arrival -> queue empty
    wall_s: float = 0.0
    virtual_s: float = 0.0
    chaos: List[dict] = field(default_factory=list)
    invariants: Optional[dict] = None
    ledger: Optional[dict] = None
    # the autoscale campaign (ISSUE 19): controller summary + the fleet
    # size curve sampled at each actuation record, so callers can assert
    # the cluster grew AND shrank with the load
    autoscaler: Optional[dict] = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _pct(samples: List[float]) -> Dict[str, float]:
    """p50/p99/max over ms samples (bench.py's shape, local so the
    runner has no bench dependency)."""
    if not samples:
        return {"p50": 0.0, "p99": 0.0, "max": 0.0, "n": 0}
    s = sorted(samples)
    def q(p: float) -> float:
        return s[min(len(s) - 1, int(math.ceil(p * len(s))) - 1)]
    return {"p50": round(q(0.50), 3), "p99": round(q(0.99), 3),
            "max": round(s[-1], 3), "n": len(s)}


class ScenarioRunner:
    """Own a cluster + live scheduler and replay traces against it.

    The scheduler runs the production configuration under test: batched
    commit, AIMD adaptive batch sizing, bounded queue (optional),
    invariant checks on.  A store watch stamps wall-clock bind and
    displacement times per pod; `replay()` converts them into the
    recovery metrics.  Construct once per scenario — the runner owns
    the scheduler thread and must be `close()`d (or used as a context
    manager)."""

    def __init__(
        self,
        nodes: int = 16,
        node_cpu: str = "16",
        node_mem: str = "64Gi",
        node_pods: int = 256,
        zones: int = 2,
        capacity: Optional[int] = None,
        batch_size: int = 64,
        batch_size_min: int = 8,
        compression: float = 1.0,
        seed: int = 0,
        ledger=None,
        bind_sleep: float = 0.0,
        config_overrides: Optional[dict] = None,
    ):
        from kubernetes_tpu.runtime.cache import SchedulerCache
        from kubernetes_tpu.runtime.queue import PodBackoff, PriorityQueue
        from kubernetes_tpu.runtime.scheduler import (
            Scheduler,
            SchedulerConfig,
        )

        self.compression = max(float(compression), 1e-9)
        self.seed = seed
        self.cluster = LocalCluster()
        for i in range(nodes):
            self.cluster.add_node(make_node(
                f"node-{i}", cpu=node_cpu, mem=node_mem, pods=node_pods,
                labels={ZONE_KEY: f"zone-{i % max(zones, 1)}"},
            ))
        inner = make_cluster_binder(self.cluster)
        if bind_sleep > 0:
            def binder(pod, node):
                time.sleep(bind_sleep)   # a throttled apiserver
                return inner(pod, node)
        else:
            binder = inner
        self.shed: List[Tuple[str, str]] = []
        # config_overrides lets a campaign turn on extra subsystems (the
        # autoscale campaign enables the capacity planner with a short
        # solve interval) without widening the runner signature per knob
        cfg_kwargs = dict(
            batch_size=batch_size,
            batch_window_s=0.0,
            disable_preemption=True,
            batched_commit=True,
            pipeline_commit=ledger is not None,
            adaptive_batch=True,
            batch_size_min=batch_size_min,
            cycle_deadline_s=2.0,
        )
        cfg_kwargs.update(config_overrides or {})
        self.scheduler = Scheduler(
            cache=SchedulerCache(),
            queue=PriorityQueue(
                capacity=capacity,
                backoff=PodBackoff(initial=0.01, max_duration=0.05),
            ),
            binder=binder,
            config=SchedulerConfig(**cfg_kwargs),
            ledger=ledger,
        )
        self._ledger = ledger
        self.scheduler.queue.on_shed = (
            lambda p, r: self.shed.append((p.name, r))
        )
        # --- the observation watch: wall-clock bind / displacement /
        # completion stamps per pod.  Registered BEFORE wire_scheduler so
        # its view is never behind the scheduler's.
        self._obs_lock = threading.Lock()
        self._bind_wall: Dict[Tuple[str, str], float] = {}
        self._bind_times: List[float] = []       # every (re)bind, for goodput
        self._displace_wall: Dict[Tuple[str, str], float] = {}
        self._displaced_seen: set = set()
        self._redisplaced = 0
        self._resched_ms: List[float] = []
        self._resched_wall: List[float] = []
        self._event_mark: Optional[float] = None
        self._completed: set = set()
        self.cluster.watch(self._observe)
        wire_scheduler(self.cluster, self.scheduler)
        self._thread = threading.Thread(
            target=self.scheduler.run, daemon=True,
            name="scenario-scheduler",
        )
        self._thread.start()

    # -- context manager ------------------------------------------------
    def __enter__(self) -> "ScenarioRunner":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        self.scheduler.stop()
        self._thread.join(timeout=10.0)
        if self._ledger is not None:
            self._ledger.flush(30.0)

    # -- the store observer ---------------------------------------------
    def _observe(self, event: str, kind: str, obj) -> None:
        if kind != "pods" or obj is None:
            return
        key = (obj.namespace, obj.name)
        now = time.monotonic()
        with self._obs_lock:
            if obj.status.phase in ("Succeeded", "Failed"):
                self._completed.add(key)
                return
            if obj.spec.node_name:
                if key not in self._bind_wall:
                    self._bind_wall[key] = now
                self._bind_times.append(now)
                t0 = self._displace_wall.pop(key, None)
                if t0 is not None:
                    self._resched_ms.append((now - t0) * 1000.0)
                    self._resched_wall.append(now)
            elif (event == "MODIFIED"
                  and obj.metadata.annotations.get(DISPLACED_BY_ANNOTATION)):
                if key in self._displace_wall:
                    return           # displaced again before rebinding
                if key in self._displaced_seen:
                    self._redisplaced += 1
                self._displaced_seen.add(key)
                self._displace_wall[key] = now
                self._bind_wall.pop(key, None)   # must rebind to count again

    # -- helpers ---------------------------------------------------------
    def bound_count(self) -> int:
        return sum(
            1 for p in self.cluster.list("pods")
            if p.spec.node_name
            and p.status.phase not in ("Succeeded", "Failed")
        )

    def _complete(self, namespace: str, name: str) -> bool:
        """Trace-lifetime completion: flip the pod to Succeeded through
        the store, which routes it out of cache + queue (the completed-
        pod path in wire_scheduler) and frees its node."""
        with self.cluster._lock:
            cur = self.cluster.get("pods", namespace, name)
            if cur is None or cur.status.phase in ("Succeeded", "Failed"):
                return False
            self.cluster.update("pods", dataclasses.replace(
                cur,
                status=dataclasses.replace(cur.status, phase="Succeeded"),
            ))
            return True

    def mark_event_start(self) -> None:
        """Stamp the ACTUAL start of a disruption from inside a chaos
        callable.  A campaign that first waits for a loaded cluster
        (await_bound — which also absorbs first-cycle compiles) calls
        this after the wait, so the goodput window measures the
        disruption, not the warm-up it deliberately sat out."""
        self._event_mark = time.monotonic()
        self._tick_timeline()

    def _tick_timeline(self) -> None:
        """Offer the timeline store a cadence-gated sample at THIS
        moment.  Called at every chaos-window edge so the ±1-interval
        alignment between chaos marks and sampled points holds by
        construction even while the scheduler thread is parked in a
        queue pop (its own tick only runs at cycle/idle boundaries):
        either a fresh sample lands now, or the gate proves one
        already exists within `interval_s`."""
        tl = getattr(self.scheduler, "timeline", None)
        if tl is None:
            return
        try:
            tl.maybe_sample()
        except Exception:  # noqa: BLE001 — observability only
            pass

    def _mark_chaos(self, edge: str, t: float, **fields) -> None:
        """Annotate one chaos-window edge on the scheduler's metrics
        timeline (ISSUE 20) at its exact wall time.  Best-effort: a
        disabled timeline must not change a campaign."""
        tl = getattr(self.scheduler, "timeline", None)
        if tl is None:
            return
        try:
            self._tick_timeline()
            tl.annotate("chaos", f"window {edge}", t=t, edge=edge,
                        **fields)
        except Exception:  # noqa: BLE001 — observability only
            pass

    def export_timeline(self, path: str) -> int:
        """Bank the scheduler's timeline store as JSONL (ISSUE 20: the
        longitudinal artifact next to the trace/ledger ones).  Returns
        the number of records written, 0 when the timeline is off."""
        tl = getattr(self.scheduler, "timeline", None)
        if tl is None:
            return 0
        return tl.export_jsonl(path)

    def await_bound(self, n: int, timeout_s: float = 10.0) -> int:
        """Block (bounded) until at least `n` pods are live-bound —
        campaigns use it inside a chaos callable so the disruption hits
        a LOADED cluster whatever the compression; returns the count."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            c = self.bound_count()
            if c >= n:
                return c
            time.sleep(0.005)
        return self.bound_count()

    def wait_drained(self, timeout_s: float = 30.0) -> float:
        """Block until nothing schedulable remains (an in-flight
        pipelined batch counts); returns the wall seconds it took."""
        t0 = time.monotonic()
        deadline = t0 + timeout_s
        q = self.scheduler.queue
        inv = self.scheduler.invariants
        while time.monotonic() < deadline:
            # three-way idle: nothing poppable, no pipelined batch in
            # flight, AND no popped pod mid-cycle (the checker's
            # outstanding count) — without the last clause a score taken
            # mid-commit reads in-flight pods as unbound+untracked (lost)
            if (not q.has_schedulable()
                    and not self.scheduler.pipeline_pending
                    and (inv is None or inv.summary()["outstanding"] == 0)):
                return time.monotonic() - t0
            time.sleep(0.005)
        return time.monotonic() - t0

    # -- the replay loop -------------------------------------------------
    def replay(
        self,
        events: Sequence[TraceEvent],
        chaos: Sequence[Tuple[float, Callable[[], object]]] = (),
        drain_timeout_s: float = 60.0,
    ) -> ScenarioResult:
        """Replay `events` under the virtual clock, firing each chaos
        callable when virtual time reaches its trigger.  Virtual time
        advances as wall*compression; the loop sleeps to pace arrivals
        and wakes early for whichever of (next event, next completion,
        next chaos) is due first.  After the last arrival it drains the
        queue, settles lifetimes, and scores."""
        events = sorted(events, key=lambda e: (e.t, e.name))
        chaos = sorted(chaos, key=lambda c: c[0])
        res = ScenarioResult()
        arrived: Dict[Tuple[str, str], TraceEvent] = {}
        evicted_keys: set = set()
        # completion heap: (virtual_due, ns, name, orig_due); entries
        # re-arm (due pushed forward) while their pod is unbound — a pod
        # can't finish before it starts — but keep orig_due so the
        # post-drain pass can settle anything whose TRACE lifetime has
        # elapsed without waiting out the re-arm slack
        comp: List[Tuple[float, str, str, float]] = []
        chaos_windows: List[Tuple[float, float]] = []  # wall (start, end)
        ei = ci = 0
        wall0 = time.monotonic()

        def vnow() -> float:
            return (time.monotonic() - wall0) * self.compression

        def settle_completions(v: float) -> None:
            while comp and comp[0][0] <= v:
                due, ns, name, orig = heapq.heappop(comp)
                key = (ns, name)
                pod = self.cluster.get("pods", ns, name)
                if pod is None or key in self._completed:
                    continue
                if pod.spec.node_name:
                    self._complete(ns, name)
                    res.completed += 1
                else:
                    # not running yet (queued, or displaced mid-chaos):
                    # lifetime hasn't elapsed — re-arm a slice later
                    heapq.heappush(comp, (due + 1.0 * self.compression,
                                          ns, name, orig))
                    break

        while ei < len(events) or ci < len(chaos):
            next_t = min(
                events[ei].t if ei < len(events) else math.inf,
                chaos[ci][0] if ci < len(chaos) else math.inf,
                comp[0][0] if comp else math.inf,
            )
            lag = next_t / self.compression - (time.monotonic() - wall0)
            if lag > 0:
                time.sleep(min(lag, 0.05))
            v = vnow()
            settle_completions(v)
            while ci < len(chaos) and chaos[ci][0] <= v:
                _, fn = chaos[ci]
                ci += 1
                w0 = time.monotonic()
                self._event_mark = None
                self._tick_timeline()
                out = fn()
                w_start = self._event_mark or w0
                w_end = time.monotonic()
                chaos_windows.append((w_start, w_end))
                # timeline annotations (ISSUE 20): both window edges at
                # their EXACT wall times (the store clock is the same
                # monotonic clock), so the chaos lane on the rendered
                # timeline aligns with the metric excursions it caused
                self._mark_chaos("start", w_start, virtual_t=round(v, 3))
                self._mark_chaos("end", w_end, virtual_t=round(v, 3))
                res.chaos.append({
                    "virtual_t": round(v, 3),
                    "result": out if isinstance(out, dict) else str(out),
                })
            while ei < len(events) and events[ei].t <= v:
                e = events[ei]
                ei += 1
                if e.kind == "evict":
                    if self.cluster.get("pods", e.namespace, e.name):
                        self.cluster.delete("pods", e.namespace, e.name)
                        res.trace_evictions += 1
                        evicted_keys.add((e.namespace, e.name))
                    continue
                pod = make_pod(e.name, namespace=e.namespace, cpu=e.cpu,
                               mem=e.mem, priority=e.priority)
                self.cluster.add_pod(pod)
                arrived[(e.namespace, e.name)] = e
                res.arrivals += 1
                if e.lifetime_s is not None:
                    due = e.t + e.lifetime_s
                    heapq.heappush(
                        comp, (due, e.namespace, e.name, due))

        res.time_to_drain_s = round(self.wait_drained(drain_timeout_s), 3)
        # settle remaining due lifetimes now that the queue is quiet:
        # judge by the ORIGINAL due time (the re-arm slack was only ever
        # "can't finish before it starts", and everything bound by now
        # has started)
        deadline = time.monotonic() + 5.0
        while comp and time.monotonic() < deadline:
            due, ns, name, orig = comp[0]
            if orig > vnow():
                break       # genuinely not yet elapsed on the trace clock
            heapq.heappop(comp)
            key = (ns, name)
            pod = self.cluster.get("pods", ns, name)
            if pod is None or key in self._completed:
                continue
            if pod.spec.node_name and self._complete(ns, name):
                res.completed += 1
        res.wall_s = round(time.monotonic() - wall0, 3)
        res.virtual_s = round(vnow(), 3)
        self._score(res, arrived, evicted_keys, chaos_windows, wall0)
        return res

    # -- scoring ---------------------------------------------------------
    def _score(self, res: ScenarioResult, arrived, evicted_keys,
               chaos_windows, wall0: float) -> None:
        with self._obs_lock:
            binds = list(self._bind_times)
            resched = list(self._resched_ms)
            resched_wall = list(self._resched_wall)
            displaced = len(self._displaced_seen)
            unresched = len(self._displace_wall)
            redisplaced = self._redisplaced
            first_binds = dict(self._bind_wall)
            completed = set(self._completed)
        res.displaced = displaced
        res.redisplaced = redisplaced
        res.rescheduled = len(resched)
        res.displaced_unrescheduled = unresched
        res.reschedule_ms = _pct(resched)
        res.first_bind_ms = _pct([
            (first_binds[k] - wall0) * 1000.0 for k in first_binds
        ])
        res.shed = len(self.shed)
        shed_names = {n for n, _ in self.shed}
        q = self.scheduler.queue
        res.queued_end = len(q)
        live = {
            (p.namespace, p.name): p for p in self.cluster.list("pods")
        }
        res.bound = sum(
            1 for p in live.values()
            if p.spec.node_name and p.status.phase not in
            ("Succeeded", "Failed")
        )
        # conservation at the pod-identity level: every arrival is
        # bound, completed, shed, trace-evicted, or still queued.  A pod
        # in none of those buckets was LOST — the failure the displaced
        # requeue path exists to prevent.
        lost = 0
        for key, e in arrived.items():
            pod = live.get(key)
            if pod is None:
                # gone from the store: completed, trace-evicted, or lost
                if (key in completed or key in evicted_keys
                        or e.name in shed_names):
                    continue
                lost += 1
            elif not pod.spec.node_name:
                # present but unbound: must be queue-tracked or shed
                if q.tracks(pod) or e.name in shed_names:
                    continue
                lost += 1
        res.lost = lost
        inv = self.scheduler.invariants
        if inv is not None:
            res.invariants = inv.summary()
            res.violations = inv.violations_total()
        if self._ledger is not None:
            self._ledger.flush(30.0)
            res.ledger = {
                "cycles": self._ledger.cycles_total,
                "bytes": self._ledger.bytes_total,
                "dropped": self._ledger.dropped_total,
            }
        # goodput: binds/s inside the EVENT window vs before it.  The
        # window runs from the first disruption's start through recovery
        # — the later of the last chaos callable returning and the last
        # displaced pod rebinding — so a millisecond-long trigger (a
        # zone's monitor tick) is still scored over the disruption it
        # caused.  No chaos -> ratio 1.0 by definition.
        if chaos_windows and binds:
            c0 = chaos_windows[0][0]
            c1 = max(w[1] for w in chaos_windows)
            if resched_wall:
                c1 = max(c1, max(resched_wall))
            before = [b for b in binds if b < c0]
            during = [b for b in binds if c0 <= b <= c1]
            # the before-span starts at the FIRST bind (first-cycle
            # compile time is dead air, not low goodput)
            span_before = max(c0 - (min(before) if before else wall0), 1e-9)
            span_during = max(c1 - c0, 1e-9)
            res.goodput_before = round(len(before) / span_before, 3)
            res.goodput_during = round(len(during) / span_during, 3)
            if res.goodput_before > 0:
                res.goodput_ratio = round(
                    res.goodput_during / res.goodput_before, 4)
            else:
                res.goodput_ratio = 1.0 if res.goodput_during >= 0 else 0.0


# ------------------------------------------------- the named campaigns


SCENARIOS = ("drain", "zone", "diurnal", "trace", "autoscale")


def run_scenario(
    kind: str,
    *,
    seed: int = 0,
    pods: int = 120,
    nodes: int = 12,
    zones: int = 3,
    rate: float = 120.0,
    compression: float = 1.0,
    capacity: Optional[int] = None,
    trace_path: Optional[str] = None,
    ledger=None,
    drain_timeout_s: float = 60.0,
    autoscale: Optional[dict] = None,
    autoscale_ledger_path: Optional[str] = None,
    timeline_path: Optional[str] = None,
) -> ScenarioResult:
    """One call per campaign — the shared engine behind
    ``bench.py --scenario`` and the scenario tests:

    - **drain**: steady synthetic trace; at one-third of the trace the
      upgrade monkey rolling-drains half the fleet (displace mode) in
      waves of 2, then uncordons — mass requeue through the shed-exempt
      displaced path, rescheduling onto the surviving half and back.
    - **zone**: same trace; one zone's nodes all go silent at once
      (lease expiry -> lifecycle taint -> displace) — correlated loss
      and mass rescheduling.  The dead zone's leases stay stale so the
      zone is NOT restored; the survivors must absorb everything.
    - **diurnal**: a sinusoidal-rate trace (two periods, amplitude
      0.9) with no chaos — the swing itself is the event, driving AIMD
      batch breathing and capacity-planner backlog oscillation.
    - **trace**: replay `trace_path` (load_trace) verbatim, no chaos —
      the external-trace front door.
    - **autoscale** (ISSUE 19): a diurnal trace over a DELIBERATELY
      small base fleet, with the capacity planner on (short solve
      interval) and a live AutoscalerController enacting its plan —
      the cluster must BREATHE: grow through the peak (plan overflow ->
      paced node registration) and shrink after it (pods complete,
      managed nodes go drainable -> cordon + PDB-paced drain ->
      delete).  Lifetimes are ~1/3 of the diurnal period here so the
      bound population actually tracks the rate curve.  `autoscale`
      overrides AutoscalerConfig knobs; `autoscale_ledger_path` records
      the actuation JSONL for the offline replay gate.  The result's
      `autoscaler` dict carries the controller summary + the fleet-size
      curve (initial/peak/final) for the grows-AND-shrinks assertion.

    Lifetimes are otherwise long relative to the replay (pods mostly
    stay bound) so displacement math is well-conditioned."""
    if kind not in SCENARIOS:
        raise ValueError(f"unknown scenario {kind!r}: one of {SCENARIOS}")
    from kubernetes_tpu.runtime.chaos import Disruptions

    mean_life = max(60.0, 4.0 * pods / max(rate, 1e-6))
    if kind == "trace":
        if not trace_path:
            raise ValueError("scenario 'trace' needs trace_path")
        events = load_trace(trace_path)
    elif kind == "diurnal":
        span = pods / max(rate, 1e-6)
        events = synthesize_trace(
            seed, count=pods, rate=rate, mean_lifetime_s=mean_life,
            diurnal=(span / 2.0, 0.9), prefix="diurnal",
        )
    elif kind == "autoscale":
        # lifetimes ~1/3 of the diurnal period: the bound population
        # must FALL after the peak for drainable capacity to appear
        span = pods / max(rate, 1e-6)
        events = synthesize_trace(
            seed, count=pods, rate=rate,
            mean_lifetime_s=max(span / 3.0, 1e-3),
            diurnal=(span / 2.0, 0.9), prefix="autoscale",
        )
    else:
        events = synthesize_trace(
            seed, count=pods, rate=rate, mean_lifetime_s=mean_life,
            prefix=kind,
        )
    runner_kwargs: dict = dict(
        nodes=nodes, zones=zones, capacity=capacity,
        compression=compression, seed=seed, ledger=ledger,
    )
    if timeline_path:
        # banking a timeline artifact: sample fast relative to the
        # compressed replay so the chaos windows land between real
        # samples (±1 interval alignment, asserted by the tests)
        runner_kwargs["config_overrides"] = {
            "timeline": True,
            "timeline_interval_s": 0.05,
            "timeline_retention": 4096,
        }
    if kind == "autoscale":
        # a small-node base fleet the peak MUST overflow, a matching
        # single-shape catalog, and a planner solving every few cycles
        # so the actuator sees fresh plans through the whole curve
        overrides = dict(runner_kwargs.get("config_overrides") or {})
        overrides.update({
            "capacity_planner": True,
            "capacity_interval_cycles": 4,
            "node_shape_catalog": [
                {"name": "autoscale-2c", "cpu": "2",
                 "memory": "4Gi", "pods": 32},
            ],
        })
        runner_kwargs.update(
            node_cpu="2", node_mem="4Gi", node_pods=32,
            config_overrides=overrides,
        )
    with ScenarioRunner(**runner_kwargs) as runner:
        monkey = Disruptions(runner.cluster, rng=random.Random(seed))
        chaos: List[Tuple[float, Callable[[], object]]] = []
        last_t = events[-1].t if events else 0.0
        # fire mid-trace, and gate on a loaded cluster: the disruption
        # must displace RUNNING pods, not race an empty ramp-up
        warm = max(4, pods // 4)
        if kind == "drain":
            half = [f"node-{i}" for i in range(nodes // 2)]

            def _drain():
                runner.await_bound(warm)
                runner.mark_event_start()
                out = monkey.rolling_drain(
                    nodes=list(half), wave_size=2,
                    retry_rounds=4, retry_after_s=0.02,
                )
                for n in half:
                    monkey.uncordon(n)
                return out

            chaos.append((last_t / 2.0, _drain))
        elif kind == "zone":

            def _zone():
                runner.await_bound(warm)
                runner.mark_event_start()
                return monkey.zone_outage(zone=f"zone-{zones - 1}")

            chaos.append((last_t / 2.0, _zone))
        autoctrl = None
        if kind == "autoscale":
            from kubernetes_tpu.runtime.autoscaler import (
                AutoscalerConfig,
                AutoscalerController,
            )

            ac_kwargs: dict = dict(
                interval_s=0.02,
                up_stable_rounds=1,
                down_stable_rounds=2,
                cooldown_s=max(0.25, last_t / compression / 8.0),
                max_nodes_per_round=4,
                drain_deadline_s=5.0,
                min_nodes=nodes,          # base fleet is the floor
                max_nodes=nodes + 64,
                node_prefix="autoscale",
            )
            ac_kwargs.update(autoscale or {})
            autoctrl = AutoscalerController(
                runner.cluster,
                planner=runner.scheduler.capacity,
                invariants=runner.scheduler.invariants,
                config=AutoscalerConfig(**ac_kwargs),
                ledger=ledger,
                ledger_path=autoscale_ledger_path,
            )
            autoctrl.start()
        try:
            result = runner.replay(
                events, chaos=chaos, drain_timeout_s=drain_timeout_s)
        finally:
            if autoctrl is not None:
                # settle window: completions have freed managed nodes;
                # give the controller a few cooldowns to shrink back
                # before judging the curve
                settle_deadline = time.monotonic() + min(
                    10.0, 4.0 * ac_kwargs["cooldown_s"] + 1.0)
                while (time.monotonic() < settle_deadline
                       and autoctrl.managed_nodes()):
                    time.sleep(0.05)
                autoctrl.stop()
        if autoctrl is not None:
            inv = runner.scheduler.invariants
            if inv is not None:
                # node-lifecycle conservation at settle time, then
                # re-bank the totals _score already took
                inv.assert_nodes_settled()
                result.invariants = inv.summary()
                result.violations = inv.violations_total()
            summary = autoctrl.summary()
            fleet_curve = [
                (r["t"], r["state"]["fleet"])
                for r in autoctrl.debug_payload(limit=256)["recent"]
            ]
            result.autoscaler = {
                "summary": summary,
                "initial": nodes,
                "peak": max(summary["fleet_peak"], nodes),
                "final": len(list(runner.cluster.list("nodes"))),
                "fleet_curve": fleet_curve[-64:],
            }
        result.chaos.insert(0, {"kind": kind, "seed": seed})
        if timeline_path:
            runner.export_timeline(timeline_path)
    return result
