"""CRI as a wire protocol: the kubelet<->runtime process boundary.

The reference's kubelet never links its container runtime — it dials a
unix socket and speaks the CRI gRPC service
(staging/src/k8s.io/cri-api/pkg/apis/runtime/v1alpha2/api.proto; client
pkg/kubelet/remote/remote_runtime.go:1-512).  This module gives the
framework the same boundary (VERDICT r3 #5): `CRIServer` exposes any
in-process backend (FakeRuntime, ProcessRuntime) over a unix stream
socket, `RemoteRuntime` is the kubelet-side client with the reference
verb set, and a `python -m kubernetes_tpu.runtime.cri` entry point runs
the server standalone so the kubelet and the runtime are separate OS
processes — kill -9 of the runtime surfaces as pod sync failures, not
kubelet crashes.

Wire format: length-prefixed JSON frames (4-byte big-endian size, then a
UTF-8 JSON object) — the binary-codec stand-in for protobuf-over-gRPC,
chosen over HTTP because CRI is a point-to-point peer protocol, not a
REST surface.  Verbs (remote_runtime.go method set, snake_cased):

  version, status,
  run_pod_sandbox, stop_pod_sandbox, remove_pod_sandbox,
  list_pod_sandboxes, pod_sandbox_status,
  create_container, start_container, stop_container, remove_container,
  list_containers, container_status

Container records live in `CRIService` (state machine CREATED ->
RUNNING -> EXITED, api.proto ContainerState) layered over any sandbox
backend, so ProcessRuntime's pause processes anchor the sandboxes while
containers stay bookkeeping — the same split the reference's pause
sandbox + app containers have."""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
from collections import namedtuple
from typing import Dict, List, Optional

RUNTIME_API_VERSION = "v1alpha2"
RUNTIME_NAME = "kubernetes-tpu-runtime"

CONTAINER_CREATED = "CONTAINER_CREATED"
CONTAINER_RUNNING = "CONTAINER_RUNNING"
CONTAINER_EXITED = "CONTAINER_EXITED"

PodRef = namedtuple("PodRef", ["namespace", "name"])


class RuntimeUnavailable(Exception):
    """The runtime socket is gone or the call failed in transport — the
    kubelet treats this as a pod-level sync failure and retries
    (remote_runtime.go returns status.Error the sync loop absorbs)."""


class CRIError(Exception):
    """The runtime executed the call and returned an error."""


# ------------------------------------------------------------- framing


def _send_frame(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(data)) + data)


def _recv_frame(sock: socket.socket) -> Optional[dict]:
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (size,) = struct.unpack(">I", hdr)
    data = b""
    while len(data) < size:
        chunk = sock.recv(min(65536, size - len(data)))
        if not chunk:
            return None
        data += chunk
    return json.loads(data)


# ------------------------------------------------------------- service


class CRIService:
    """The full verb set over a sandbox backend: sandboxes delegate to
    the backend (pause processes for ProcessRuntime), containers are
    records with the api.proto state machine."""

    def __init__(self, backend):
        self.backend = backend
        self._containers: Dict[str, dict] = {}
        self._next = 0
        self._lock = threading.Lock()

    # -- sandboxes (delegated)

    def version(self) -> dict:
        return {"runtime_name": RUNTIME_NAME,
                "runtime_api_version": RUNTIME_API_VERSION}

    def status(self) -> dict:
        return {"conditions": [
            {"type": "RuntimeReady", "status": True},
            {"type": "NetworkReady", "status": True},
        ]}

    def run_pod_sandbox(self, namespace: str, name: str) -> str:
        return self.backend.run_pod_sandbox(PodRef(namespace, name))

    def stop_pod_sandbox(self, sandbox_id: str) -> None:
        self.backend.stop_pod_sandbox(sandbox_id)
        with self._lock:
            for c in self._containers.values():
                if (c["sandbox_id"] == sandbox_id
                        and c["state"] == CONTAINER_RUNNING):
                    c["state"] = CONTAINER_EXITED
                    c["exit_code"] = 137

    def remove_pod_sandbox(self, sandbox_id: str) -> None:
        self.backend.remove_pod_sandbox(sandbox_id)
        with self._lock:
            self._containers = {
                cid: c for cid, c in self._containers.items()
                if c["sandbox_id"] != sandbox_id
            }

    def list_pod_sandboxes(self) -> List[dict]:
        return [dict(sb, pod=list(sb["pod"]))
                for sb in self.backend.list_pod_sandboxes()]

    def pod_sandbox_status(self, sandbox_id: str) -> dict:
        for sb in self.backend.list_pod_sandboxes():
            if sb["id"] == sandbox_id:
                return dict(sb, pod=list(sb["pod"]))
        raise CRIError(f"sandbox {sandbox_id!r} not found")

    # -- containers (records)

    def create_container(self, sandbox_id: str, name: str,
                         image: str = "") -> str:
        if not any(sb["id"] == sandbox_id
                   for sb in self.backend.list_pod_sandboxes()):
            raise CRIError(f"sandbox {sandbox_id!r} not found")
        with self._lock:
            self._next += 1
            cid = f"container-{self._next}"
            self._containers[cid] = {
                "id": cid, "sandbox_id": sandbox_id, "name": name,
                "image": image, "state": CONTAINER_CREATED,
                "exit_code": None,
            }
        return cid

    def _container(self, container_id: str) -> dict:
        """Caller must hold self._lock (CRIServer runs one thread per
        connection — every container-state read/transition serializes on
        the one lock so e.g. remove_pod_sandbox cannot interleave with
        start_container and leave a RUNNING record on a reaped sandbox)."""
        c = self._containers.get(container_id)
        if c is None:
            raise CRIError(f"container {container_id!r} not found")
        return c

    def start_container(self, container_id: str) -> None:
        with self._lock:
            c = self._container(container_id)
            if c["state"] != CONTAINER_CREATED:
                raise CRIError(
                    f"container {container_id!r} is {c['state']}, not CREATED")
            c["state"] = CONTAINER_RUNNING

    def stop_container(self, container_id: str,
                       timeout: float = 0) -> None:
        with self._lock:
            c = self._container(container_id)
            if c["state"] == CONTAINER_RUNNING:
                c["state"] = CONTAINER_EXITED
                c["exit_code"] = 0

    def remove_container(self, container_id: str) -> None:
        with self._lock:
            c = self._containers.get(container_id)
            if c is not None and c["state"] == CONTAINER_RUNNING:
                raise CRIError(f"container {container_id!r} is running")
            self._containers.pop(container_id, None)

    def list_containers(self,
                        sandbox_id: Optional[str] = None) -> List[dict]:
        with self._lock:
            return [dict(c) for c in self._containers.values()
                    if sandbox_id is None or c["sandbox_id"] == sandbox_id]

    def container_status(self, container_id: str) -> dict:
        with self._lock:
            return dict(self._container(container_id))

    def exec_sync(self, container_id: str, cmd: List[str],
                  timeout: float = 10.0) -> dict:
        """ExecSync (api.proto): run cmd in the container's context and
        return stdout/stderr/exit_code.  This framework's containers are
        host processes anchored by the sandbox pause, so exec runs the
        command as a host subprocess — the same execution domain."""
        import subprocess

        with self._lock:  # state check only; the exec itself runs unlocked
            c = self._container(container_id)
            if c["state"] != CONTAINER_RUNNING:
                raise CRIError(
                    f"container {container_id!r} is {c['state']}, not RUNNING")
        try:
            out = subprocess.run(
                list(cmd), capture_output=True, timeout=timeout)
        except subprocess.TimeoutExpired:
            return {"stdout": "", "stderr": "exec timed out",
                    "exit_code": 124}
        except OSError as e:
            return {"stdout": "", "stderr": str(e), "exit_code": 126}
        return {
            "stdout": out.stdout.decode(errors="replace"),
            "stderr": out.stderr.decode(errors="replace"),
            "exit_code": out.returncode,
        }


# -------------------------------------------------------------- server


class CRIServer:
    """Serve a CRIService on a unix socket; one thread per connection
    (the gRPC server analog)."""

    def __init__(self, service: CRIService, socket_path: str):
        self.service = service
        self.socket_path = socket_path
        if os.path.exists(socket_path):
            os.unlink(socket_path)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(socket_path)
        self._sock.listen(16)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "CRIServer":
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._thread.start()
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while True:
                req = _recv_frame(conn)
                if req is None:
                    return
                rid = req.get("id")
                method = req.get("method", "")
                params = req.get("params") or {}
                fn = getattr(self.service, method, None)
                if fn is None or method.startswith("_"):
                    _send_frame(conn, {
                        "id": rid,
                        "error": {"message": f"unknown method {method!r}"},
                    })
                    continue
                try:
                    result = fn(**params)
                    _send_frame(conn, {"id": rid, "result": result})
                except Exception as e:  # executed-but-failed -> CRIError
                    _send_frame(conn, {
                        "id": rid, "error": {"message": str(e)},
                    })
        except OSError:
            pass
        finally:
            conn.close()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        finally:
            if os.path.exists(self.socket_path):
                try:
                    os.unlink(self.socket_path)
                except OSError:
                    pass


# -------------------------------------------------------------- client


class RemoteRuntime:
    """Kubelet-side CRI client (remote_runtime.go): drop-in for the
    in-process runtime seam — run/stop/remove/list sandbox calls travel
    the socket; transport failures raise RuntimeUnavailable, which the
    kubelet absorbs as pod-level sync failures."""

    def __init__(self, socket_path: str, timeout: float = 5.0):
        self.socket_path = socket_path
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._next = 0
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(self.timeout)
            try:
                s.connect(self.socket_path)
            except OSError as e:
                raise RuntimeUnavailable(
                    f"runtime socket {self.socket_path}: {e}") from e
            self._sock = s
        return self._sock

    def _call(self, method: str, **params):
        with self._lock:
            self._next += 1
            rid = self._next
            try:
                sock = self._connect()
                _send_frame(sock, {"id": rid, "method": method,
                                   "params": params})
                resp = _recv_frame(sock)
            except (OSError, RuntimeUnavailable) as e:
                self.close()
                if isinstance(e, RuntimeUnavailable):
                    raise
                raise RuntimeUnavailable(
                    f"runtime call {method} failed: {e}") from e
            if resp is None:  # peer vanished mid-call (kill -9)
                self.close()
                raise RuntimeUnavailable(
                    f"runtime closed the connection during {method}")
            if resp.get("error"):
                raise CRIError(resp["error"].get("message", "runtime error"))
            return resp.get("result")

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # the kubelet's runtime seam
    def run_pod_sandbox(self, pod) -> str:
        return self._call("run_pod_sandbox",
                          namespace=pod.namespace, name=pod.name)

    def stop_pod_sandbox(self, sandbox_id: str) -> None:
        self._call("stop_pod_sandbox", sandbox_id=sandbox_id)

    def remove_pod_sandbox(self, sandbox_id: str) -> None:
        self._call("remove_pod_sandbox", sandbox_id=sandbox_id)

    def list_pod_sandboxes(self) -> List[dict]:
        return [dict(sb, pod=tuple(sb["pod"]))
                for sb in self._call("list_pod_sandboxes")]

    def pod_sandbox_status(self, sandbox_id: str) -> dict:
        return self._call("pod_sandbox_status", sandbox_id=sandbox_id)

    # container verbs
    def create_container(self, sandbox_id: str, name: str,
                         image: str = "") -> str:
        return self._call("create_container", sandbox_id=sandbox_id,
                          name=name, image=image)

    def start_container(self, container_id: str) -> None:
        self._call("start_container", container_id=container_id)

    def stop_container(self, container_id: str, timeout: float = 0) -> None:
        self._call("stop_container", container_id=container_id,
                   timeout=timeout)

    def remove_container(self, container_id: str) -> None:
        self._call("remove_container", container_id=container_id)

    def list_containers(self, sandbox_id=None) -> List[dict]:
        return self._call("list_containers", sandbox_id=sandbox_id)

    def container_status(self, container_id: str) -> dict:
        return self._call("container_status", container_id=container_id)

    def exec_sync(self, container_id: str, cmd: List[str],
                  timeout: float = 10.0) -> dict:
        return self._call("exec_sync", container_id=container_id,
                          cmd=list(cmd), timeout=timeout)

    def version(self) -> dict:
        return self._call("version")

    def status(self) -> dict:
        return self._call("status")


def main(argv=None) -> None:
    """Standalone runtime daemon: `python -m kubernetes_tpu.runtime.cri
    --socket /tmp/cri.sock [--backend process|fake]`."""
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--socket", required=True)
    ap.add_argument("--backend", choices=("fake", "process"),
                    default="fake")
    args = ap.parse_args(argv)
    from kubernetes_tpu.runtime.kubelet import FakeRuntime, ProcessRuntime

    backend = ProcessRuntime() if args.backend == "process" else FakeRuntime()
    srv = CRIServer(CRIService(backend), args.socket)
    srv.start()
    print(f"cri: serving {args.backend} runtime on {args.socket}",
          flush=True)
    threading.Event().wait()  # serve forever


if __name__ == "__main__":
    main()
