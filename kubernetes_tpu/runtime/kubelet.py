"""Node agent: the kubelet slice over the blackboard.

The reference kubelet (pkg/kubelet, SURVEY section 3.4) is a sync loop
driven by three channels — apiserver watch (configCh), runtime relist
(plegCh), housekeeping — talking to the container runtime over the CRI gRPC
contract (staging/src/k8s.io/cri-api api.proto) and PATCHing status back.
The standalone analog keeps every seam:

  * PodSandboxRuntime — the CRI slice (RunPodSandbox / StopPodSandbox /
    RemovePodSandbox / ListPodSandboxes); `FakeRuntime` is the hollow
    backend (kubemark's fake docker client analog), a real node would put a
    gRPC client here;
  * Kubelet.observe — the configCh: pods bound to this node sync into
    sandboxes and report Running (statusManager update);
  * Kubelet.pleg_relist — the plegCh: reconcile runtime state against
    desired state, complete pods the `completer` approves;
  * Kubelet.heartbeat — the node-lease renewal;
  * Kubelet.eviction_tick — pkg/kubelet/eviction slice: under a
    MemoryPressure condition, BestEffort pods are evicted first (phase
    Failed, reason Evicted), mirroring the qos-ranked eviction order.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Dict, List, Optional

from kubernetes_tpu.api.types import Node, Pod, PodStatus, qos_class
from kubernetes_tpu.runtime.cluster import ADDED, DELETED, MODIFIED, LocalCluster
from kubernetes_tpu.runtime.controllers import renew_node_lease

SANDBOX_READY = "SANDBOX_READY"
SANDBOX_NOTREADY = "SANDBOX_NOTREADY"


class FakeRuntime:
    """In-memory CRI backend (hollow_kubelet.go's fake docker client)."""

    _ids = itertools.count(1)

    def __init__(self):
        self.sandboxes: Dict[str, dict] = {}

    def run_pod_sandbox(self, pod: Pod) -> str:
        sid = f"sandbox-{next(self._ids)}"
        self.sandboxes[sid] = {
            "id": sid,
            "pod": (pod.namespace, pod.name),
            "state": SANDBOX_READY,
        }
        return sid

    def stop_pod_sandbox(self, sandbox_id: str) -> None:
        sb = self.sandboxes.get(sandbox_id)
        if sb is not None:
            sb["state"] = SANDBOX_NOTREADY

    def remove_pod_sandbox(self, sandbox_id: str) -> None:
        self.sandboxes.pop(sandbox_id, None)

    def list_pod_sandboxes(self) -> List[dict]:
        return list(self.sandboxes.values())


class Kubelet:
    """One node's agent.  Drive with events (wire via `register`) plus
    explicit pleg_relist()/heartbeat()/eviction_tick() calls from a loop or
    a test harness (the syncLoopIteration select arms)."""

    def __init__(
        self,
        cluster: LocalCluster,
        node: Node,
        runtime=None,
        completer=None,
        liveness=None,
        readiness=None,
        register: bool = True,
        subscribe: bool = True,
        checkpoint_dir: Optional[str] = None,
    ):
        self.cluster = cluster
        self.node = node
        self.runtime = runtime if runtime is not None else FakeRuntime()
        self.completer = completer
        # resource management (pkg/kubelet/cm, volumemanager, stats): the
        # cgroup hierarchy as data, the volume mount state machine, and
        # the observed-usage provider feeding eviction + metrics
        from kubernetes_tpu.runtime.kubelet_resources import (
            CgroupManager,
            StatsProvider,
            VolumeManager,
        )

        self.cgroups = CgroupManager()
        self.volume_manager = VolumeManager(cluster, node.name)
        self.stats = StatsProvider(cluster, node.name)
        # device/cpu managers + node-local checkpoints (pkg/kubelet/cm/
        # devicemanager + cpumanager + checkpointmanager): with a
        # checkpoint_dir, allocations survive a kubelet restart
        from kubernetes_tpu.runtime.kubelet_devices import (
            CheckpointManager,
            CPUManager,
            DeviceManager,
        )

        self.checkpoints = (
            CheckpointManager(checkpoint_dir) if checkpoint_dir else None
        )
        self.devices = DeviceManager(self.checkpoints)
        cpu_alloc = node.status.allocatable.get("cpu")
        self.cpu_manager = CPUManager(
            int(cpu_alloc.value) if cpu_alloc is not None else 0,
            self.checkpoints,
        )
        # prober manager seam (pkg/kubelet/prober): callables pod -> bool.
        # liveness False -> container restarted (sandbox recreated,
        # restartCount++); readiness False -> Ready condition cleared
        # (endpoints stop routing) without a restart.
        self.liveness = liveness
        self.readiness = readiness
        self.sandbox_of: Dict[tuple, str] = {}   # pod key -> sandbox id
        self.containers_of: Dict[tuple, list] = {}  # pod key -> container ids
        # pods waiting on WaitForAttachAndMount (retried on node events)
        self._awaiting_volumes: set = set()
        self.evictions: List[tuple] = []
        if register:
            cluster.add_node(node)
        if register and subscribe:
            cluster.watch(self.observe)
        # kubelet :10250 /exec analog: runtimes with the ExecSync verb
        # publish an exec handler the apiserver's pods/exec subresource
        # dispatches through (ref kubelet server.go GetExec -> CRI)
        if hasattr(self.runtime, "exec_sync"):
            cluster.node_exec[node.name] = self.exec_in_pod

    def exec_in_pod(self, namespace: str, name: str, container: str,
                    command, timeout: float = 10.0) -> dict:
        """Resolve the pod's sandbox + container record and ExecSync the
        command (ref pkg/kubelet/server/server.go:701-741 getExec ->
        kuberuntime ExecSync).  Returns {stdout, stderr, exit_code}."""
        key = (namespace, name)
        sid = self.sandbox_of.get(key)
        if sid is None:
            raise KeyError(f"pod {namespace}/{name} has no running sandbox")
        target = None
        for c in self.runtime.list_containers(sid):
            if not container or c.get("name") == container:
                target = c
                break
        if target is None:
            raise KeyError(
                f"container {container!r} not found in {namespace}/{name}")
        return self.runtime.exec_sync(target["id"], list(command), timeout)

    # ------------------------------------------------------------ configCh

    def observe(self, event: str, kind: str, obj) -> None:
        if kind == "nodes" and obj.name == self.node.name:
            self.node = obj  # track condition changes (pressure)
            # volumesAttached may have grown: retry pods blocked on
            # WaitForAttachAndMount (the volume manager's wakeup)
            for key in list(self._awaiting_volumes):
                pod = self.cluster.get("pods", *key)
                if pod is None:
                    self._awaiting_volumes.discard(key)
                elif self.volume_manager.all_mounted(pod):
                    self.sync_pod(pod)
            return
        if kind != "pods" or obj.spec.node_name != self.node.name:
            return
        key = (obj.namespace, obj.name)
        if event == DELETED or obj.status.phase in ("Succeeded", "Failed"):
            self._awaiting_volumes.discard(key)
            self._teardown(key, pod=obj)
            return
        if key in self.sandbox_of:
            # event-driven completion (the hollow-node fast path; pleg_relist
            # re-consults for completers that declined here)
            if (
                obj.status.phase == "Running"
                and self.completer is not None
                and self.completer(obj)
            ):
                self._teardown(key)
                self.cluster.update(
                    "pods",
                    dataclasses.replace(
                        obj, status=PodStatus(phase="Succeeded")
                    ),
                )
            return
        self.sync_pod(obj)

    def sync_pod(self, pod: Pod) -> None:
        """kubelet.syncPod -> pod cgroup -> WaitForAttachAndMount ->
        kuberuntime SyncPod -> CRI RunPodSandbox, then the statusManager
        reports Running.  A pod whose PV-backed volume hasn't been
        surfaced on node.status.volumesAttached yet stays Pending (no
        sandbox) until a node/claim event re-syncs it — the reference
        blocks syncPod on the volume manager the same way."""
        key = (pod.namespace, pod.name)
        if pod.status.phase in ("Failed", "Succeeded"):
            # terminal phases never re-host (kubelet_pods.go
            # podIsTerminated gates syncPod): an admission-rejected pod
            # stays Failed until the controller replaces it
            return
        if key in self.sandbox_of:
            # already sandboxed (a watch-triggered sync raced an explicit
            # one): syncPod's sandbox-actions step finds nothing to do —
            # re-creating here would LEAK the live sandbox
            return
        self.cgroups.create_pod_cgroup(pod)
        if not self.volume_manager.all_mounted(pod):
            self._awaiting_volumes.add(key)
            return
        self._awaiting_volumes.discard(key)
        try:
            # device + exclusive-cpu admission (cm.Allocate before the
            # sandbox exists): failure is an admission error on THIS pod
            self.devices.allocate(pod)
            self.cpu_manager.add_pod(pod)
        except Exception as e:
            self.cluster.events.eventf(
                "Pod", pod.namespace, pod.name, "Warning",
                "UnexpectedAdmissionError", "%s", e,
            )
            # terminal rejection (kubelet_pods.go rejectPod): leaving the
            # pod Pending-and-bound would hold its scheduler-side
            # resources forever; Failed lets the controller replace it
            self.cluster.update(
                "pods",
                dataclasses.replace(
                    pod,
                    status=dataclasses.replace(
                        pod.status, phase="Failed",
                        reason="UnexpectedAdmissionError", message=str(e),
                    ),
                ),
            )
            return
        try:
            sid = self.runtime.run_pod_sandbox(pod)
            self.sandbox_of[key] = sid
            # kuberuntime SyncPod step 6-7: create + start one container
            # per spec container inside the new sandbox (runtimes without
            # the container verb set — the hollow FakeRuntime — skip)
            if hasattr(self.runtime, "create_container"):
                cids = []
                specs = pod.spec.containers or None
                for c in (specs if specs else [None]):
                    cid = self.runtime.create_container(
                        sid, c.name if c is not None else "main",
                        image=c.image if c is not None else "")
                    self.runtime.start_container(cid)
                    cids.append(cid)
                self.containers_of[key] = cids
        except Exception as e:
            # a dead/unreachable runtime (kill -9 across the CRI socket,
            # runtime/cri.py RuntimeUnavailable) is a POD sync failure,
            # never a kubelet crash: surface the event and leave the pod
            # Pending for the next sync to retry (syncPod error path)
            self.cluster.events.eventf(
                "Pod", pod.namespace, pod.name, "Warning",
                "FailedCreatePodSandBox",
                "runtime: %s", e,
            )
            return
        if pod.status.phase != "Running":
            self.cluster.update(
                "pods",
                dataclasses.replace(
                    pod,
                    # the statusManager stamps startTime (preemption's
                    # earliest-start-time criterion reads it)
                    status=PodStatus(phase="Running", start_time=time.time()),
                ),
            )

    def _teardown(self, key: tuple, pod=None) -> None:
        self.containers_of.pop(key, None)  # die with their sandbox (CRI
        # StopPodSandbox exits containers; RemovePodSandbox reaps records)
        sid = self.sandbox_of.pop(key, None)
        if sid is not None:
            try:
                self.runtime.stop_pod_sandbox(sid)
                self.runtime.remove_pod_sandbox(sid)
            except Exception:
                # an unreachable runtime cannot stop the sandbox now; the
                # PLEG relist reconciles once it returns
                pass
        # DELETED events carry the final object; the store no longer has it
        pod = pod if pod is not None else self.cluster.get("pods", *key)
        if pod is not None:
            self.cgroups.remove_pod_cgroup(pod)
            self.devices.release(pod)
            self.cpu_manager.remove_pod(pod)
        self.volume_manager.sync()  # unmount the departed pod's volumes

    # ------------------------------------------------------ device plugins

    def register_device_plugin(self, plugin) -> None:
        """Device-plugin registration (devicemanager Registration): the
        resource becomes node allocatable/capacity immediately, so the
        scheduler's resource-fit columns see it like cpu/memory."""
        self.devices.register(plugin)
        self._publish_device_allocatable()

    def _publish_device_allocatable(self) -> None:
        from kubernetes_tpu.api.resource import parse_quantity

        node = self.cluster.get("nodes", "", self.node.name)
        if node is None:
            return
        alloc = dict(node.status.allocatable)
        cap = dict(node.status.capacity)
        for res, n in self.devices.allocatable().items():
            alloc[res] = parse_quantity(str(n))
            cap[res] = parse_quantity(str(n))
        self.node = dataclasses.replace(
            node, status=dataclasses.replace(
                node.status, allocatable=alloc, capacity=cap))
        self.cluster.update("nodes", self.node)

    # -------------------------------------------------------------- plegCh

    def pleg_relist(self) -> int:
        """Reconcile runtime sandboxes against the store (PLEG): complete
        pods the completer approves, tear down sandboxes whose pod is gone.
        Returns completions this sweep."""
        done = 0
        try:
            sandboxes = self.runtime.list_pod_sandboxes()
        except Exception:
            return 0  # runtime away: nothing to reconcile this sweep
        for sb in sandboxes:
            ns, name = sb["pod"]
            pod = self.cluster.get("pods", ns, name)
            if pod is None or pod.spec.node_name != self.node.name:
                # reap directly by id: orphans (kubelet restarted over a
                # live runtime) are not in sandbox_of
                self.sandbox_of.pop((ns, name), None)
                self.runtime.stop_pod_sandbox(sb["id"])
                self.runtime.remove_pod_sandbox(sb["id"])
                continue
            if (
                pod.status.phase == "Running"
                and self.completer is not None
                and self.completer(pod)
            ):
                self._teardown((ns, name))
                self.cluster.update(
                    "pods",
                    dataclasses.replace(
                        pod, status=PodStatus(phase="Succeeded")
                    ),
                )
                done += 1
        return done

    # --------------------------------------------------------- housekeeping

    def heartbeat(self, now: Optional[float] = None) -> None:
        renew_node_lease(self.cluster, self.node.name, now=now)

    def probe_tick(self) -> int:
        """Prober manager sweep (pkg/kubelet/prober/prober_manager.go): run
        liveness and readiness probes against every sandboxed Running pod.
        Liveness failure kills + recreates the container (restartCount++);
        readiness flips the Ready condition only.  Returns restarts."""
        restarts = 0
        for key in list(self.sandbox_of):
            pod = self.cluster.get("pods", *key)
            if pod is None or pod.status.phase != "Running":
                continue
            if self.liveness is not None and not self.liveness(pod):
                self._teardown(key)
                try:
                    self.sandbox_of[key] = self.runtime.run_pod_sandbox(pod)
                except Exception as e:
                    # a dead runtime mid-restart: pod event, prober
                    # survives; the next sync_pod re-creates the sandbox
                    self.cluster.events.eventf(
                        "Pod", pod.namespace, pod.name, "Warning",
                        "FailedCreatePodSandBox",
                        "restart after failed liveness probe: %s", e,
                    )
                    continue
                pod = dataclasses.replace(
                    pod,
                    status=dataclasses.replace(
                        pod.status,
                        restart_count=pod.status.restart_count + 1,
                        # without a readiness probe a running container IS
                        # ready (the reference defaults Ready=true); with
                        # one, stay out of rotation until it passes
                        ready=self.readiness is None,
                    ),
                )
                self.cluster.update("pods", pod)
                self.cluster.events.eventf(
                    "Pod", pod.namespace, pod.name, "Warning", "Unhealthy",
                    "liveness probe failed; container restarted",
                )
                restarts += 1
                continue
            if self.readiness is not None:
                ready = bool(self.readiness(pod))
                if ready != pod.status.ready:
                    self.cluster.update(
                        "pods",
                        dataclasses.replace(
                            pod,
                            status=dataclasses.replace(
                                pod.status, ready=ready
                            ),
                        ),
                    )
        return restarts

    def eviction_tick(self, max_evict: Optional[int] = None) -> List[tuple]:
        """pkg/kubelet/eviction (eviction_manager.go + helpers.go
        rankMemoryPressure): under MemoryPressure, rank by OBSERVED
        usage-over-request — exceeders first (BestEffort pods, with zero
        requests and nonzero usage, always exceed, reproducing the
        QoS-first outcome), then lower priority, then largest overage —
        phase Failed, torn down, recorded as an Evicted event.  Returns
        evicted pod keys."""
        from kubernetes_tpu.runtime.kubelet_resources import (
            rank_for_memory_eviction,
        )

        if self.node.status.conditions.get("MemoryPressure") != "True":
            return []
        pods = []
        for key in list(self.sandbox_of):
            pod = self.cluster.get("pods", *key)
            if pod is not None:
                pods.append(pod)
        if not pods:
            return []
        ranked = rank_for_memory_eviction(pods, self.stats.usage_fn)
        exceeders = [p for p, over in ranked if over > 0]
        # every usage-over-request pod goes this tick; otherwise shed the
        # top-ranked one and reassess (the reference evicts one victim
        # per synchronize loop)
        chosen = exceeders if exceeders else [ranked[0][0]]
        victims = [((p.namespace, p.name), p) for p in chosen]
        if max_evict is not None:
            victims = victims[:max_evict]
        evicted = []
        for key, pod in victims:
            self._teardown(key)
            self.cluster.update(
                "pods",
                dataclasses.replace(pod, status=PodStatus(phase="Failed")),
            )
            self.cluster.events.eventf(
                "Pod", pod.namespace, pod.name, "Warning", "Evicted",
                "node %s under memory pressure", self.node.name,
            )
            evicted.append(key)
        self.evictions.extend(evicted)
        return evicted


class ProcessRuntime:
    """CRI backend anchored by REAL pause processes (native/pause.c — the
    analog of the reference's only compiled-C artifact, build/pause/
    pause.c): RunPodSandbox spawns one pause process per sandbox, Stop
    SIGTERMs it, Remove reaps the record.  The pause binary holds the
    sandbox alive, exits cleanly on SIGTERM, and reaps zombies reparented
    to it — byte-for-byte the reference pause contract.

    Builds the binary on first use via `make -C native` when missing."""

    def __init__(self, pause_path: Optional[str] = None):
        import os
        import subprocess

        if pause_path is None:
            root = os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            )
            native = os.path.join(root, "native")
            pause_path = os.path.join(native, "pause")
            if not os.path.exists(pause_path):
                subprocess.run(
                    ["make", "-C", native], check=True,
                    capture_output=True,
                )
        self.pause_path = pause_path
        self._procs: Dict[str, object] = {}   # sandbox id -> Popen
        self.sandboxes: Dict[str, dict] = {}
        self._ids = itertools.count(1)

    def run_pod_sandbox(self, pod: Pod) -> str:
        import subprocess

        sid = f"sandbox-{next(self._ids)}"
        proc = subprocess.Popen(
            [self.pause_path],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        self._procs[sid] = proc
        self.sandboxes[sid] = {
            "id": sid,
            "pod": (pod.namespace, pod.name),
            "state": SANDBOX_READY,
            "pid": proc.pid,
        }
        return sid

    def stop_pod_sandbox(self, sandbox_id: str) -> None:
        proc = self._procs.get(sandbox_id)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except Exception:
                proc.kill()
        sb = self.sandboxes.get(sandbox_id)
        if sb is not None:
            sb["state"] = SANDBOX_NOTREADY

    def remove_pod_sandbox(self, sandbox_id: str) -> None:
        self.stop_pod_sandbox(sandbox_id)
        self._procs.pop(sandbox_id, None)
        self.sandboxes.pop(sandbox_id, None)

    def list_pod_sandboxes(self) -> List[dict]:
        return list(self.sandboxes.values())
