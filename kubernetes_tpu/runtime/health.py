"""Scheduler healthz + metrics HTTP endpoints.

The reference serves /healthz and Prometheus /metrics from the scheduler
binary itself (cmd/kube-scheduler/app/server.go:194-222
installMetricHandler / newHealthzHandler); previously only the extender
sidecar exposed them here.  `start_health_server` serves the shared metrics
registry and an optional liveness callback (the leader-election watchdog
hook, server.go:196-197).
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from kubernetes_tpu.utils import metrics as m


class HealthServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        healthy: Optional[Callable[[], bool]] = None,
        registry=None,
    ):
        self._healthy = healthy or (lambda: True)
        self._registry = registry or m.REGISTRY
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def _send(self, body: bytes, code: int = 200, ct: str = "text/plain"):
                self.send_response(code)
                self.send_header("Content-Type", ct)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    if outer._healthy():
                        self._send(b"ok")
                    else:
                        self._send(b"unhealthy", 500)
                elif self.path == "/metrics":
                    self._send(
                        outer._registry.expose().encode(),
                        ct="text/plain; version=0.0.4",
                    )
                else:
                    self._send(b"not found", 404)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self):
        return self._httpd.server_address

    def start(self) -> "HealthServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def start_health_server(host: str = "127.0.0.1", port: int = 0, **kw) -> HealthServer:
    return HealthServer(host, port, **kw).start()
