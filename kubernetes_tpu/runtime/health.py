"""Scheduler health: device breaker state + healthz/metrics HTTP endpoints.

The reference serves /healthz and Prometheus /metrics from the scheduler
binary itself (cmd/kube-scheduler/app/server.go:194-222
installMetricHandler / newHealthzHandler); previously only the extender
sidecar exposed them here.  `start_health_server` serves the shared metrics
registry and an optional liveness callback (the leader-election watchdog
hook, server.go:196-197).

`DeviceHealth` is the TPU-specific half: the circuit breaker over the
accelerator datapath (codec/faults.py classifies the errors, the scheduler
wires the policy).  The reference has no analog — its scheduler never loses
a backend — but the Borg/Omega lineage in PAPERS.md keeps serving through
partial infrastructure failure, and that is the contract here: a failing
device degrades the control plane to the CPU reference engine instead of
stalling it.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from kubernetes_tpu.codec.faults import FAULT_PERSISTENT
from kubernetes_tpu.utils import metrics as m

# breaker states (classic Nygard circuit-breaker vocabulary)
BREAKER_CLOSED = "closed"        # device path live
BREAKER_OPEN = "open"            # device path disabled; CPU degraded mode
BREAKER_HALF_OPEN = "half_open"  # cool-down elapsed; one canary batch allowed

_STATE_GAUGE = {BREAKER_CLOSED: 0.0, BREAKER_HALF_OPEN: 1.0, BREAKER_OPEN: 2.0}


class DeviceHealth:
    """Classified-failure circuit breaker for the device datapath.

    Policy (wired by runtime/scheduler.py from SchedulerConfig knobs):

      * transient faults retry the same in-flight batch with jittered
        exponential backoff (`backoff_s`); `failure_threshold` CONSECUTIVE
        classified failures trip the breaker;
      * a persistent fault (device lost) trips it immediately;
      * while OPEN, `allow_device()` is False until `open_duration_s`
        elapses, then the state moves to HALF_OPEN and exactly the next
        cycle runs on device as a canary: success closes the breaker
        (fast path restored), any failure re-opens it.

    Single-scheduling-thread invariant: like DeviceSnapshotCache, this
    object is only mutated from the scheduling thread (dispatch/fence/
    preempt all run there), so state transitions need no lock; reads from
    other threads (healthz) see a consistent-enough snapshot.

    `clock` and the seeded rng keep tests deterministic."""

    def __init__(
        self,
        failure_threshold: int = 3,
        open_duration_s: float = 0.05,
        backoff_base_s: float = 0.005,
        backoff_max_s: float = 0.05,
        backoff_jitter: float = 0.5,
        seed: int = 0,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable[[str, str], None]] = None,
        transitions_maxlen: int = 256,
    ):
        self.failure_threshold = max(1, int(failure_threshold))
        self.open_duration_s = float(open_duration_s)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.backoff_jitter = float(backoff_jitter)
        self._rng = random.Random(seed)
        self._clock = clock
        self._on_transition = on_transition
        self.state = BREAKER_CLOSED
        self.consecutive_failures = 0
        self.fault_counts: Dict[str, int] = {}
        # (from, to) audit trail — the breaker's transition history, pinned
        # by the chaos tests (open -> half_open -> closed on recovery).
        # BOUNDED: a flapping device transitions forever, and a long-lived
        # scheduler must not leak memory for it — the deque keeps the
        # recent window for postmortems while the UNBOUNDED record is the
        # scheduler_device_breaker_transitions_total counter family.
        self.transitions: deque = deque(maxlen=max(1, int(transitions_maxlen)))
        self.probes = 0  # half-open canary batches granted
        self._opened_at = 0.0
        # NB: the gauge is only written on TRANSITIONS (its zero-value
        # default already means closed): constructing a second
        # DeviceHealth must not reset another instance's exported state.
        # With multiple schedulers in one process the unlabeled gauge is
        # last-writer-wins; the per-instance truth lives in .state.

    # ------------------------------------------------------------ queries

    @property
    def device_available(self) -> bool:
        """Non-mutating: is the fast path currently trusted?  (allow_device
        may transition open->half_open; this never does — preemption and
        other secondary device users key off it so they cannot consume the
        canary probe.)"""
        return self.state == BREAKER_CLOSED

    def allow_device(self) -> bool:
        """Gate for the next scheduling cycle's engine choice.  CLOSED:
        yes.  OPEN: no, until the cool-down elapses — then HALF_OPEN and
        yes (the canary).  HALF_OPEN: yes (at most one cycle is in flight
        on the single scheduling thread)."""
        if self.state == BREAKER_OPEN and (
            self._clock() - self._opened_at >= self.open_duration_s
        ):
            self._transition(BREAKER_HALF_OPEN)
        if self.state == BREAKER_HALF_OPEN:
            self.probes += 1
            return True
        return self.state == BREAKER_CLOSED

    # ------------------------------------------------------------ updates

    def record_failure(self, fault_class: str) -> bool:
        """Account one classified device failure; returns True when the
        breaker is OPEN afterwards (callers stop retrying and degrade)."""
        self.consecutive_failures += 1
        self.fault_counts[fault_class] = (
            self.fault_counts.get(fault_class, 0) + 1
        )
        if (
            self.state == BREAKER_HALF_OPEN           # canary failed
            or fault_class == FAULT_PERSISTENT        # device lost
            or self.consecutive_failures >= self.failure_threshold
        ):
            self.trip()
        return self.state == BREAKER_OPEN

    def record_success(self) -> None:
        """A device cycle completed: reset the failure streak; a HALF_OPEN
        canary success restores the fast path."""
        self.consecutive_failures = 0
        if self.state != BREAKER_CLOSED:
            self._transition(BREAKER_CLOSED)

    def trip(self) -> None:
        """Force the breaker OPEN and (re)start the cool-down clock."""
        if self.state != BREAKER_OPEN:
            self._transition(BREAKER_OPEN)
        self._opened_at = self._clock()

    def backoff_s(self, attempt: int) -> float:
        """Jittered exponential backoff for transient-retry `attempt`
        (0-based).  Jitter is additive-proportional (delay * [1, 1+j]) from
        the seeded rng; the cap applies AFTER jitter so no sleep ever
        exceeds backoff_max_s (the fault-matrix tests run inside tier-1)."""
        base = self.backoff_base_s * (2.0 ** attempt)
        jittered = base * (1.0 + self.backoff_jitter * self._rng.random())
        return min(jittered, self.backoff_max_s)

    def _transition(self, to: str) -> None:
        frm, self.state = self.state, to
        self.transitions.append((frm, to))
        m.BREAKER_STATE.set(_STATE_GAUGE[to])
        m.BREAKER_TRANSITIONS.inc(to=to)
        if self._on_transition is not None:
            self._on_transition(frm, to)


class ShardHealth:
    """Per-shard breaker bank: one circuit breaker PER MESH DEVICE,
    alongside the global DeviceHealth breaker.

    The global breaker answers "can the device path be trusted at all";
    this bank answers "which shard is the problem" — the attribution the
    elastic degradation ladder (runtime/scheduler.py) needs to rebuild
    the mesh without the failing device instead of demoting an 8-chip
    control plane to the sequential CPU adapter over one dead shard.

    Per shard, the lifecycle mirrors DeviceHealth: closed -> open on a
    persistent fault / `failure_threshold` consecutive classified
    failures / a failed half-open probe; open -> half_open once
    `open_duration_s` elapses (probe_due); half_open -> closed on a
    successful probe OF THAT DEVICE (the canary targets the lost shard,
    not the surviving mesh).  A shard whose breaker is not closed is out
    of the live mesh (`lost()`).

    Single-scheduling-thread invariant: mutated only from the scheduling
    thread (fault handling and probes both run there); reads from other
    threads (telemetry, /debug/cluster, heartbeat) see a
    consistent-enough snapshot, like DeviceHealth."""

    def __init__(
        self,
        device_ids: Iterable[int],
        failure_threshold: int = 2,
        open_duration_s: float = 0.05,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable[[int, str, str], None]] = None,
        transitions_maxlen: int = 256,
    ):
        self.failure_threshold = max(1, int(failure_threshold))
        self.open_duration_s = float(open_duration_s)
        self._clock = clock
        self._on_transition = on_transition
        self.ids: Tuple[int, ...] = tuple(int(d) for d in device_ids)
        self._state: Dict[int, str] = {d: BREAKER_CLOSED for d in self.ids}
        self._consecutive: Dict[int, int] = {d: 0 for d in self.ids}
        self._opened_at: Dict[int, float] = {}
        self.fault_counts: Dict[int, Dict[str, int]] = {
            d: {} for d in self.ids
        }
        # (shard, from, to) — bounded like DeviceHealth.transitions; the
        # unbounded record is the shard-labeled metric families
        self.transitions: deque = deque(maxlen=max(1, int(transitions_maxlen)))
        self.probes: Dict[int, int] = {d: 0 for d in self.ids}

    # ------------------------------------------------------------ queries

    def state(self, shard: int) -> str:
        return self._state[shard]

    def states(self) -> Dict[int, str]:
        """{device id: breaker state} snapshot (telemetry/debug)."""
        return dict(self._state)

    def lost(self) -> frozenset:
        """Device ids currently out of the live mesh (breaker not
        closed — open or half_open-probing)."""
        return frozenset(
            d for d, s in self._state.items() if s != BREAKER_CLOSED
        )

    def probe_due(self, shard: int) -> bool:
        """Half-open gate for the lost-shard canary: OPEN moves to
        HALF_OPEN once the cool-down elapses; HALF_OPEN stays probe-able
        (at most one probe is in flight on the scheduling thread)."""
        s = self._state[shard]
        if s == BREAKER_OPEN and (
            self._clock() - self._opened_at.get(shard, 0.0)
            >= self.open_duration_s
        ):
            self._transition(shard, BREAKER_HALF_OPEN)
            s = BREAKER_HALF_OPEN
        if s == BREAKER_HALF_OPEN:
            self.probes[shard] += 1
            return True
        return False

    # ------------------------------------------------------------ updates

    def record_failure(self, shard: int, fault_class: str) -> bool:
        """Account one classified fault attributed to `shard`.  Returns
        True only when this failure NEWLY opened the shard's breaker (the
        ladder's shrink trigger fires once per loss; repeat faults on an
        already-lost shard fall through to the global policy)."""
        self._consecutive[shard] = self._consecutive.get(shard, 0) + 1
        counts = self.fault_counts.setdefault(shard, {})
        counts[fault_class] = counts.get(fault_class, 0) + 1
        m.SHARD_FAULTS.inc(shard=str(shard), **{"class": fault_class})
        state = self._state[shard]
        if state == BREAKER_OPEN:
            # already lost: restart the cool-down, nothing new
            self._opened_at[shard] = self._clock()
            return False
        if (
            state == BREAKER_HALF_OPEN           # probe of the shard failed
            or fault_class == FAULT_PERSISTENT   # shard lost
            or self._consecutive[shard] >= self.failure_threshold
        ):
            self._transition(shard, BREAKER_OPEN)
            self._opened_at[shard] = self._clock()
            return True
        return False

    def record_success(self, shard: int) -> None:
        """A probe of the lost shard succeeded (or a closed shard served
        cleanly): reset its streak and close its breaker."""
        self._consecutive[shard] = 0
        if self._state[shard] != BREAKER_CLOSED:
            self._transition(shard, BREAKER_CLOSED)

    def heal(self, shards: Iterable[int]) -> None:
        """A device round-trip over `shards` succeeded: reset their
        consecutive-failure streaks — the per-shard analog of
        DeviceHealth.record_success healing the global streak after
        every clean cycle.  Without this the "consecutive" counter is
        secretly cumulative: two isolated transients weeks apart would
        cross the threshold and shrink the mesh.  Only CLOSED shards
        heal — a lost shard's streak belongs to its half-open probe
        (record_success), and it was not part of this round-trip."""
        for d in shards:
            if self._state.get(d) == BREAKER_CLOSED:
                self._consecutive[d] = 0

    def _transition(self, shard: int, to: str) -> None:
        frm = self._state[shard]
        self._state[shard] = to
        self.transitions.append((shard, frm, to))
        m.SHARD_BREAKER_STATE.set(_STATE_GAUGE[to], shard=str(shard))
        if self._on_transition is not None:
            self._on_transition(shard, frm, to)


class HealthServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        healthy: Optional[Callable[[], bool]] = None,
        registry=None,
        traces: Optional[Callable[[], dict]] = None,
    ):
        self._healthy = healthy or (lambda: True)
        self._registry = registry or m.REGISTRY
        # /debug/traces: the flight recorder's span ring as Chrome
        # trace-event JSON (open in Perfetto / chrome://tracing).  The
        # default serves the process-wide recorder — the one a default-
        # constructed Scheduler records into.
        if traces is None:
            from kubernetes_tpu.runtime.flightrecorder import RECORDER

            traces = RECORDER.chrome_trace
        self._traces = traces
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def _send(self, body: bytes, code: int = 200, ct: str = "text/plain"):
                self.send_response(code)
                self.send_header("Content-Type", ct)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path, _, query = self.path.partition("?")
                if path == "/healthz":
                    if outer._healthy():
                        self._send(b"ok")
                    else:
                        self._send(b"unhealthy", 500)
                elif path == "/metrics":
                    self._send(
                        outer._registry.expose().encode(),
                        ct="text/plain; version=0.0.4",
                    )
                else:
                    # EVERY debug endpoint routes through the shared
                    # table (runtime/ledger.py DEBUG_RENDERERS) — one
                    # registration serves this server AND the
                    # apiserver, so an endpoint can no longer be
                    # exposed on one and forgotten on the other.  The
                    # constructor-injected traces callable rides the
                    # overrides seam.
                    from kubernetes_tpu.runtime.ledger import (
                        debug_dispatch,
                    )

                    body = debug_dispatch(
                        path, query, overrides={"traces": outer._traces}
                    )
                    if body is None:
                        self._send(b"not found", 404)
                    else:
                        self._send(body, ct="application/json")

            def do_POST(self):
                path, _, query = self.path.partition("?")
                from kubernetes_tpu.runtime.ledger import debug_post

                res = debug_post(path, query)
                if res is None:
                    self._send(b"not found", 404)
                else:
                    code, body = res
                    self._send(body, code, ct="application/json")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self):
        return self._httpd.server_address

    def start(self) -> "HealthServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def start_health_server(host: str = "127.0.0.1", port: int = 0, **kw) -> HealthServer:
    return HealthServer(host, port, **kw).start()
