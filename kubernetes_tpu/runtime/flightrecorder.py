"""Flight recorder: always-on bounded span ring + anomaly postmortems.

The black-box model (the aviation metaphor is exact): the scheduler
records every finished cycle's root span into a small ring buffer at
negligible cost — a deque append per cycle, no serialization — so when
an anomaly fires the seconds BEFORE it are already captured.  Anomaly
triggers (wired in runtime/scheduler.py): breaker trip, shed burst,
cycle-deadline overrun, degraded cycle, unclassified device error.
Each trigger dumps a postmortem snapshot: the ring's span trees, a
caller-supplied state dict (queue depth, breaker/AIMD state), and the
metrics registry text — everything a human needs to reconstruct the
incident without having had debug logging on.

The reference has no analog (kubelet's flight-recorder-style node
problem detector is the closest cousin); PAPERS' Gavel/RL-tuning lines
both assume exactly this per-decision timeline exists.

`RECORDER` is the process-wide default (the metrics REGISTRY pattern):
the scheduler records into it unless handed its own instance, and the
health server + apiserver serve it at /debug/traces.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.utils.trace import Span, chrome_trace


class FlightRecorder:
    """Bounded ring of finished cycle spans + bounded postmortem log.

    Thread-safe: record() is called from the scheduling thread,
    postmortem() from scheduling/event paths, readers (HTTP handlers)
    from server threads.  Postmortems are throttled PER TRIGGER
    (min_interval_s) so a shed storm produces one snapshot, not one per
    dropped pod; the first firing of each trigger always lands."""

    def __init__(
        self,
        capacity: int = 64,
        postmortem_capacity: int = 16,
        postmortem_min_interval_s: float = 0.5,
    ):
        self.capacity = int(capacity)
        self._ring: "deque[Span]" = deque(maxlen=max(1, self.capacity))
        self._postmortems: "deque[dict]" = deque(
            maxlen=max(1, int(postmortem_capacity))
        )
        self.min_interval_s = float(postmortem_min_interval_s)
        self._last_fired: Dict[str, float] = {}
        self._lock = threading.Lock()
        self.recorded_total = 0
        self.postmortem_total = 0

    # ------------------------------------------------------------ recording

    def record(self, span: Span) -> None:
        """Retire one finished cycle span into the ring (O(1), the
        always-on cost — no serialization happens here)."""
        with self._lock:
            self._ring.append(span)
            self.recorded_total += 1

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._postmortems.clear()
            self._last_fired.clear()

    # ----------------------------------------------------------- postmortem

    def postmortem(
        self,
        trigger: str,
        detail: str = "",
        state=None,  # dict, or a () -> dict thunk (lazy, see below)
        metrics_text: Optional[Callable[[], str]] = None,
        in_flight: Optional[List[Span]] = None,
    ) -> Optional[dict]:
        """Snapshot the ring + system state for one anomaly.  Returns the
        snapshot dict, or None when this trigger fired inside its
        throttle window (the storm case — the first snapshot already
        captured the lead-up).  `metrics_text` — and `state`, which may
        be a dict OR a thunk returning one — are evaluated only when the
        snapshot actually fires: a shed storm calls this once per
        dropped pod, and the throttled calls must not pay for a state
        snapshot they discard.  `in_flight` carries the CURRENT cycle's
        (possibly unfinished) span — a breaker trip fires mid-cycle,
        before the failing cycle retires into the ring, and the
        postmortem must still contain its spans."""
        now = time.monotonic()
        with self._lock:
            last = self._last_fired.get(trigger)
            if last is not None and now - last < self.min_interval_s:
                return None
            self._last_fired[trigger] = now
            ring = list(self._ring)
        if callable(state):
            try:
                state = state()
            except Exception as e:  # noqa: BLE001 — never lose the snapshot
                state = {"error": f"<state unavailable: {e}>"}
        ring_ids = {sp.span_id for sp in ring}
        live = [
            sp for sp in (in_flight or ())
            if sp is not None and sp.span_id not in ring_ids
        ]
        snap = {
            "trigger": trigger,
            "detail": detail,
            "time": time.time(),
            "monotonic": now,
            "state": dict(state or {}),
            "cycles": [sp.to_dict() for sp in ring],
            "in_flight": [sp.to_dict() for sp in live],
        }
        if metrics_text is not None:
            try:
                snap["metrics"] = metrics_text()
            except Exception as e:  # noqa: BLE001 — never lose the snapshot
                snap["metrics"] = f"<metrics unavailable: {e}>"
        with self._lock:
            self._postmortems.append(snap)
            self.postmortem_total += 1
        return snap

    def postmortems(self, trigger: Optional[str] = None) -> List[dict]:
        with self._lock:
            out = list(self._postmortems)
        if trigger is not None:
            out = [p for p in out if p["trigger"] == trigger]
        return out

    # --------------------------------------------------------------- export

    def chrome_trace(self, limit: Optional[int] = None) -> dict:
        """The ring as Chrome trace-event JSON, with one instant event
        per recorded postmortem so anomalies show up ON the timeline.
        `limit` keeps only the NEWEST n cycle spans (the /debug/traces
        ?limit=N query; the handlers also halve it until the body fits
        the hard response-size cap)."""
        spans = self.spans()
        if limit is not None and limit >= 0:
            spans = spans[-limit:] if limit else []
        out = chrome_trace(spans)
        with self._lock:
            pms = list(self._postmortems)
        for pm in pms:
            out["traceEvents"].append({
                "name": f"postmortem:{pm['trigger']}",
                "cat": "ktpu.anomaly",
                "ph": "i",
                "s": "g",  # global-scope instant: draws across all tracks
                "ts": int(pm["monotonic"] * 1e6),
                "pid": 1,
                "tid": 1,
                "args": {"detail": pm["detail"]},
            })
        return out


# process-wide default (the REGISTRY pattern in utils/metrics.py): one
# ring every component records into unless wired with its own instance.
# RECORDER stays a real module binding — callers import it by value —
# while the install/replica registry rides the shared ProcessDefault
# helper (runtime/defaults.py) like its observability siblings.
RECORDER = FlightRecorder()

from kubernetes_tpu.runtime.defaults import ProcessDefault  # noqa: E402

_DEFAULT = ProcessDefault("flightrecorder")
_DEFAULT.set(RECORDER)


def get_default() -> FlightRecorder:
    return _DEFAULT.get()


def set_default(rec: FlightRecorder, replica: int = 0) -> None:
    global RECORDER
    _DEFAULT.set(rec, replica)
    if int(replica) == 0:
        RECORDER = rec


def replica_instances() -> dict:
    """{replica id: FlightRecorder} of every install this process saw."""
    return _DEFAULT.replicas()
