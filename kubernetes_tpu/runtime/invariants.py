"""Online invariant checker: conservation proofs across every failure path.

The resilience stack (PRs 3/4/9/10) promises "popped pods are never
lost" on every path — retry, breaker trip, CPU degrade, mesh shrink,
gang demotion, bind failure, shed storm.  Each path carries its own
requeue guard, but the promise itself was only checked by test
assertions AFTER a scenario ran.  This module makes it a LIVE property:
a cheap, always-on checker fed from the existing commit seams, so a
chaos soak over the whole degradation ladder is pass/fail by
construction ("zero `scheduler_invariant_violations_total`") instead of
a per-scenario bookkeeping exercise.

Rules (the `rule` label on the metric):

  conservation  every pod popped from the scheduling queue ends in
                EXACTLY one of bound / requeued / shed — resolved twice,
                or re-popped while an earlier pop is unresolved, is a
                violation.  (Unschedulable verdicts requeue — the
                unschedulableQ — so "requeued" covers both.)
  double_bind   a pod reported bound while the checker still holds it
                bound from an earlier cycle (no intervening requeue/
                removal): the double-charge bug class the gang recovery
                path is guarded against.
  capacity      committed per-node usage exceeds allocatable on a row a
                cycle just committed to (checked only over the rows the
                cycle touched, so the check is O(batch), not O(N)).
  lost_pod      assert_drained() found popped-but-unresolved pods after
                the queue and pipeline drained — the direct "pods went
                missing" detector chaos soaks call at teardown.

Violations never raise into the scheduling loop: each one increments
scheduler_invariant_violations_total{rule=}, records into a bounded
ring, and fires the scheduler's flight-recorder postmortem seam — a
checker must report corruption, not add a crash path to it.

The checker deliberately tracks only pods it saw popped (note_popped):
direct schedule_cycle() callers and informer-driven re-adds resolve
keys the checker never registered, and those are ignored rather than
misread as violations.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from kubernetes_tpu.utils import klog
from kubernetes_tpu.utils import metrics as m

RULE_CONSERVATION = "conservation"
RULE_DOUBLE_BIND = "double_bind"
RULE_CAPACITY = "capacity"
RULE_LOST_POD = "lost_pod"
# autoscaler actuation rules (ISSUE 19: runtime/autoscaler.py)
RULE_NODE_LIFECYCLE = "node_lifecycle"
RULE_EVICTION_BUDGET = "eviction_budget"
RULE_CAPACITY_FLOOR = "capacity_floor"

# node lifecycle states (the RULE_NODE_LIFECYCLE vocabulary): every
# node REGISTERED by an actuation must end active, drained (rolled
# back to service counts as active), or removed — a node stuck
# mid-transition at settle time is the autoscaler's lost-pod analog
NODE_REGISTERED = "registered"
NODE_ACTIVE = "active"
NODE_DRAINING = "draining"
NODE_REMOVED = "removed"

# resolution kinds for a popped pod (the conservation vocabulary)
RES_BOUND = "bound"
RES_REQUEUED = "requeued"
RES_SHED = "shed"

# small slack over the engines' f32 arithmetic: the encoder accumulates
# requests in float32, so exact <= comparisons would fire on rounding
_CAPACITY_EPS = 1e-3
_CAPACITY_REL = 1e-5


class InvariantChecker:
    """The always-on conservation checker (see module docstring).

    Thread-safe: the scheduling thread feeds pops/requeues/capacity,
    while binds may arrive from waiting-pod threads and sheds from any
    add() caller; one lock guards the tracking maps.  Cost per event is
    a dict operation — the perf budget rides the existing <2%-of-cycle
    telemetry pin."""

    def __init__(
        self,
        on_violation: Optional[Callable[[str, str], None]] = None,
        max_tracked: int = 65536,
        violations_maxlen: int = 256,
    ):
        self._lock = threading.Lock()
        self._on_violation = on_violation
        self._max_tracked = max(16, int(max_tracked))
        # pod key -> [cycle, resolution-or-None]; insertion-ordered so
        # resolved entries age out at the cap (unresolved entries are
        # exactly what assert_drained must keep)
        self._tracked: "OrderedDict[Tuple[str, str], List]" = OrderedDict()
        # unresolved-entry count, maintained incrementally: summary()
        # runs on the per-cycle telemetry seam, so it must be O(1), not
        # an O(tracked) scan (the <2%-of-cycle telemetry pin)
        self._outstanding = 0
        # pod key -> node for pods the scheduler believes bound
        self._bound: "OrderedDict[Tuple[str, str], str]" = OrderedDict()
        self.violations: deque = deque(maxlen=max(1, int(violations_maxlen)))
        self.counts: Dict[str, int] = {}
        self.events_total = 0
        # violations recorded under the lock, fired to on_violation AFTER
        # it is released: the callback is the scheduler's postmortem seam,
        # whose state dump re-enters summary() — invoking it with the
        # (non-reentrant) lock held would deadlock the scheduling thread
        # on the first real violation
        self._pending_cb: List[Tuple[str, str]] = []
        # node name -> lifecycle state for nodes an actuation registered
        # or is draining (RULE_NODE_LIFECYCLE); counts maintained
        # incrementally so summary() stays O(1)
        self._node_state: Dict[str, str] = {}
        self._node_counts: Dict[str, int] = {}

    # ------------------------------------------------------------- seams

    @staticmethod
    def _key(pod) -> Tuple[str, str]:
        return (pod.namespace, pod.name)

    def note_popped(self, pods, cycle: int = 0) -> None:
        """A batch left the queue (run_once / the express lane): each pod
        must come back through exactly one resolution seam."""
        if not pods:
            return
        with self._lock:
            self.events_total += len(pods)
            for pod in pods:
                key = self._key(pod)
                entry = self._tracked.get(key)
                if entry is not None and entry[1] is None:
                    self._violation_locked(
                        RULE_CONSERVATION,
                        f"pod {key[0]}/{key[1]} popped again while its "
                        f"cycle-{entry[0]} pop is unresolved",
                    )
                # a re-popped pod was requeued: whatever bind the checker
                # still holds was forgotten by the rollback path
                self._bound.pop(key, None)
                if entry is None or entry[1] is not None:
                    self._outstanding += 1
                self._tracked[key] = [cycle, None]
                self._tracked.move_to_end(key)
            self._prune_locked()
        self._fire_callbacks()

    def note_bound(self, pod, node: str = "") -> None:
        """A bind succeeded (batched tail / per-pod / gang / async
        waiting-pod completion)."""
        key = self._key(pod)
        with self._lock:
            self.events_total += 1
            if key in self._bound:
                self._violation_locked(
                    RULE_DOUBLE_BIND,
                    f"pod {key[0]}/{key[1]} bound to {node or '?'} while "
                    f"already bound to {self._bound[key] or '?'}",
                )
            self._bound[key] = node
            while len(self._bound) > self._max_tracked:
                self._bound.popitem(last=False)
            self._resolve_locked(key, RES_BOUND)
        self._fire_callbacks()

    def note_requeued(self, pod) -> None:
        """The pod went back into the queue (unschedulable verdict, bind
        failure rollback, gang surplus readd, batch-loss guard)."""
        key = self._key(pod)
        with self._lock:
            self.events_total += 1
            self._bound.pop(key, None)
            self._resolve_locked(key, RES_REQUEUED)
        self._fire_callbacks()

    def note_shed(self, pod) -> None:
        """The bounded queue dropped the pod (overload shedding)."""
        key = self._key(pod)
        with self._lock:
            self.events_total += 1
            entry = self._tracked.get(key)
            if entry is not None and entry[1] is None:
                # a popped pod is not IN the queue, so the queue shedding
                # it means double-tracking — still record the resolution
                # so drain checks stay meaningful
                self._violation_locked(
                    RULE_CONSERVATION,
                    f"pod {key[0]}/{key[1]} shed while popped",
                )
            if entry is not None:
                # shed ends the pod's life in this control plane: drop
                # the entry so a same-name re-create starts clean
                if entry[1] is None:
                    self._outstanding -= 1
                del self._tracked[key]
            self._bound.pop(key, None)
        self._fire_callbacks()

    def note_displaced(self, pod) -> None:
        """A BOUND pod was displaced back toward the queue by a
        cluster-lifecycle event (NodeLifecycleController eviction, a
        drain wave, a zone outage): clear the bound mark and drop the
        tracked entry so the shed-exempt displaced requeue is NOT
        misread as "resolved twice: bound then requeued" and the pod's
        next pop opens a fresh conservation window.  Mass eviction is a
        legal lifecycle transition, not a conservation bug — the rules
        resume the moment the displaced pod is popped again."""
        key = self._key(pod)
        with self._lock:
            self.events_total += 1
            self._bound.pop(key, None)
            entry = self._tracked.pop(key, None)
            if entry is not None and entry[1] is None:
                self._outstanding -= 1

    def note_removed(self, pod) -> None:
        """The pod left the cluster entirely (preemption victim delete,
        informer delete): clear every mark so a same-name successor
        starts clean."""
        key = self._key(pod)
        with self._lock:
            self._bound.pop(key, None)
            entry = self._tracked.pop(key, None)
            if entry is not None and entry[1] is None:
                self._outstanding -= 1

    def check_capacity(self, rows, requested, allocatable,
                       row_name=None) -> None:
        """Committed usage <= allocatable over the node rows a cycle just
        committed to.  `requested`/`allocatable` are the encoder's f32
        [N, R] arrays (read under the cache lock by the caller); `rows`
        the touched row indices."""
        if len(rows) == 0:
            return
        rows = np.asarray(rows, np.int64)
        req = np.asarray(requested)[rows]
        alloc = np.asarray(allocatable)[rows]
        # only columns with declared capacity: PodFitsResources compares
        # per-requested-resource (used + req <= alloc), so committed
        # usage in an undeclared (zero-allocatable) column is always 0 —
        # comparing it would only trip the checker on float dust
        over = (req > alloc * (1.0 + _CAPACITY_REL) + _CAPACITY_EPS) & (
            alloc > 0.0
        )
        with self._lock:
            self.events_total += 1
        if not over.any():
            return
        bad_rows = rows[np.flatnonzero(over.any(axis=1))]
        names = [
            (row_name(int(r)) if row_name is not None else str(int(r)))
            for r in bad_rows[:4]
        ]
        with self._lock:
            self._violation_locked(
                RULE_CAPACITY,
                f"committed usage exceeds allocatable on {len(bad_rows)} "
                f"node(s): {', '.join(names)}",
            )
        self._fire_callbacks()

    def assert_drained(self) -> bool:
        """After the queue AND pipeline drained, no popped pod may still
        be unresolved.  Returns True when clean; on failure records ONE
        lost_pod violation naming a sample and clears the stale entries
        (so a soak's next phase is judged on its own)."""
        with self._lock:
            lost = [k for k, e in self._tracked.items() if e[1] is None]
            if not lost:
                return True
            sample = ", ".join(f"{ns}/{n}" for ns, n in lost[:4])
            self._violation_locked(
                RULE_LOST_POD,
                f"{len(lost)} popped pod(s) unresolved after drain: "
                f"{sample}",
            )
            for k in lost:
                del self._tracked[k]
            self._outstanding -= len(lost)
        self._fire_callbacks()
        return False

    # --------------------------------------- autoscaler seams (ISSUE 19)

    def _node_set_locked(self, name: str, state: Optional[str]) -> None:
        old = self._node_state.pop(name, None)
        if old is not None:
            self._node_counts[old] -= 1
            if not self._node_counts[old]:
                del self._node_counts[old]
        if state is not None:
            self._node_state[name] = state
            self._node_counts[state] = self._node_counts.get(state, 0) + 1

    def note_node_registered(self, name: str) -> None:
        """An actuation registered a node into the store (scale-up).
        It must be reported active (schedulable), draining, or removed
        before assert_nodes_settled — a registered node that vanishes
        from the seams is leaked capacity."""
        with self._lock:
            self.events_total += 1
            if self._node_state.get(name) in (NODE_REGISTERED,
                                              NODE_ACTIVE, NODE_DRAINING):
                self._violation_locked(
                    RULE_NODE_LIFECYCLE,
                    f"node {name} registered while already "
                    f"{self._node_state[name]}",
                )
            self._node_set_locked(name, NODE_REGISTERED)
        self._fire_callbacks()

    def note_node_active(self, name: str) -> None:
        """The node is serving (registered node confirmed schedulable,
        or a drain rolled back to service)."""
        with self._lock:
            self.events_total += 1
            self._node_set_locked(name, NODE_ACTIVE)

    def note_node_draining(self, name: str) -> None:
        """A scale-down cordoned the node; it must end removed or be
        rolled back to active."""
        with self._lock:
            self.events_total += 1
            self._node_set_locked(name, NODE_DRAINING)

    def note_node_removed(self, name: str) -> None:
        """The node left the store (drain completed + delete, or a
        faulted scale-up batch deregistered): terminal, clears the
        entry so a same-name re-registration starts clean."""
        with self._lock:
            self.events_total += 1
            if name in self._node_state:
                self._node_set_locked(name, None)
        self._fire_callbacks()

    def assert_nodes_settled(self) -> bool:
        """Node-lifecycle conservation at settle time (scenario/bench
        teardown): every node an actuation touched must be active or
        removed — anything still 'registered' (never confirmed) or
        'draining' (cordon without a completed drain OR rollback) is a
        violation.  Clears the stuck entries so a soak's next phase is
        judged on its own, mirroring assert_drained."""
        with self._lock:
            stuck = [
                n for n, s in self._node_state.items()
                if s in (NODE_REGISTERED, NODE_DRAINING)
            ]
            if not stuck:
                return True
            sample = ", ".join(
                f"{n}({self._node_state[n]})" for n in stuck[:4]
            )
            self._violation_locked(
                RULE_NODE_LIFECYCLE,
                f"{len(stuck)} node(s) stuck mid-transition after "
                f"settle: {sample}",
            )
            for n in stuck:
                self._node_set_locked(n, None)
        self._fire_callbacks()
        return False

    def note_evicted(self, pod, pdbs_matching: int,
                     budgets_debited: int) -> None:
        """An eviction was GRANTED (controllers.try_evict): every
        matching PDB must have been debited one disruption unit — an
        eviction that slipped past a matching budget is the
        thundering-drain race the debit-under-lock exists to close."""
        with self._lock:
            self.events_total += 1
            if pdbs_matching > 0 and budgets_debited < pdbs_matching:
                key = self._key(pod)
                self._violation_locked(
                    RULE_EVICTION_BUDGET,
                    f"pod {key[0]}/{key[1]} evicted with "
                    f"{budgets_debited}/{pdbs_matching} matching "
                    f"budget(s) debited",
                )
        self._fire_callbacks()

    def check_capacity_floor(self, remaining, committed,
                             detail: str = "") -> bool:
        """Scale-down guard: fleet allocatable AFTER removing the drain
        set must still cover committed usage per resource.  `remaining`
        and `committed` are f64[R] totals.  Returns True when the floor
        holds; False records a violation (the actuator also refuses the
        removal — capacity never drops below committed usage)."""
        remaining = np.asarray(remaining, np.float64)
        committed = np.asarray(committed, np.float64)
        with self._lock:
            self.events_total += 1
        under = committed > remaining * (1.0 + _CAPACITY_REL) + _CAPACITY_EPS
        if not under.any():
            return True
        with self._lock:
            self._violation_locked(
                RULE_CAPACITY_FLOOR,
                f"scale-down would drop fleet capacity below committed "
                f"usage in {int(under.sum())} resource column(s)"
                + (f" ({detail})" if detail else ""),
            )
        self._fire_callbacks()
        return False

    # ---------------------------------------------------------- internals

    def _resolve_locked(self, key, kind: str) -> None:
        entry = self._tracked.get(key)
        if entry is None:
            return  # not popped through a tracked seam: ignore
        if entry[1] is not None:
            self._violation_locked(
                RULE_CONSERVATION,
                f"pod {key[0]}/{key[1]} resolved twice: "
                f"{entry[1]} then {kind}",
            )
        else:
            self._outstanding -= 1
        entry[1] = kind

    def _prune_locked(self) -> None:
        """Age out RESOLVED entries beyond the cap (oldest first);
        unresolved entries are never pruned — they are the lost-pod
        evidence."""
        if len(self._tracked) <= self._max_tracked:
            return
        for key in list(self._tracked):
            if len(self._tracked) <= self._max_tracked:
                break
            if self._tracked[key][1] is not None:
                del self._tracked[key]

    def _violation_locked(self, rule: str, detail: str) -> None:
        self.counts[rule] = self.counts.get(rule, 0) + 1
        self.violations.append((rule, detail))
        m.INVARIANT_VIOLATIONS.inc(rule=rule)
        klog.errorf("invariant violation (%s): %s", rule, detail)
        self._pending_cb.append((rule, detail))

    def _fire_callbacks(self) -> None:
        """Deliver violations queued by _violation_locked to the
        on_violation callback OUTSIDE the lock (see _pending_cb).  Every
        public seam calls this after releasing; exceptions never escape
        (a checker must report corruption, not add a crash path)."""
        if self._on_violation is None:
            return
        with self._lock:
            if not self._pending_cb:
                return
            pending, self._pending_cb = self._pending_cb, []
        for rule, detail in pending:
            try:
                self._on_violation(rule, detail)
            except Exception as e:  # noqa: BLE001 — never crash the loop
                klog.errorf("invariant-violation callback failed: %s", e)

    # ------------------------------------------------------------ readers

    def violations_total(self) -> int:
        return sum(self.counts.values())

    def summary(self) -> dict:
        """Bounded state for /debug/cluster + the heartbeat line.  O(1)
        in the tracked population — it runs on the per-cycle telemetry
        seam (record_mesh), inside the <2%-of-cycle pin."""
        with self._lock:
            return {
                "violations": dict(self.counts),
                "violations_total": sum(self.counts.values()),
                "outstanding": self._outstanding,
                "tracked": len(self._tracked),
                "bound": len(self._bound),
                "nodes": dict(self._node_counts),
                "recent": [list(v) for v in list(self.violations)[-8:]],
            }
