"""Controller-completeness sweep: the last 8 non-cloud reconcilers
(VERDICT r3 #7 / missing #4) — storage-object protection finalizers,
ClusterRole aggregation, node TTL annotations, bootstrap-token signing of
the cluster-info ConfigMap, CSR garbage collection, PVC expansion, and the
root-CA ConfigMap publisher.  Each is a small reconciler on the existing
WorkQueue/Reconciler machinery (runtime/controllers.py).

Reference:
  * pkg/controller/volume/pvcprotection/pvc_protection_controller.go:1-288
    and .../pvprotection: a finalizer (kubernetes.io/pvc-protection /
    kubernetes.io/pv-protection) defers deletion while the object is in
    use; the store's finalizer semantics live in runtime/cluster.py
    delete/update.
  * pkg/controller/clusterroleaggregation/clusterroleaggregation_controller.go:1-213:
    ClusterRoles with an aggregationRule get .rules = union of the rules
    of every ClusterRole matched by the label selectors.
  * pkg/controller/ttl/ttl_controller.go:1-291: annotate nodes with
    node.alpha.kubernetes.io/ttl from cluster-size boundaries (with the
    reference's hysteresis bands).
  * pkg/controller/bootstrap/bootstrapsigner.go:1-306: detached-JWS-sign
    the kube-public/cluster-info ConfigMap with every signing-enabled
    bootstrap token (jws-kubeconfig-<tokenid> keys).
  * pkg/controller/certificates/cleaner/cleaner.go: drop CSRs that are
    approved/denied older than 1h or pending older than 24h.
  * pkg/controller/volume/expand/expand_controller.go: grow the bound
    PV when a claim requests more than the volume provides.
  * pkg/controller/certificates/rootcacertpublisher/publisher.go:
    a kube-root-ca.crt ConfigMap in every active namespace.
"""

from __future__ import annotations

import base64
import dataclasses
import hashlib
import hmac
import json
import time
from typing import Optional

from kubernetes_tpu.runtime.cluster import (
    ADDED,
    DELETED,
    MODIFIED,
    ConflictError,
    LocalCluster,
)
from kubernetes_tpu.runtime.controllers import Reconciler

PVC_PROTECTION_FINALIZER = "kubernetes.io/pvc-protection"
PV_PROTECTION_FINALIZER = "kubernetes.io/pv-protection"
TTL_ANNOTATION = "node.alpha.kubernetes.io/ttl"
ROOT_CA_CONFIGMAP = "kube-root-ca.crt"


def _with_finalizer(meta, fin: str):
    if fin in meta.finalizers:
        return meta
    return dataclasses.replace(meta, finalizers=meta.finalizers + (fin,))


def _without_finalizer(meta, fin: str):
    return dataclasses.replace(
        meta, finalizers=tuple(f for f in meta.finalizers if f != fin)
    )


class PVCProtectionController(Reconciler):
    """Add the pvc-protection finalizer to every live claim; lift it from
    terminating claims no running pod uses (pvc_protection_controller.go
    askInformer/askAPIServer collapsed to a store list)."""

    WATCH_KINDS = ("persistentvolumeclaims", "pods")

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind == "persistentvolumeclaims" and event != DELETED:
            self.queue.add((obj.namespace, obj.name))
        elif kind == "pods":
            # a pod going away may unblock a terminating claim it used
            for v in getattr(obj.spec, "volumes", ()) or ():
                claim = (v.get("persistentVolumeClaim") or {})
                if claim.get("claimName"):
                    self.queue.add((obj.namespace, claim["claimName"]))

    def _in_use(self, ns: str, name: str) -> bool:
        for pod in self.cluster.list("pods"):
            if pod.namespace != ns:
                continue
            if (pod.status.phase or "Pending") in ("Succeeded", "Failed"):
                continue  # terminated pods don't pin the claim
            for v in pod.spec.volumes or ():
                if (v.get("persistentVolumeClaim") or {}).get(
                        "claimName") == name:
                    return True
        return False

    def sync(self, key) -> None:
        ns, name = key
        pvc, rv = self.cluster.get_with_rv("persistentvolumeclaims", ns, name)
        if pvc is None:
            return
        meta = pvc.metadata
        if meta.deletion_timestamp is None:
            if PVC_PROTECTION_FINALIZER not in meta.finalizers:
                self.cluster.update(
                    "persistentvolumeclaims",
                    dataclasses.replace(
                        pvc, metadata=_with_finalizer(
                            meta, PVC_PROTECTION_FINALIZER)),
                    expect_rv=rv,
                )
        elif (PVC_PROTECTION_FINALIZER in meta.finalizers
              and not self._in_use(ns, name)):
            self.cluster.update(
                "persistentvolumeclaims",
                dataclasses.replace(
                    pvc, metadata=_without_finalizer(
                        meta, PVC_PROTECTION_FINALIZER)),
                expect_rv=rv,
            )


class PVProtectionController(Reconciler):
    """pv-protection finalizer: a terminating PV is released only once no
    claim is bound to it (pvprotection/pv_protection_controller.go)."""

    WATCH_KINDS = ("persistentvolumes",)

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind == "persistentvolumes" and event != DELETED:
            self.queue.add(obj.name)

    def sync(self, name: str) -> None:
        pv, rv = self.cluster.get_with_rv("persistentvolumes", "", name)
        if pv is None:
            return
        meta = pv.metadata
        bound = pv.phase == "Bound" or bool(pv.claim_ref)
        if meta.deletion_timestamp is None:
            if PV_PROTECTION_FINALIZER not in meta.finalizers:
                self.cluster.update(
                    "persistentvolumes",
                    dataclasses.replace(
                        pv, metadata=_with_finalizer(
                            meta, PV_PROTECTION_FINALIZER)),
                    expect_rv=rv,
                )
        elif PV_PROTECTION_FINALIZER in meta.finalizers and not bound:
            self.cluster.update(
                "persistentvolumes",
                dataclasses.replace(
                    pv, metadata=_without_finalizer(
                        meta, PV_PROTECTION_FINALIZER)),
                expect_rv=rv,
            )


class ClusterRoleAggregationController(Reconciler):
    """ClusterRoles with an aggregationRule get .rules = the union of every
    selected ClusterRole's rules (clusterroleaggregation_controller.go
    syncClusterRole; rule order follows selector then role-name order)."""

    WATCH_KINDS = ("clusterroles",)

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind != "clusterroles" or not isinstance(obj, dict):
            return
        if obj.get("aggregationRule"):
            self.queue.add(obj.get("name", ""))
        else:
            # a labeled part changed: re-sync every aggregating role
            for role in self.cluster.list("clusterroles"):
                if isinstance(role, dict) and role.get("aggregationRule"):
                    self.queue.add(role.get("name", ""))

    def sync(self, name: str) -> None:
        from kubernetes_tpu.api.labels import selector_from_label_selector

        role = self.cluster.get("clusterroles", "", name)
        if role is None or not role.get("aggregationRule"):
            return
        selectors = (role["aggregationRule"].get("clusterRoleSelectors")
                     or [])
        rules = []
        for ls in selectors:
            sel = selector_from_label_selector(ls)
            if sel is None:
                continue
            for part in sorted(
                    self.cluster.list("clusterroles"),
                    key=lambda r: r.get("name", "")):
                if not isinstance(part, dict) or part.get("name") == name:
                    continue
                if sel.matches(part.get("labels")
                               or (part.get("metadata") or {}).get(
                                   "labels") or {}):
                    rules.extend(part.get("rules") or [])
        if rules != (role.get("rules") or []):
            self.cluster.update("clusterroles", {**role, "rules": rules})


# reference boundaries (ttl_controller.go:102-109): overlapping bands give
# hysteresis so a cluster hovering at a threshold doesn't flap annotations
TTL_BOUNDARIES = (
    (0, 100, 0),
    (90, 500, 15),
    (450, 1000, 30),
    (900, 2000, 60),
    (1800, 10000, 300),
    (9000, 1 << 31, 600),
)


class NodeTTLController(Reconciler):
    """Annotate every node with the cluster-size-derived object-cache TTL
    (ttl_controller.go): kubelets use it to decide how long secrets/
    configmaps may be cached."""

    WATCH_KINDS = ("nodes",)

    def __init__(self, cluster: LocalCluster, informers=None):
        self._ttl = 0
        super().__init__(cluster, informers=informers)

    def _desired_ttl(self) -> int:
        n = len(self.cluster.list("nodes"))
        cur = self._ttl
        for lo, hi, ttl in TTL_BOUNDARIES:
            if ttl == cur:
                # stay in the current band while the size is inside its
                # (overlapping) hysteresis range
                if lo <= n <= hi:
                    return cur
        for lo, hi, ttl in TTL_BOUNDARIES:
            if n <= hi:
                return ttl
        return TTL_BOUNDARIES[-1][2]

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind == "nodes":
            if event in (ADDED, DELETED):
                # size change: every node may need the new annotation
                for node in self.cluster.list("nodes"):
                    self.queue.add(node.name)
            elif event == MODIFIED:
                self.queue.add(obj.name)

    def sync(self, name: str) -> None:
        node, rv = self.cluster.get_with_rv("nodes", "", name)
        if node is None:
            return
        self._ttl = self._desired_ttl()
        want = str(self._ttl)
        if node.metadata.annotations.get(TTL_ANNOTATION) == want:
            return
        ann = {**node.metadata.annotations, TTL_ANNOTATION: want}
        self.cluster.update(
            "nodes",
            dataclasses.replace(
                node, metadata=dataclasses.replace(
                    node.metadata, annotations=ann)),
            expect_rv=rv,
        )


def _b64url(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).rstrip(b"=").decode()


def compute_detached_jws(content: str, token_id: str,
                         token_secret: str) -> str:
    """Detached-payload JWS (RFC 7515 appendix F) over the cluster-info
    kubeconfig, HS256 keyed by the bootstrap token secret with the token
    id as kid — what `kubeadm join --discovery-token` verifies
    (bootstrapsigner.go computeDetachedSig)."""
    header = _b64url(json.dumps(
        {"alg": "HS256", "kid": token_id}, separators=(",", ":")
    ).encode())
    payload = _b64url(content.encode())
    sig = hmac.new(token_secret.encode(),
                   f"{header}.{payload}".encode(), hashlib.sha256).digest()
    return f"{header}..{_b64url(sig)}"


class BootstrapSigner(Reconciler):
    """Keep kube-public/cluster-info signed by every signing-enabled
    bootstrap token; stale signatures (revoked/expired tokens) are
    removed (bootstrapsigner.go signConfigMap)."""

    WATCH_KINDS = ("configmaps", "secrets")

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind == "configmaps" and isinstance(obj, dict):
            if (obj.get("namespace") == "kube-public"
                    and obj.get("name") == "cluster-info"):
                self.queue.add("cluster-info")
        elif kind == "secrets" and isinstance(obj, dict):
            if obj.get("type") == "bootstrap.kubernetes.io/token":
                self.queue.add("cluster-info")

    def _signing_tokens(self):
        for s in self.cluster.list("secrets"):
            if not isinstance(s, dict):
                continue
            if s.get("type") != "bootstrap.kubernetes.io/token":
                continue
            if s.get("namespace") != "kube-system":
                continue
            data = {**(s.get("data") or {}), **(s.get("stringData") or {})}
            if str(data.get("usage-bootstrap-signing",
                            "true")).lower() != "true":
                continue
            tid, tsec = data.get("token-id"), data.get("token-secret")
            if tid and tsec:
                yield tid, tsec

    def sync(self, _key) -> None:
        cm = self.cluster.get("configmaps", "kube-public", "cluster-info")
        if cm is None:
            return
        data = dict(cm.get("data") or {})
        content = data.get("kubeconfig", "")
        want = {
            f"jws-kubeconfig-{tid}": compute_detached_jws(content, tid, tsec)
            for tid, tsec in self._signing_tokens()
        }
        new_data = {k: v for k, v in data.items()
                    if not k.startswith("jws-kubeconfig-")}
        new_data.update(want)
        if new_data != data:
            self.cluster.update(
                "configmaps", {**cm, "data": new_data})


class CSRCleaner:
    """Garbage-collect settled CertificateSigningRequests (cleaner.go):
    approved/denied CSRs after 1h, pending after 24h."""

    APPROVED_EXPIRY = 3600.0
    DENIED_EXPIRY = 3600.0
    PENDING_EXPIRY = 24 * 3600.0

    def __init__(self, cluster: LocalCluster):
        self.cluster = cluster

    @staticmethod
    def _created(csr: dict) -> Optional[float]:
        from kubernetes_tpu.api.types import parse_time

        meta = csr.get("metadata") or {}
        return parse_time(meta.get("creationTimestamp")
                          or csr.get("creationTimestamp"))

    def tick(self, now: Optional[float] = None) -> int:
        if not self.cluster.has_kind("certificatesigningrequests"):
            return 0
        now = time.time() if now is None else now
        n = 0
        for csr in list(self.cluster.list("certificatesigningrequests")):
            if not isinstance(csr, dict):
                continue
            created = self._created(csr)
            if created is None:
                continue  # unknown age: never reap
            conds = {c.get("type")
                     for c in (csr.get("status") or {}).get("conditions")
                     or []}
            age = now - created
            settled = ("Approved" in conds and age > self.APPROVED_EXPIRY) \
                or ("Denied" in conds and age > self.DENIED_EXPIRY)
            pending = not conds and age > self.PENDING_EXPIRY
            if settled or pending:
                self.cluster.delete(
                    "certificatesigningrequests", "", csr.get("name", ""))
                n += 1
        return n


class ExpandController(Reconciler):
    """Volume expansion (expand_controller.go distilled): when a bound
    claim requests more than its volume provides, grow the volume to the
    requested size (the in-tree resize step; filesystem resize is the
    kubelet's NodeExpand, out of scope for a control-plane store)."""

    WATCH_KINDS = ("persistentvolumeclaims",)

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind == "persistentvolumeclaims" and event != DELETED:
            self.queue.add((obj.namespace, obj.name))

    def sync(self, key) -> None:
        ns, name = key
        pvc = self.cluster.get("persistentvolumeclaims", ns, name)
        if pvc is None or not pvc.volume_name or pvc.request is None:
            return
        pv, rv = self.cluster.get_with_rv(
            "persistentvolumes", "", pvc.volume_name)
        if pv is None or pv.capacity is None:
            return
        if pvc.request.value > pv.capacity.value:
            self.cluster.update(
                "persistentvolumes",
                dataclasses.replace(pv, capacity=pvc.request),
                expect_rv=rv,
            )


class RootCACertPublisher(Reconciler):
    """Publish the cluster root CA into a kube-root-ca.crt ConfigMap in
    every active namespace (rootcacertpublisher/publisher.go) — what pods
    mount to verify the apiserver.  The CA content comes from the
    kube-system/kube-root-ca Secret (minted by kubeadm init when serving
    over TLS) or the constructor."""

    WATCH_KINDS = ("namespaces", "configmaps")

    def __init__(self, cluster: LocalCluster, ca_cert: str = "",
                 informers=None):
        self._ca = ca_cert
        super().__init__(cluster, informers=informers)

    def _root_ca(self) -> str:
        if self._ca:
            return self._ca
        if self.cluster.has_kind("secrets"):
            s = self.cluster.get("secrets", "kube-system", "kube-root-ca")
            if isinstance(s, dict):
                return (s.get("data") or {}).get("ca.crt", "")
        return ""

    def _on_event(self, event: str, kind: str, obj) -> None:
        if kind == "namespaces":
            ns = obj.get("name") if isinstance(obj, dict) else obj.name
            self.queue.add(ns)
        elif (kind == "configmaps" and isinstance(obj, dict)
                and obj.get("name") == ROOT_CA_CONFIGMAP):
            self.queue.add(obj.get("namespace", "default"))

    def sync(self, ns: str) -> None:
        nso = self.cluster.get("namespaces", "", ns)
        if nso is None:
            return
        phase = ((nso.get("status") or {}).get("phase", "Active")
                 if isinstance(nso, dict) else "Active")
        if phase == "Terminating":
            return
        ca = self._root_ca()
        if not ca:
            return
        cm = self.cluster.get("configmaps", ns, ROOT_CA_CONFIGMAP)
        want = {
            "namespace": ns, "name": ROOT_CA_CONFIGMAP,
            "kind": "ConfigMap", "apiVersion": "v1",
            "data": {"ca.crt": ca},
        }
        if cm is None:
            try:
                self.cluster.create("configmaps", want)
            except ConflictError:
                pass
        elif (cm.get("data") or {}).get("ca.crt") != ca:
            self.cluster.update("configmaps", {**cm, "data": {"ca.crt": ca}})
