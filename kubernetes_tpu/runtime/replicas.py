"""SchedulerReplicaSet: N scheduler replicas over one process's state.

ISSUE 14 / ROADMAP item 3 — break the one-Python-loop ceiling.  The
replica set builds N `Scheduler` instances (threads) sharing:

  * ONE SchedulerCache/SnapshotEncoder (commits serialize under the
    cache lock; everything else overlaps),
  * ONE PriorityQueue, hash-sharded N ways (each replica pops only its
    stable shard; requeues return to the owner shard),
  * ONE SnapshotHub — THE resident device snapshot every replica
    dispatches against, refreshed atomically per dispatch and tagged
    with its generation,
  * ONE ConflictReconciler sequencing every commit: zero-conflict
    cycles admit on the generation fence; conflicted cycles run the
    fused admission scan, keep the sequenced winner per node, and
    requeue only the losers (DRF-tiebroken, quota-enforced),
  * ONE DecisionLedger (replica id + commit seq in every block), and
    the process flight recorder.

Replica 0 is the "primary": it owns the compiled engines (siblings
reuse the same jitted callables — no N-fold compile), the express lane
(a single cross-shard latency lane), and the default observability
installs (/debug/* primary payloads; /debug/replicas serves the
explicit aggregate).

Scope: replicas require batched_commit and demote gangs to plain pods;
extenders/framework plugins and device-mesh sharding are not combined
with replicas yet (one scale-out axis at a time — the mesh shards the
node tensor, replicas shard the queue).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, List, Optional

from kubernetes_tpu.runtime.cache import SchedulerCache
from kubernetes_tpu.runtime.queue import PriorityQueue
from kubernetes_tpu.runtime.reconciler import ConflictReconciler, SnapshotHub
from kubernetes_tpu.runtime.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.utils import metrics as m


class SchedulerReplicaSet:
    """N queue-sharded scheduler replicas with optimistic conflict
    reconciliation (see module docstring)."""

    def __init__(
        self,
        replicas: int = 2,
        cache: Optional[SchedulerCache] = None,
        queue: Optional[PriorityQueue] = None,
        binder: Optional[Callable] = None,
        config: Optional[SchedulerConfig] = None,
        recorder=None,
        ledger=None,
        victim_deleter=None,
        pdb_lister=None,
    ):
        n = max(1, int(replicas))
        config = config if config is not None else SchedulerConfig()
        if not config.batched_commit:
            raise ValueError(
                "SchedulerReplicaSet requires batched_commit (the "
                "reconciler admits winners as one sequenced delta)"
            )
        if config.shard_devices or config.mesh_shape:
            raise ValueError(
                "SchedulerReplicaSet does not combine with device-mesh "
                "sharding yet: the mesh shards the node tensor, replicas "
                "shard the queue — pick one scale-out axis per process"
            )
        self.n = n
        self.cache = cache if cache is not None else SchedulerCache()
        self.queue = (
            queue if queue is not None
            else PriorityQueue(capacity=config.queue_capacity, shards=n)
        )
        if hasattr(self.queue, "set_shards"):
            self.queue.set_shards(n)
        self.reconciler = ConflictReconciler()
        self.config = config
        # replica 0: the primary — owns engines, express lane, ledger,
        # and the default observability installs
        cfg0 = dataclasses.replace(config, replicas=n)
        r0 = Scheduler(
            cache=self.cache, queue=self.queue, binder=binder,
            config=cfg0, recorder=recorder, ledger=ledger,
            victim_deleter=victim_deleter, pdb_lister=pdb_lister,
            replica_id=0, replica_of=n, reconciler=self.reconciler,
        )
        self._assemble(r0)

    def _assemble(self, r0: Scheduler) -> None:
        """Attach the shared hub to the primary and build the sibling
        replicas around it (shared by __init__ and from_primary)."""
        n = self.n
        # THE shared resident snapshot: the hub wraps replica 0's device
        # cache (mesh-free by the constructor guard) and becomes every
        # replica's dispatch surface — including replica 0's
        self.hub = SnapshotHub(self.cache, r0._dev_snapshot)
        r0.attach_hub(self.hub)
        self.schedulers: List[Scheduler] = [r0]
        for i in range(1, n):
            cfg_i = dataclasses.replace(
                r0.config,
                express_lane=False,       # one express lane (replica 0)
                decision_ledger=False,    # share replica 0's ledger
                heartbeat_s=0.0,          # one heartbeat line, not N
            )
            self.schedulers.append(
                Scheduler(
                    cache=self.cache, queue=self.queue,
                    binder=r0.binder, config=cfg_i,
                    recorder=r0.recorder, ledger=r0.ledger,
                    victim_deleter=r0.victim_deleter,
                    pdb_lister=r0.pdb_lister,
                    replica_id=i, replica_of=n,
                    reconciler=self.reconciler, snapshot_hub=self.hub,
                    share_engines_with=r0,
                )
            )
        self._threads: List[threading.Thread] = []
        m.REPLICAS.set(float(n))

    @classmethod
    def from_primary(cls, primary: Scheduler,
                     replicas: int) -> "SchedulerReplicaSet":
        """Wrap an ALREADY-WIRED scheduler (cmd/base
        build_wired_scheduler: cluster events, informers, binder) as
        replica 0 of an N-way set.  Must run before the primary serves
        its first cycle — it retrofits the replica identity, the
        sequenced reconciler, and the shared hub onto it."""
        n = max(1, int(replicas))
        cfg = primary.config
        if not cfg.batched_commit:
            raise ValueError("replicas require batched_commit")
        if primary.framework is not None:
            raise ValueError(
                "replicas require the batched commit path; a framework "
                "forces per-pod commits that bypass the reconciler"
            )
        if cfg.shard_devices or cfg.mesh_shape:
            raise ValueError(
                "replicas do not combine with device-mesh sharding yet"
            )
        self = cls.__new__(cls)
        self.n = n
        self.cache = primary.cache
        self.queue = primary.queue
        if hasattr(self.queue, "set_shards"):
            self.queue.set_shards(n)
        self.reconciler = ConflictReconciler()
        self.config = cfg
        cfg.replicas = n
        primary._replica_of = n
        primary._reconciler = self.reconciler
        self._assemble(primary)
        return self

    # ------------------------------------------------------------ running

    @property
    def primary(self) -> Scheduler:
        return self.schedulers[0]

    def prewarm(self, **kw):
        """Pre-pay compiles once: replicas share replica 0's
        executables, so warming the primary warms the fleet — plus the
        reconciler's admission-kernel ladder (a first-conflict compile
        mid-traffic would read as a conflict-cost spike)."""
        out = self.primary.prewarm(**kw)
        self.reconciler.prewarm(
            self.config.batch_size, self.cache.encoder.dims.R
        )
        return out

    def start(self) -> None:
        """One daemon thread per replica running its scheduling loop.
        Restartable: a previous stop() only parked the loops (the
        shared queue stays open), so clearing the stop flags resumes."""
        if self._threads:
            return
        for s in self.schedulers:
            s._stop.clear()
        for s in self.schedulers:
            t = threading.Thread(
                target=s.run, name=f"scheduler-replica-{s._replica_id}",
                daemon=True,
            )
            self._threads.append(t)
            t.start()

    def stop(self, timeout_s: float = 10.0) -> None:
        """Park every replica loop WITHOUT closing the shared queue
        (Scheduler.stop would — and a closed queue cannot serve a later
        start(); bench sweeps warm, stop, and re-run).  Loops exit
        within their pop timeout; run() flushes in-flight work on the
        way out.  close() ends the set for good."""
        for s in self.schedulers:
            s._stop.set()
        for t in self._threads:
            t.join(timeout_s)
        self._threads = []

    def close(self) -> None:
        """Terminal stop: park the loops AND close the shared queue."""
        self.stop()
        self.queue.close()

    def run_until_drained(self, budget_s: float = 60.0,
                          poll_s: float = 0.01) -> int:
        """start() (if not already running), then wait until nothing
        schedulable remains (active/backoff work or an in-flight
        pipelined batch) or the budget expires.  Returns pods placed
        across all replicas during the wait.  Pods parked unschedulable
        do NOT keep the wait alive (no cluster events fire here)."""
        placed0 = self.placed_total
        self.start()
        deadline = time.monotonic() + budget_s
        while time.monotonic() < deadline:
            busy = self.queue.has_schedulable() or any(
                s.pipeline_pending for s in self.schedulers
            )
            if not busy:
                break
            time.sleep(poll_s)
        return self.placed_total - placed0

    # ---------------------------------------------------------- aggregate

    @property
    def placed_total(self) -> int:
        return sum(
            s._outcome_totals["placed"] for s in self.schedulers
        )

    @property
    def unschedulable_total(self) -> int:
        return sum(
            s._outcome_totals["unschedulable"] for s in self.schedulers
        )

    @property
    def conflicts_total(self) -> int:
        return self.reconciler.conflicts_total

    def assert_drained(self) -> bool:
        """Every replica's invariant checker confirms no popped pod is
        unresolved (the chaos-soak teardown gate).  True when clean."""
        ok = True
        for s in self.schedulers:
            if s.invariants is not None:
                ok = s.invariants.assert_drained() and ok
        return ok

    def invariant_violations_total(self) -> int:
        return sum(
            s.invariants.violations_total()
            for s in self.schedulers if s.invariants is not None
        )

    def summary(self) -> dict:
        """The /debug/replicas-shaped roll-up for bench artifacts."""
        return {
            "replicas": self.n,
            "placed": self.placed_total,
            "unschedulable": self.unschedulable_total,
            "conflicts": self.conflicts_total,
            "quota_vetoes": self.reconciler.quota_vetoes_total,
            "reconciler": self.reconciler.stats(),
            "hub_refreshes": self.hub.refreshes,
            "invariant_violations": self.invariant_violations_total(),
            "per_replica": {
                str(s._replica_id): {
                    "placed": s._outcome_totals["placed"],
                    "unschedulable": s._outcome_totals["unschedulable"],
                    "conflicts": s.conflicts_total,
                    "cycles_results": len(s.results),
                }
                for s in self.schedulers
            },
        }
